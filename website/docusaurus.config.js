// Project docs site (the analogue of the reference's docusaurus website,
// reference website/docusaurus.config.js). Build with `npm install && npm
// run build` inside website/; docs sources live in ../docs.
module.exports = {
  title: 'spark-ensemble-tpu',
  tagline: 'Ensemble learning compiled to XLA: Bagging, Boosting, GBM, Stacking on TPU',
  url: 'https://example.github.io',
  baseUrl: '/spark-ensemble-tpu/',
  organizationName: 'spark-ensemble-tpu',
  projectName: 'spark-ensemble-tpu',
  // docs are plain CommonMark (.md), not MDX — parse them as such
  markdown: { format: 'detect' },
  onBrokenLinks: 'warn',
  onBrokenMarkdownLinks: 'warn',
  themeConfig: {
    navbar: {
      title: 'spark-ensemble-tpu',
      items: [
        { to: 'docs/overview', label: 'Documentation', position: 'right' },
        // generated API reference (tools/gen_api_docs.py), the analogue
        // of the reference's scaladoc navbar item
        { to: 'docs/api/index', label: 'API', position: 'right' },
      ],
    },
    colorMode: {
      disableSwitch: true,
    },
  },
  presets: [
    [
      '@docusaurus/preset-classic',
      {
        docs: {
          path: '../docs',
          sidebarPath: require.resolve('./sidebars.js'),
        },
        theme: {
          customCss: require.resolve('./src/css/custom.css'),
        },
      },
    ],
  ],
};
