import React from 'react';
import Layout from '@theme/Layout';
import Link from '@docusaurus/Link';
import useBaseUrl from '@docusaurus/useBaseUrl';
import styles from './styles.module.css';

const FEATURES = [
  {
    title: 'Four meta-estimator families',
    body:
      'Bagging (SubBag), Boosting (SAMME, SAMME.R, Drucker R2), ' +
      'Gradient Boosting Machines with Newton updates and line-searched ' +
      'step sizes, and Stacking — classification and regression, over ' +
      'pluggable base learners (histogram trees, linear models, ' +
      'naive Bayes, MLPs, linear-leaf trees).',
  },
  {
    title: 'Compiled to XLA, shaped for the MXU',
    body:
      'Base-learner fits fuse across ensemble members and class dims ' +
      'into single histogram matmuls; rounds run as scan-chunked XLA ' +
      'programs; routing is gather-free. Precision tiers trade exact-f32 ' +
      'statistics for bf16 MXU passes, with a Pallas VMEM-resident ' +
      'kernel and a row-chunked stream tier for HBM-scale data.',
  },
  {
    title: 'Distributed by sharding, not by driver',
    body:
      'fit(..., mesh=...) shards rows and members over a ' +
      'jax.sharding.Mesh — psum-ed histograms, a gather-free exact ' +
      'distributed quantile, multi-host rendezvous, and hybrid ICI/DCN ' +
      'meshes. Communication per round is O(nodes x bins), never O(rows), ' +
      'and a compiled-HLO test locks that contract in.',
  },
  {
    title: 'The full framework, not a sketch',
    body:
      'Validated params, save/load persistence with format evolution, ' +
      'training checkpoint/resume, cross-validation and pipelines, ' +
      'evaluators, profiling hooks, a native C++ data-loader fast path, ' +
      'generated API docs, and a benchmark suite from toy to 2M-row ' +
      'configs.',
  },
];

const QUICKSTART = `import spark_ensemble_tpu as se

model = se.GBMClassifier(
    num_base_learners=100,
    updates="newton",
    optimized_weights=True,
).fit(X, y, mesh=se.parallel.data_member_mesh(8))

proba = model.predict_proba(X)
model.save("gbm.model")`;

export default function Home() {
  return (
    <Layout
      title="spark-ensemble-tpu"
      description="Ensemble learning compiled to XLA on TPU meshes">
      <header className={styles.hero}>
        <h1>spark-ensemble-tpu</h1>
        <p className={styles.tagline}>
          Ensemble learning compiled to XLA: Bagging, Boosting, GBM and
          Stacking meta-estimators, sharded across TPU meshes.
        </p>
        <div className={styles.buttons}>
          <Link
            className="button button--primary button--lg"
            to={useBaseUrl('docs/overview')}>
            Get started
          </Link>
          <Link
            className="button button--outline button--primary button--lg"
            to={useBaseUrl('docs/api/index')}>
            API reference
          </Link>
          <Link
            className="button button--outline button--primary button--lg"
            to={useBaseUrl('docs/distributed')}>
            Distributed training
          </Link>
        </div>
      </header>
      <main className={styles.main}>
        <section className={styles.features}>
          {FEATURES.map(({title, body}) => (
            <div key={title} className={styles.feature}>
              <h3>{title}</h3>
              <p>{body}</p>
            </div>
          ))}
        </section>
        <section className={styles.quickstart}>
          <h2>Quick start</h2>
          <pre>
            <code>{QUICKSTART}</code>
          </pre>
          <p>
            A re-design of{' '}
            <a href="https://github.com/pierrenodet/spark-ensemble">
              pierrenodet/spark-ensemble
            </a>{' '}
            (Scala/Spark) for JAX on TPU: same estimator semantics and
            defaults, same test bar, TPU-first internals.
          </p>
        </section>
      </main>
    </Layout>
  );
}
