import React from 'react';
import Layout from '@theme/Layout';
import Link from '@docusaurus/Link';

export default function Home() {
  return (
    <Layout title="spark-ensemble-tpu">
      <main style={{padding: '4rem', textAlign: 'center'}}>
        <h1>spark-ensemble-tpu</h1>
        <p>
          Ensemble learning compiled to XLA: Bagging, Boosting, GBM and
          Stacking meta-estimators over pluggable base learners, sharded
          across TPU meshes.
        </p>
        <Link className="button button--primary" to="docs/overview">
          Get started
        </Link>
      </main>
    </Layout>
  );
}
