module.exports = {
  docs: {
    Documentation: [
      'overview',
      'bagging',
      'boosting',
      'gbm',
      'stacking',
      'selection',
      'distributed',
      'example',
    ],
  },
};
