"""Estimator/Model base classes and the BaseLearner functional protocol.

This is the TPU build's **execution-backend seam** — the analogue of the
reference's ``HasBaseLearner.fitBaseLearner`` funnel
(`ensembleParams.scala:64-81`), through which every ensemble trains every
base model.  Where the reference rebinds DataFrame columns and calls
``baseLearner.fit(df, paramMap)`` (one Spark job per member), here a base
learner exposes a *pure functional* triple:

  - ``make_fit_ctx(X, num_classes)``: shared preprocessing computed once per
    ensemble fit (e.g. quantile binning for trees) — hoisted out of the
    member loop so members share it;
  - ``fit_from_ctx(ctx, y, w, feature_mask, key, axis_name=None) ->
    params``: a pure, jit-compiled, **vmappable** fit over fixed-shape
    arrays.  Row sampling arrives via ``w`` (Poisson/Bernoulli weights) and
    feature subspaces via ``feature_mask`` — the static-shape encoding of
    the reference's ``RDD.sample`` + ``slice`` (`HasSubBag.scala:73-84`);
    under ``shard_map`` row sharding the learner psums its sufficient
    statistics over ``axis_name`` (see ``ops.collective.preduce``);
  - ``predict_fn(params, X)`` (+ ``predict_raw_fn``/``predict_proba_fn`` for
    classifiers): pure predict, vmappable over a stacked member axis.

Ensembles vmap ``fit_from_ctx`` over ``(key, w, feature_mask)`` to train all
members in one XLA program — replacing the reference's driver thread-pool
``Future`` parallelism (`BaggingClassifier.scala:180-201`).

Weight support mirrors the reference's dispatch on ``HasWeightCol``
(`ensembleParams.scala:64-81`): all built-in learners support weights;
a learner may set ``supports_weight = False`` and ensembles will warn and
drop weights, like `StackingClassifier.scala:147-150`.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_ensemble_tpu.params import Param, Params, gt_eq, in_array
from spark_ensemble_tpu.utils.instrumentation import instrumented_fit


def as_f32(x) -> jax.Array:
    return jnp.asarray(x, dtype=jnp.float32)


@jax.tree_util.register_static
class Static:
    """Wrap a hashable value so it rides a pytree (e.g. a fit ctx passed as
    a jit argument) as STATIC treedef data rather than a traced leaf.  Used
    for ctx fields like ``num_classes`` that shape the traced program."""

    def __init__(self, value):
        self.value = value

    def __hash__(self):
        return hash(self.value)

    def __eq__(self, other):
        return isinstance(other, Static) and self.value == other.value

    def __repr__(self):
        return f"Static({self.value!r})"


def static_value(v):
    """Unwrap ``Static`` (pass plain values through, for back-compat)."""
    return v.value if isinstance(v, Static) else v


# Process-wide cache of jitted training programs, keyed by estimator/base
# config fingerprints (`Params.config_key`).  Estimator `fit` methods build
# their round-step closures over *config only* (all data flows through
# arguments) and register them here, so a second fit with the same config —
# another estimator instance, a CV fold, a bench run after warmup — reuses
# the compiled XLA program instead of retracing.  LRU-bounded: compiled
# programs hold device buffers for constants.
_PROGRAM_CACHE: "OrderedDict[tuple, Callable]" = OrderedDict()
_PROGRAM_CACHE_SIZE = 128
_PROGRAM_CACHE_LOCK = threading.Lock()

# Program-call observers (analysis/contracts.py): while any observer is
# registered, every program handed out by `cached_program` (and every
# `_predict_program` dispatch) is wrapped so each CALL reports its tag and
# abstract argument signature.  Counting distinct (tag, signature) pairs is
# how the contract checker pins compile budgets *independently of cache
# warmth*: a cache hit, a persistent-compile-cache hit, and a chaos-retry
# replay all re-call the same signature and count once.  The wrapper is
# never stored in the cache — a later unobserved caller gets the raw fn.
_PROGRAM_OBSERVERS: list = []

# Operator-plane program sink (telemetry/programz.py): unlike the scoped
# observers above, the sink is a process-lifetime hook the live program
# inventory installs once.  It receives every call with its measured call
# wall and (on cache misses) the program build wall, so /programz can
# attribute compile cost per program.  Held in a one-slot list so the
# call-time check is one global load; when no sink is installed AND no
# observer is registered, `_maybe_observed` hands back the raw fn and the
# hot path pays nothing.
_PROGRAM_SINK: list = [None]


def set_program_sink(sink) -> None:
    """Install (or, with ``None``, remove) the process-wide program-call
    sink: ``sink(tag, signature, fn, args, kwargs, call_s, build_s)``.
    One slot only — the operator plane owns it (telemetry/programz.py);
    programs fetched while neither a sink nor an observer was active are
    unwrapped and stay invisible, so enable the inventory before fitting.
    """
    _PROGRAM_SINK[0] = sink


def observe_program_calls(callback):
    """Context manager registering ``callback(tag, signature, fn, args,
    kwargs)`` for every cached-program / predict-program call in the
    enclosed scope.  ``fn`` is the underlying jitted callable (so the
    observer can abstractly re-trace it); observers must be thread-safe —
    stacking fits members concurrently."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        _PROGRAM_OBSERVERS.append(callback)
        try:
            yield
        finally:
            _PROGRAM_OBSERVERS.remove(callback)

    return _scope()


def _aval_signature(args, kwargs=None) -> tuple:
    """Abstract (shape, dtype) signature of a call's arguments — the same
    information jit keys its trace cache on, minus weak types."""
    sig = []
    leaves = list(jax.tree_util.tree_leaves(args))
    if kwargs:
        leaves += jax.tree_util.tree_leaves(kwargs)
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            sig.append((tuple(shape), str(getattr(leaf, "dtype", ""))))
        else:
            sig.append((type(leaf).__name__, repr(leaf)[:48]))
    return tuple(sig)


def _maybe_observed(
    key: tuple, fn: Callable, build_s: Optional[float] = None
) -> Callable:
    if not _PROGRAM_OBSERVERS and _PROGRAM_SINK[0] is None:
        return fn
    tag = key[0] if key and isinstance(key[0], str) else repr(key[:1])

    def observed(*args, **kwargs):
        sig = _aval_signature(args, kwargs)
        for cb in list(_PROGRAM_OBSERVERS):
            cb(tag, sig, fn, args, kwargs)
        sink = _PROGRAM_SINK[0]
        if sink is None:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        # call wall is dispatch wall (async backends return before the
        # program finishes); the inventory uses the FIRST call's wall as
        # the trace+compile attribution, which is the synchronous part
        sink(tag, sig, fn, args, kwargs, time.perf_counter() - t0, build_s)
        return out

    return observed


def cached_program(key: tuple, build: Callable[[], Callable]) -> Callable:
    """Return the jitted program for ``key``, building it on first use.

    ``build`` must return an already-jitted callable whose trace depends
    only on information captured in ``key`` (plus argument shapes/dtypes,
    which jax.jit handles itself).  Thread-safe: concurrent member fits
    (stacking's driver-Future analogue) may race on the cache.

    The default backend is appended to every key: some builders branch on
    ``jax.default_backend()`` at trace time (e.g. fused-vs-vmapped
    ``predict_forest``), so a process that switches backends between fits
    must not reuse a program traced for the other backend.
    """
    from spark_ensemble_tpu import autotune

    # persistent compilation cache (SE_TPU_COMPILE_CACHE): every program
    # build funnels through here, so enabling it once at the chokepoint
    # covers fit, predict, and serving warmup alike
    autotune.ensure_compilation_cache()
    # tuning-state fingerprint: trace-time tunables (hist tier, stream
    # chunk, fused-cell budgets) are latched into programs, so programs
    # traced under different tuned configs must never share a key
    key = key + (jax.default_backend(),) + autotune.fingerprint()
    with _PROGRAM_CACHE_LOCK:
        fn = _PROGRAM_CACHE.get(key)
        if fn is not None:
            _PROGRAM_CACHE.move_to_end(key)
            return _maybe_observed(key, fn)
    t_build = time.perf_counter()
    fn = build()
    build_s = time.perf_counter() - t_build
    with _PROGRAM_CACHE_LOCK:
        existing = _PROGRAM_CACHE.get(key)
        if existing is not None:
            # lost a build race: keep the winner, but refresh its LRU slot
            _PROGRAM_CACHE.move_to_end(key)
            return _maybe_observed(key, existing)
        _PROGRAM_CACHE[key] = fn
        while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_SIZE:
            _PROGRAM_CACHE.popitem(last=False)
    return _maybe_observed(key, fn, build_s=build_s)


# ---------------------------------------------------------------------------
# shared fit-context scope (docs/pipeline.md: tuning-layer binning reuse)
# ---------------------------------------------------------------------------
#
# `BaseLearner.make_fit_ctx` computes dataset preprocessing (quantile
# binning, feature stats) that depends only on (X, learner config,
# num_classes).  A tuning sweep fits the SAME X under many (param-map,
# fold) combos — with weight-mask folds every fit sees the identical full
# matrix, so recomputing the binning per fit is pure waste.  Inside a
# `shared_fit_context()` scope the family fits route through
# `make_shared_fit_ctx`, which memoizes per (X identity, shape/dtype,
# learner config, num_classes); outside a scope it degrades to a plain
# `make_fit_ctx` call, so per-fit behavior is unchanged.

_FIT_CTX_SCOPE = threading.local()


def shared_fit_context():
    """Context manager activating a fit-ctx memo for the enclosed fits
    (nests by stacking: the inner scope wins, the outer is restored)."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        prev = getattr(_FIT_CTX_SCOPE, "cache", None)
        _FIT_CTX_SCOPE.cache = {}
        try:
            yield
        finally:
            _FIT_CTX_SCOPE.cache = prev

    return _scope()


def make_shared_fit_ctx(learner, X, num_classes: Optional[int] = None):
    """``learner.make_fit_ctx(X, num_classes)`` memoized under the active
    :func:`shared_fit_context` scope (one binning pass per distinct
    dataset/config), or computed directly when no scope is active.

    Keyed by ``id(X)`` plus shape/dtype and the learner's ``config_key()``
    — the X reference is pinned in the cache entry, so a recycled ``id``
    cannot alias a different matrix within a scope."""
    cache = getattr(_FIT_CTX_SCOPE, "cache", None)
    if cache is None:
        return learner.make_fit_ctx(X, num_classes)
    shape = tuple(getattr(X, "shape", ())) or (len(X),)
    dtype = str(getattr(X, "dtype", ""))
    key = (id(X), shape, dtype, learner.config_key(), num_classes)
    hit = cache.get(key)
    if hit is None:
        hit = (X, learner.make_fit_ctx(X, num_classes))
        cache[key] = hit
    return hit[1]


# ---------------------------------------------------------------------------
# predict-path shape bucketing (docs/serving.md)
# ---------------------------------------------------------------------------
#
# Model predict programs are cached per instance by `_cached_jit`, but jit
# still traces one program per distinct X.shape — so a caller feeding ad-hoc
# batch sizes (a serving loop, CV folds of uneven length) silently recompiles
# on every novel row count.  Every predict op here is row-independent, so
# padding X up to a shared bucket and slicing the rows back out returns
# bit-identical values for the real rows while collapsing the shape space to
# O(log n) buckets.

PREDICT_BUCKETS_ENV = "SE_TPU_PREDICT_BUCKETS"

_BUCKET_POW2_EXACT = 512  # below this, plain next-power-of-two
_BUCKET_OCTAVE_STEPS = 8  # above: pow2/8 granularity, <= 12.5% padding


def predict_buckets_enabled() -> bool:
    """Bucketing is on by default; ``SE_TPU_PREDICT_BUCKETS=0`` restores the
    exact-shape behavior (one trace per novel row count)."""
    return os.environ.get(PREDICT_BUCKETS_ENV, "") not in ("0", "off")


def bucket_rows(n: int) -> int:
    """Padded row count for a predict batch of ``n`` rows: the next power
    of two for small batches, then steps of 1/8 of the power of two BELOW
    ``n`` — padding stays <= 12.5% of ``n`` with 8 buckets per octave.
    Both ladder knobs resolve through autotune (the module constants are
    the live defaults; measured winners override them per device)."""
    from spark_ensemble_tpu.autotune import resolve as _tuned

    n = int(n)
    if n <= 1:
        return 1
    pow2 = 1 << (n - 1).bit_length()
    if pow2 <= int(_tuned("predict_bucket_pow2_exact", _BUCKET_POW2_EXACT)):
        return pow2
    octave = int(
        _tuned("predict_bucket_octave_steps", _BUCKET_OCTAVE_STEPS)
    )
    step = max((pow2 // 2) // max(octave, 1), 1)
    return ((n + step - 1) // step) * step


def pad_rows_to_bucket(X) -> jax.Array:
    """``X`` as f32 with rows zero-padded up to ``bucket_rows(len(X))``.
    Host inputs (numpy/lists — the serving boundary) pad in numpy so the
    pad itself never compiles; device arrays pad with ``jnp.pad`` to stay
    on device (a one-op compile per novel shape, cached thereafter)."""
    n = np.shape(X)[0]
    nb = bucket_rows(n)
    if nb == n:
        return as_f32(X)
    if isinstance(X, jax.Array):
        pad = [(0, nb - n)] + [(0, 0)] * (X.ndim - 1)
        return jnp.pad(as_f32(X), pad)
    Xa = np.asarray(X, np.float32)
    buf = np.zeros((nb,) + Xa.shape[1:], np.float32)
    buf[:n] = Xa
    return jnp.asarray(buf)


def mesh_fit_kwargs(estimator, mesh) -> dict:
    """``{'mesh': mesh}`` when the estimator's fit supports distributed
    training, else ``{}`` — lets composite estimators (tuning, pipelines)
    forward a mesh without caring which stages are mesh-aware."""
    if mesh is None:
        return {}
    import inspect

    if "mesh" in inspect.signature(estimator.fit).parameters:
        return {"mesh": mesh}
    return {}


def resolve_weights(y: jax.Array, sample_weight) -> jax.Array:
    if sample_weight is None:
        return jnp.ones_like(y, dtype=jnp.float32)
    return as_f32(sample_weight)


def infer_num_classes(y, num_classes: Optional[int] = None) -> int:
    """Class count from labels, with the reference's label validation
    (`BoostingClassifier.scala:152-161` via ``extractInstances``): labels
    must be finite non-negative integers.  An explicit ``num_classes``
    overrides inference — required when a split (e.g. a validation fold)
    may not contain the top class — and labels must lie in [0, K)."""
    ya = np.asarray(y)
    if ya.size == 0:
        raise ValueError("cannot infer num_classes from empty labels")
    if not np.all(np.isfinite(ya)):
        raise ValueError("classification labels must be finite")
    if np.any(ya != np.round(ya)) or np.any(ya < 0):
        bad = ya[(ya != np.round(ya)) | (ya < 0)][0]
        raise ValueError(
            f"classification labels must be non-negative integers; got {bad!r}"
        )
    k = int(ya.max()) + 1
    if num_classes is not None:
        num_classes = int(num_classes)
        if num_classes < 2:
            raise ValueError(f"num_classes must be >= 2; got {num_classes}")
        if k > num_classes:
            raise ValueError(
                f"labels contain class {k - 1} but num_classes={num_classes}; "
                f"labels must lie in [0, num_classes)"
            )
        return num_classes
    return max(k, 2)


def member_leaves(base) -> int:
    """Chunk-budget heuristic for ``ops.tree.predict_chunked_rows``: leaves
    the base learner's FUSED predict routes through (1 for non-tree
    learners — chunking is then harmless headroom).  Capped at the fused
    path's depth limit: deeper trees take the unfused walk fallback, which
    never builds the [rows, members, leaves] one-hot being budgeted."""
    from spark_ensemble_tpu.ops.tree import _MATMUL_PREDICT_MAX_DEPTH

    depth = int(getattr(base, "max_depth", 0) or 0)
    return 2 ** min(depth, _MATMUL_PREDICT_MAX_DEPTH)


def resolved_scan_chunk(est, n_rows=None) -> int:
    """The round-loop chunk length for an iterative estimator: the
    hand-set ``scan_chunk`` param always wins; when the user left it at
    the default, a measured winner for this device/shape class
    (autotune: "scan_chunk") overrides the default literal."""
    chunk = max(int(est.scan_chunk), 1)
    if "scan_chunk" in est._param_values:
        return chunk
    from spark_ensemble_tpu.autotune import resolve as _tuned

    return max(int(_tuned("scan_chunk", chunk, n=n_rows)), 1)


class Model(Params):
    """A fitted model: estimator config + learned params pytree."""

    def __init__(self, params: Any = None, num_features: int = 0, **kwargs):
        super().__init__(**kwargs)
        self.params = params
        self.num_features = num_features

    def predict(self, X) -> jax.Array:
        raise NotImplementedError

    @property
    def feature_metadata(self):
        """Feature names for this model's input columns
        (`Utils.getFeaturesMetadata`, `Utils.scala:42-61`); anonymous
        ``f{i}`` names when the ``feature_names`` param was not set."""
        from spark_ensemble_tpu.utils.features import FeatureMetadata

        return FeatureMetadata.resolve(self.feature_names, self.num_features)

    @property
    def feature_importances_(self) -> np.ndarray:
        """Gain-based feature importances, normalized to sum 1 — Spark
        ``TreeEnsembleModel.featureImportances`` semantics (the reference's
        users read it off their Spark base models): each member tree's
        gains are normalized to sum 1 FIRST, members average with equal
        weight, and the average is renormalized.  Per-member normalization
        matters for boosting/GBM, where raw gains decay geometrically with
        the shrinking residuals — summing raw gains would reduce to the
        first round's view.  Members with no realized split are skipped;
        an all-leaf model returns zeros.  Raises AttributeError for base
        learners without an impurity-gain notion (linear, NB, MLP, dummy)."""
        gains = np.asarray(self._feature_gains_raw(), np.float64)
        gains = gains.reshape(-1, gains.shape[-1])
        sums = gains.sum(axis=1, keepdims=True)
        active = sums[:, 0] > 0
        if not active.any():
            return np.zeros(gains.shape[-1])
        imp = (gains[active] / sums[active]).mean(axis=0)
        return imp / imp.sum()

    def _feature_gains_raw(self):
        """Raw (unnormalized) gains: ensemble models reach through their
        stacked members via the base learner's ``feature_gains_fn``;
        standalone learner models ARE their learner."""
        if isinstance(self.params, dict) and "members" in self.params:
            members = self.params["members"]
            if members is None:  # zero kept rounds/members
                return np.zeros((self.num_features,))
            return self._base().feature_gains_fn(members, self.num_features)
        gains_fn = getattr(self, "feature_gains_fn", None)
        if gains_fn is None:
            # e.g. stacking models: heterogeneous members each carry their
            # own importances (query model.base_models[i] directly)
            raise AttributeError(
                f"{type(self).__name__} has no feature gains (gain-based "
                "importances exist for tree base learners only; for "
                "stacking, read them off the individual base_models)"
            )
        return gains_fn(self.params, self.num_features)

    def member(self, i: int) -> "Model":
        """Member ``i`` as a standalone fitted model — the analogue of the
        reference models' ``models`` array of base-learner models (e.g.
        `BaggingClassificationModel`'s constructor arg).  Member params are
        sliced out of the stacked pytree; subspace-trained members predict
        correctly without their mask (splits/coefs never use masked
        features).  GBMClassifier's [round, class-dim] grid overrides this
        with a two-index version."""
        if not (isinstance(self.params, dict) and "members" in self.params):
            raise AttributeError(
                f"{type(self).__name__} has no stacked members"
            )
        members = self.params["members"]
        if members is None:
            raise IndexError("model kept zero members")
        # explicit bounds check: jax CLAMPS out-of-range integer indices,
        # which would silently return the last member
        n_members = jax.tree_util.tree_leaves(members)[0].shape[0]
        if not 0 <= i < n_members:
            raise IndexError(f"member index {i} out of range [0, {n_members})")
        params_i = jax.tree_util.tree_map(lambda x: x[i], members)
        base = self._base()
        return base.model_from_params(
            params_i,
            self.num_features,
            getattr(self, "num_classes", None) if base.is_classifier else None,
        )

    def member_feature_names(self, i: int):
        """Feature names of member ``i``'s subspace — the reference
        re-indexes column metadata after ``slice()`` the same way."""
        masks = self.params.get("masks") if isinstance(self.params, dict) else None
        if masks is None:
            raise AttributeError(
                f"{type(self).__name__} has no per-member feature subspaces"
            )
        return self.feature_metadata.select(np.asarray(masks[i])).names

    def _cached_jit(self, name: str, builder):
        """Per-instance jit cache: model predict paths are built once and
        reused across calls (a fresh vmap/jit per call would retrace).
        Keyed by backend too — predict builders may branch on
        ``jax.default_backend()`` at trace time (see ``cached_program``)."""
        cache = getattr(self, "_jit_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_jit_cache", cache)
        key = (name, jax.default_backend())
        if key not in cache:
            cache[key] = jax.jit(builder)
        return cache[key]

    def _predict_program(
        self, name: str, builder, args: tuple, X, out_row_axis: int = 0
    ) -> jax.Array:
        """Run a cached predict program with X's rows padded to a shared
        shape bucket (``bucket_rows``): every model predict entry point
        routes through here so ad-hoc batch sizes hit one compiled program
        per bucket instead of retracing per novel ``X.shape[0]``.  All
        predict ops are row-independent, so the real rows' values are
        bit-identical to an unpadded call; ``out_row_axis`` names the output
        axis that carries rows (1 for ``[members, n]`` member stacks)."""
        fn = _maybe_observed((f"predict:{name}",), self._cached_jit(name, builder))
        n = np.shape(X)[0]
        if not predict_buckets_enabled() or bucket_rows(n) == n:
            return fn(*args, as_f32(X))
        out = fn(*args, pad_rows_to_bucket(X))
        index = (slice(None),) * out_row_axis + (slice(0, n),)
        return out[index]

    def pack(self):
        """This model compacted for serving: a :class:`~spark_ensemble_tpu.
        serving.export.PackedModel` — flat dict of stacked device arrays +
        static metadata, save/load-able, bit-identical predictions
        (docs/serving.md)."""
        from spark_ensemble_tpu.serving.export import pack

        return pack(self)

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_jit_cache", None)
        return state

    def save(self, path: str):
        from spark_ensemble_tpu.utils import persist

        persist.save(self, path)


class RegressionModel(Model):
    def score(self, X, y, sample_weight=None) -> float:
        """R^2 on (X, y) — the default metric of the RegressionEvaluator's
        Spark counterpart family; equivalent to
        ``RegressionEvaluator(metric="r2").evaluate(self, X, y)``."""
        from spark_ensemble_tpu.evaluation import RegressionEvaluator

        return RegressionEvaluator(metric="r2").evaluate(
            self, X, y, sample_weight
        )


class ClassificationModel(Model):
    """Adds raw scores / probabilities (reference: ProbabilisticClassifier)."""

    def __init__(self, num_classes: int = 2, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes

    def predict_raw(self, X) -> jax.Array:
        raise NotImplementedError

    def predict_proba(self, X) -> jax.Array:
        raise NotImplementedError

    def predict(self, X) -> jax.Array:
        return jnp.argmax(self.predict_proba(X), axis=-1).astype(jnp.float32)

    def score(self, X, y, sample_weight=None) -> float:
        """Accuracy on (X, y); equivalent to
        ``MulticlassClassificationEvaluator(metric="accuracy")``."""
        from spark_ensemble_tpu.evaluation import (
            MulticlassClassificationEvaluator,
        )

        return MulticlassClassificationEvaluator(metric="accuracy").evaluate(
            self, X, y, sample_weight
        )


class CheckpointableParams(Params):
    """Shared checkpoint/resume plumbing for the iterative estimators
    (GBM, Boosting) — one copy of the resume-identity exclusion list so a
    new observability param cannot silently invalidate checkpoints in one
    family but not another."""

    # params that do NOT affect training math: excluded from the resume
    # fingerprint so budget/cadence/observability changes keep checkpoints
    # resumable
    _RESUME_EXCLUDED = (
        "num_base_learners",
        "checkpoint_interval",
        "checkpoint_dir",
        "profile_dir",
        "telemetry_path",
        "feature_names",
        "scan_chunk",
        # robustness knobs change failure HANDLING, not round math: a
        # clean run produces identical rounds under any of them, so
        # checkpoints stay resumable across policy changes
        "on_nonfinite",
        "max_retries",
        "allow_nan",
    )

    def _resume_identity(self):
        p = self.params_to_json_dict()
        for k in self._RESUME_EXCLUDED:
            p.pop(k, None)
        return p

    # written into every checkpoint state so the members layout is explicit
    # (a base learner whose params pytree is a top-level Python list must
    # not be mistaken for the legacy per-round-list layout)
    MEMBERS_LAYOUT = "stacked"

    @staticmethod
    def _resume_chunks(st, weights_key: str = "weights"):
        """Checkpointed members/weights -> round-stacked chunk lists.
        Branches on the explicit ``members_layout`` marker; checkpoints
        without one (pre-marker) fall back to container-type sniffing for
        the legacy per-round-list layout."""
        st_members, st_weights = st["members"], st[weights_key]
        layout = st.get("members_layout")
        if layout is not None and layout != CheckpointableParams.MEMBERS_LAYOUT:
            # fail fast: decoding an unknown layout as legacy would garble
            # the resume far from the cause
            raise ValueError(
                f"unrecognized checkpoint members_layout {layout!r}; "
                f"expected {CheckpointableParams.MEMBERS_LAYOUT!r}"
            )
        legacy = layout is None and isinstance(st_members, list)
        if legacy:
            return (
                [
                    jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], m)
                    for m in st_members
                ],
                [jnp.asarray(x, dtype=jnp.float32)[None] for x in st_weights],
            )
        return (
            [jax.tree_util.tree_map(jnp.asarray, st_members)],
            [jnp.asarray(st_weights, dtype=jnp.float32)],
        )

    def _checkpointer(self, *shape_parts, telem=None):
        from spark_ensemble_tpu.utils.checkpoint import (
            TrainingCheckpointer,
            run_fingerprint,
        )

        return TrainingCheckpointer(
            self.checkpoint_dir,
            self.checkpoint_interval,
            fingerprint=run_fingerprint(
                type(self).__name__,
                self._resume_identity(),
                *[int(s) for s in shape_parts],
            ),
            retry_policy=self._retry_policy(),
            telem=telem,
        )

    # -- warm-start resume (serving/export.py fit_resume) ------------------
    #
    # A served PackedModel is a committed-round checkpoint in disguise: the
    # first k rounds of a stagewise fit ARE the state a checkpoint at round
    # k-1 would hold (PackedModel.take's absolute-round-index contract).
    # fit_resume synthesizes that state host-side and installs it here; the
    # next fit() consumes it exactly like a loaded checkpoint and re-enters
    # the round loop at round k.  A real on-disk checkpoint always wins —
    # a crashed refresh fit with checkpointing retries from its own later
    # state, never from the older packed prefix.

    def _set_warm_resume(self, last_round, st):
        self._warm_resume_state = (int(last_round), dict(st))
        # marks this estimator as a background refresh fit: the round loop
        # exposes chaos ``refresh_crash`` sites only on refresh fits, so a
        # foreground fit can never trip a refresh-targeted fault
        self._refresh_active = True

    def _take_warm_resume(self):
        state = getattr(self, "_warm_resume_state", None)
        self._warm_resume_state = None
        return state

    @property
    def _is_refresh_fit(self):
        return bool(getattr(self, "_refresh_active", False))


class Estimator(Params):
    """Base estimator: ``fit(X, y, sample_weight) -> Model``."""

    is_classifier = False
    supports_weight = True

    profile_dir = Param(
        None,
        doc="when set, every fit() captures a jax.profiler trace "
        "(TensorBoard-viewable) into this directory — the TPU analogue of "
        "the reference tests' spark.time wall-clock prints (SURVEY.md §5)",
    )
    telemetry_path = Param(
        None,
        doc="when set, every fit() appends its structured telemetry event "
        "stream (round timings, losses, per-phase costs, compile counts, "
        "device memory stats) to this JSONL file; the SE_TPU_TELEMETRY "
        "environment variable is the no-code-change equivalent "
        "(docs/telemetry.md).  Not part of any program-cache or "
        "checkpoint-resume identity — toggling it recompiles nothing",
    )
    feature_names = Param(
        None,
        doc="optional column names for X; carried onto fitted models and "
        "re-indexed through feature subspaces (`Utils.scala:42-61`)",
    )
    on_nonfinite = Param(
        "raise",
        in_array(["off", "raise", "skip_round", "halve_step", "stop_early"]),
        doc="numeric-guard policy when a round produces non-finite outputs "
        "(NaN/Inf member params, losses, or line-search step sizes): "
        "'raise' fails fast with NonFiniteError, 'skip_round' drops the "
        "poisoned round's contribution and keeps training, 'halve_step' "
        "re-runs the round with a halved step size until finite (families "
        "without a scalable step degrade to skip), 'stop_early' truncates "
        "the ensemble to the last good round, 'off' disables the check. "
        "Detection costs one fused jitted reduction per round chunk "
        "(docs/robustness.md); not part of any program-cache or "
        "checkpoint-resume identity",
    )
    max_retries = Param(
        2,
        gt_eq(0),
        doc="retries (with exponential backoff + jitter) of a round "
        "dispatch or checkpoint write that fails with a transient "
        "RuntimeError/OSError (XLA device errors, flaky filesystems); "
        "0 disables retry.  Each retry emits a 'retry' telemetry event "
        "(docs/robustness.md)",
    )
    allow_nan = Param(
        False,
        doc="skip the fail-fast NaN/Inf validation of X/y at fit() entry; "
        "by default non-finite inputs raise ValueError instead of "
        "silently producing a NaN model (docs/robustness.md)",
    )

    def fit(self, X, y, sample_weight=None) -> Model:
        raise NotImplementedError

    # -- robustness runtime hooks (docs/robustness.md) ---------------------

    def _retry_policy(self):
        """The estimator's retry policy, or ``None`` when retries are off
        (``retry_call`` treats None as the default policy, so callers gate
        on max_retries themselves via this returning a 0-retry policy)."""
        from spark_ensemble_tpu.robustness.retry import RetryPolicy

        return RetryPolicy(max_retries=int(self.max_retries))

    def _numeric_guard(self, telem=None):
        """A per-fit :class:`NumericGuard` bound to this estimator's
        ``on_nonfinite`` policy and the fit's telemetry stream."""
        from spark_ensemble_tpu.robustness.guards import NumericGuard

        return NumericGuard(
            self.on_nonfinite, family=type(self).__name__, telem=telem
        )

    def _validate_fit_inputs(self, X, y=None):
        from spark_ensemble_tpu.robustness.validate import validate_fit_inputs

        validate_fit_inputs(
            X, y, allow_nan=bool(self.allow_nan), family=type(self).__name__
        )


class BaseLearner(Estimator):
    """An estimator trainable through the functional member protocol."""

    def make_fit_ctx(self, X: jax.Array, num_classes: Optional[int] = None) -> Any:
        """Shared preprocessing (binning, feature stats); pure pytree out."""
        return as_f32(X)

    def fit_from_ctx(
        self,
        ctx: Any,
        y: jax.Array,
        w: jax.Array,
        feature_mask: Optional[jax.Array],
        key: jax.Array,
        axis_name: Optional[str] = None,
    ) -> Any:
        """Pure, jittable, vmappable member fit -> params pytree.

        ``axis_name`` names the mesh data axis when the fit runs inside
        ``shard_map`` with rows sharded across devices: the learner must
        ``psum`` its sufficient statistics over that axis so every shard
        computes the identical global model — the SPMD analogue of the
        reference's executors aggregating per-partition statistics with
        ``treeAggregate`` (`GBMClassifier.scala:344-355`).
        """
        raise NotImplementedError

    def fit_many_from_ctx(
        self,
        ctx: Any,
        ys: jax.Array,  # [n, M] per-member target columns
        ws: jax.Array,  # [n, M] per-member weights
        feature_masks: Optional[jax.Array],  # [M, d] | [d] | None
        keys: jax.Array,  # [M, 2] | [2]
        axis_name: Optional[str] = None,
    ) -> Any:
        """Fit M members in one program -> stacked params (leading M axis).

        Default: ``vmap`` of ``fit_from_ctx`` — one XLA program for all
        members, the baseline replacement for the reference's driver-side
        ``Future`` pools.  Learners whose member fits share large read-only
        operands override this to FUSE members into single kernels instead
        (trees fold the member axis into the histogram matmul's M dim,
        ``ops.tree.fit_forest``) — vmap alone re-streams the shared operand
        per member and leaves the op bandwidth-bound.
        """
        M = ys.shape[1]
        mask_axis = 0
        if feature_masks is None:
            mask_axis = None
        elif feature_masks.ndim == 1:
            feature_masks = jnp.broadcast_to(
                feature_masks[None, :], (M,) + feature_masks.shape
            )
        if keys.ndim == 1:
            keys = jnp.broadcast_to(keys[None, :], (M,) + keys.shape)
        return jax.vmap(
            lambda y, w, m, k: self.fit_from_ctx(
                ctx, y, w, m, k, axis_name=axis_name
            ),
            in_axes=(1, 1, mask_axis, 0),
        )(ys, ws, feature_masks, keys)

    def fit_and_direction(
        self, ctx, y, w, feature_mask, key, X, axis_name=None
    ):
        """Member fit PLUS the fitted member's predictions on the SAME rows
        (the GBM round's ``direction``) -> (params, pred[n]).

        Default: fit then predict.  Learners whose fit already routes every
        row to its output region override this to REUSE that routing
        instead of re-walking the model (trees return the leaf ids their
        fit computed — the per-round predict re-route disappears)."""
        params = self.fit_from_ctx(
            ctx, y, w, feature_mask, key, axis_name=axis_name
        )
        return params, self.predict_fn(params, X)

    def fit_and_proba(
        self, ctx, y, w, feature_mask, key, X, axis_name=None
    ):
        """Classifier member fit PLUS class probabilities on the SAME rows
        (SAMME.R's per-round input) -> (params, proba[n, k]).  Default:
        fit then predict_proba; routing-reuse learners override."""
        params = self.fit_from_ctx(
            ctx, y, w, feature_mask, key, axis_name=axis_name
        )
        return params, self.predict_proba_fn(params, X)

    def fit_many_and_directions(
        self, ctx, ys, ws, feature_masks, keys, X, axis_name=None
    ):
        """Fused-member analogue of ``fit_and_direction`` ->
        (stacked params, preds[n, M])."""
        params = self.fit_many_from_ctx(
            ctx, ys, ws, feature_masks, keys, axis_name=axis_name
        )
        preds = jax.vmap(lambda p: self.predict_fn(p, X))(params)
        return params, preds.T

    def ctx_gather_rows(self, ctx: Any, idx: jax.Array) -> Any:
        """Gather the fit ctx's row-indexed leaves into a compacted buffer
        (gradient-based row sampling, models/gbm.py): ``idx[m]`` selects the
        surviving rows, so downstream histogram/leaf kernels genuinely
        process ``m`` rows per dispatch instead of masking ``n``.  The
        default ctx is the feature matrix itself; learners whose ctx mixes
        row-indexed and replicated leaves override (trees gather the binned
        matrix and keep thresholds whole)."""
        return jax.tree_util.tree_map(lambda leaf: leaf[idx], ctx)

    def fit_gathered_and_direction(
        self, ctx_s, y_s, w_s, feature_mask, key, X, axis_name=None
    ):
        """Member fit over a row-compacted ctx (``ctx_gather_rows``) PLUS
        the fitted member's predictions on the FULL rows -> (params,
        pred[n]).  The fit sees only the gathered survivors; the direction
        re-routes every original row through the fitted model (for trees
        the raw-threshold route is bit-identical to the binned route —
        ``test_binned_and_raw_predict_agree``)."""
        params = self.fit_from_ctx(
            ctx_s, y_s, w_s, feature_mask, key, axis_name=axis_name
        )
        return params, self.predict_fn(params, X)

    def fit_gathered_many_and_directions(
        self, ctx_s, ys_s, ws_s, feature_masks, keys, X, axis_name=None
    ):
        """Fused-member analogue of ``fit_gathered_and_direction`` ->
        (stacked params, preds[n, M]); the full-row re-route uses the
        learner's fused multi-member predict."""
        params = self.fit_many_from_ctx(
            ctx_s, ys_s, ws_s, feature_masks, keys, axis_name=axis_name
        )
        return params, self.predict_many_fn(params, X).T

    def ctx_specs(self, ctx: Any, data_axis: str):
        """``shard_map`` PartitionSpecs for the fit ctx under row sharding:
        row-indexed leaves sharded over ``data_axis``, the rest replicated.
        The default ctx is the feature matrix itself, sharded on axis 0."""
        from jax.sharding import PartitionSpec as P

        return jax.tree_util.tree_map(
            lambda leaf: P(data_axis, *([None] * (jnp.ndim(leaf) - 1))), ctx
        )

    def predict_fn(self, params: Any, X: jax.Array) -> jax.Array:
        """Regression value [n] (regressors) or class index f32[n] (classifiers)."""
        raise NotImplementedError

    def predict_many_fn(self, params: Any, X: jax.Array) -> jax.Array:
        """Stacked-member predict -> [M, n].  Default: vmap of
        ``predict_fn``; learners with a fused multi-member kernel (trees:
        one column-select matmul for all members, ``ops.tree.predict_forest``)
        override this — ensemble model predict paths route through it."""
        return jax.vmap(lambda p: self.predict_fn(p, X))(params)

    def predict_proba_many_fn(self, params: Any, X: jax.Array) -> jax.Array:
        """Stacked-member probabilities -> [M, n, k]; default vmap."""
        return jax.vmap(lambda p: self.predict_proba_fn(p, X))(params)

    def predict_raw_fn(self, params: Any, X: jax.Array) -> jax.Array:
        raise NotImplementedError

    def predict_proba_fn(self, params: Any, X: jax.Array) -> jax.Array:
        raise NotImplementedError

    def feature_gains_fn(self, params: Any, d: int) -> jax.Array:
        """Per-feature split-gain sums ``f32[..., d]`` (stacked members keep
        their leading axes).  Only learners with an impurity-gain notion
        (trees) implement this; it feeds ``Model.feature_importances_``."""
        raise AttributeError(
            f"{type(self).__name__} has no feature gains (gain-based "
            "importances exist for tree base learners only)"
        )

    def model_from_params(
        self, params: Any, num_features: int, num_classes: Optional[int] = None
    ) -> Model:
        raise NotImplementedError

    # ------------------------------------------------------------------
    # standalone sklearn-style fit built on the functional protocol
    # ------------------------------------------------------------------
    @instrumented_fit
    def fit(self, X, y, sample_weight=None, num_classes=None, mesh=None) -> Model:
        """Fit this learner standalone; with ``mesh`` the fit runs as one
        shard_map-ed SPMD program with rows sharded over "data" — every
        built-in learner already psums its sufficient statistics over
        ``axis_name`` (the protocol contract, see ``fit_from_ctx``), so the
        SAME functional fit that ensembles distribute works distributed
        here, zero per-learner code.  (Padding rows carry weight 0.)"""
        X = as_f32(X)
        y = as_f32(y)
        self._validate_fit_inputs(X, y)
        w = resolve_weights(y, sample_weight)
        num_classes = (
            infer_num_classes(y, num_classes) if self.is_classifier else None
        )
        ctx = make_shared_fit_ctx(self, X, num_classes)
        key = jax.random.PRNGKey(getattr(self, "seed", 0) or 0)
        if mesh is None:
            params = self.fit_from_ctx(ctx, y, w, None, key)
            return self.model_from_params(params, X.shape[1], num_classes)

        from jax.sharding import PartitionSpec as P

        from spark_ensemble_tpu.compat import shard_map

        from spark_ensemble_tpu.parallel.mesh import (
            mesh_row_spec,
            mesh_sizes,
            pad_rows,
            shard_ctx_rows,
        )

        data_size, _ = mesh_sizes(mesh)
        ax = mesh_row_spec(mesh)
        n_pad = y.shape[0] + (-y.shape[0]) % data_size
        ctx, ctx_specs = shard_ctx_rows(mesh, self, ctx, n_pad)
        row = jax.sharding.NamedSharding(mesh, P(ax))
        y = jax.device_put(pad_rows(y, n_pad), row)
        w = jax.device_put(pad_rows(w, n_pad), row)
        # snapshot: the cached program must not observe later set_params
        # mutations of the caller's instance (same discipline as ensembles)
        base = self.copy()
        fit_sharded = cached_program(
            ("base_fit_sharded", base.config_key(), num_classes, mesh),
            lambda: jax.jit(
                shard_map(
                    lambda ctx, y, w, key: base.fit_from_ctx(
                        ctx, y, w, None, key, axis_name=ax
                    ),
                    mesh=mesh,
                    in_specs=(ctx_specs, P(ax), P(ax), P()),
                    out_specs=P(),
                    check_vma=False,
                )
            ),
        )
        params = fit_sharded(ctx, y, w, key)
        return self.model_from_params(params, X.shape[1], num_classes)
