"""Gradient Boosting Machines (regressor + multiclass classifier), TPU-native.

Re-designs the reference's GBM loop (`GBMRegressor.scala:237-476`,
`GBMClassifier.scala:219-496`) for XLA:

- The per-round step — subbag sampling, pseudo-residual computation
  (gradient or Newton with the reference's ``max(h, 1e-2)`` hessian floor and
  ``0.5 * h / sum_h * w`` weight scaling), histogram-tree fit, line search,
  prediction update — compiles to ONE jitted XLA program, traced once and
  reused every round.  The reference instead launches several Spark jobs per
  round (sample, residual map, base fit, N Brent evaluations, update).
- Row sampling keeps static shapes: Poisson/Bernoulli *bag weights* instead
  of materialized subsets (same estimator statistics as ``RDD.sample``);
  feature subspaces are boolean masks zeroing split gains instead of sliced
  vectors (`HasSubBag.scala:73-84`).
- Line search runs on-device: Brent over [0, 100] for dim=1
  (reference: commons-math ``BrentOptimizer``, `GBMRegressor.scala:311,413`)
  and projected Newton over the box [0, inf)^K for multiclass (reference:
  breeze ``LBFGSB``, `GBMClassifier.scala:290-292,427`).  Matching the
  reference's aggregator (`GBMLoss.scala:50-74`), the objective weights rows
  by their bag multiplicity only.
- The K per-class trees the reference fits in parallel driver Futures
  (`GBMClassifier.scala:377-411`) are a single ``vmap`` over the class dim.
- Validation early-stop (patience ``num_rounds``, tolerance ``validation_tol``
  with the reference's ``max(err, 0.01)`` scaling) runs in the host round
  loop; the final model trims to ``i - v`` members like ``take(i - v)``
  (`GBMRegressor.scala:474`).
- Huber's adaptive delta re-estimates the alpha-quantile of absolute
  residuals each round with the exact sort-based quantile kernel (reference:
  distributed ``approxQuantile``, `GBMRegressor.scala:342-353`).

The round loop itself stays on the host (data-dependent stopping), carrying
predictions as device arrays — the analogue of the reference's RDD lineage,
minus the need for ``PeriodicRDDCheckpointer``.

Distributed mapping (``fit(..., mesh=...)`` — the SPMD replacement for the
reference's entire distribution story, `GBMClassifier.scala:325-483`):

| reference (Spark)                        | here (XLA)                        |
|------------------------------------------|-----------------------------------|
| RDD rows on executors                    | rows sharded over mesh "data"     |
| treeReduce/treeAggregate (hessian sums,  | lax.psum over "data"              |
|   split histograms via base-learner jobs)|                                   |
| driver Futures over K class dims         | class-dim block sharded over      |
|                                          |   "member", all_gather to rejoin  |
| Broadcast(line-search coefficients)      | replicated operands (SPMD)        |
| breeze LBFGS-B on the driver, each       | projected Newton inside the       |
|   evaluation a distributed pass          |   shard_map; psum per evaluation  |
"""

from __future__ import annotations

import logging
import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_ensemble_tpu.compat import shard_map

from spark_ensemble_tpu import execution as _execution
from spark_ensemble_tpu.models.base import (
    BaseLearner,
    CheckpointableParams,
    ClassificationModel,
    Estimator,
    RegressionModel,
    as_f32,
    cached_program,
    infer_num_classes,
    make_shared_fit_ctx,
    member_leaves,
    mesh_fit_kwargs,
    resolve_weights,
    resolved_scan_chunk,
)
from spark_ensemble_tpu.ops.tree import predict_chunked_rows
from spark_ensemble_tpu.models.dummy import DummyClassifier, DummyRegressor
from spark_ensemble_tpu.models.tree import DecisionTreeRegressor
from spark_ensemble_tpu.ops import losses as losses_mod
from spark_ensemble_tpu.ops.linesearch import brent_minimize, projected_newton_box
from spark_ensemble_tpu.parallel.mesh import (
    mesh_row_spec as _mesh_row_spec,
    mesh_sizes as _mesh_sizes,
    pad_rows as _pad_rows,
    setup_row_sharding,
    shard_fit_rows,
    shard_validation_rows,
)
from spark_ensemble_tpu.params import Param, gt, gt_eq, in_array, in_range
from spark_ensemble_tpu.telemetry.events import FitTelemetry
from spark_ensemble_tpu.telemetry.quality import drift_reference_from_ctx
from spark_ensemble_tpu.utils.instrumentation import (
    Instrumentation,
    instrumented_fit,
)
from spark_ensemble_tpu.utils.quantile import weighted_quantile
from spark_ensemble_tpu.utils.random import (
    bootstrap_weights,
    subspace_mask,
)

logger = logging.getLogger(__name__)

def slice_pytree(tree: Any, n: int):
    return jax.tree_util.tree_map(lambda x: x[:n], tree)


def concat_pytrees(chunks: List[Any]):
    """Concatenate round-stacked pytrees along the leading (round) axis."""
    if len(chunks) == 1:
        return chunks[0]
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *chunks
    )


def _round_cost(base, n: int, d: int, members: int, sample_plan=None):
    """Static per-round cost model for telemetry round events (ops/tree.py
    ``round_cost_est``): resolved histogram tier, packed-lane width, HBM
    bytes and MXU flops per round.  ``members`` is the number of trees a
    round fits (1 for the regressor, the class dim for the classifier).
    With a compacted-sampling plan the histogram costs are modeled at the
    bucket size and the ledger row carries the predicted HBM saving
    (``sampled_rows``/``sample_bucket``/``hbm_saved_est``).  None when the
    base learner is not a histogram tree."""
    try:
        from spark_ensemble_tpu.ops.tree import round_cost_est

        out = round_cost_est(
            n=int(n), d=int(d), k=1, M=int(members),
            max_depth=int(base.max_depth), max_bins=int(base.max_bins),
            hist=str(getattr(base, "hist", "auto")),
            hist_precision=str(getattr(base, "hist_precision", "highest")),
            sampled_rows=(
                int(sample_plan["bucket"]) if sample_plan else None
            ),
        )
        if out is not None and sample_plan is not None:
            out["sampled_rows"] = int(sample_plan["sampled_rows"])
            out["sample_bucket"] = int(sample_plan["bucket"])
        return out
    except (AttributeError, TypeError, ValueError):
        return None


class _GBMParams(CheckpointableParams, Estimator):
    """Shared GBM params (reference `GBMParams.scala:29-137` defaults)."""

    base_learner = Param(
        None, is_estimator=True,
        doc="base learner fitted each round on the pseudo-residuals "
        "(snapshot-copied per round); defaults to a depth-5 histogram "
        "DecisionTreeRegressor",
    )
    num_base_learners = Param(
        10, gt_eq(1), doc="boosting rounds (reference maxIter analogue)"
    )
    learning_rate = Param(
        1.0, gt(0.0), doc="shrinkage applied to each round's step"
    )
    optimized_weights = Param(
        True,
        doc="line-search the per-round step size(s): Brent (closed-form "
        "for squared loss) for regression, projected-Newton box search "
        "over the class dims for classification; False uses 1.0",
    )
    updates = Param(
        "gradient", in_array(["gradient", "newton"]),
        doc="pseudo-residual rule: 'gradient' fits -g, 'newton' fits "
        "-g/h with the hessian floor/scaling of GBMClassifier.scala",
    )
    subsample_ratio = Param(
        1.0, in_range(0.0, 1.0, lower_inclusive=False),
        doc="per-round row subsample (stochastic gradient boosting); "
        "enters as Poisson/Bernoulli weights, not row subsets",
    )
    sample_method = Param(
        "uniform", in_array(["uniform", "goss"]),
        doc="'goss' = gradient-based one-side sampling (fast-sampling GTB "
        "family, arXiv:1911.08820; extension — the reference has only "
        "uniform subbagging): each round keeps the top_rate fraction of "
        "rows by gradient magnitude plus an amplified other_rate sample "
        "of the rest; composes with subsample_ratio as a weight product",
    )
    top_rate = Param(
        0.2, in_range(0.0, 1.0),
        doc="GOSS: fraction of rows kept deterministically by gradient "
        "magnitude",
    )
    other_rate = Param(
        0.1, in_range(0.0, 1.0, lower_inclusive=False),
        doc="GOSS: fraction of the FULL dataset sampled from the "
        "small-gradient rest (kept rows amplified by the reciprocal "
        "keep-rate, so the rest's gradient mass is unbiased)",
    )
    sampling = Param(
        "none", in_array(["none", "goss", "mvs"]),
        doc="gradient-based row sampling with TRUE row compaction "
        "(docs/sampling.md): per round the rows are ranked on device by "
        "gradient magnitude ('goss', arXiv:1911.08820) or by minimal-"
        "variance sampling probability ('mvs'), and the survivors are "
        "GATHERED into a power-of-two-bucketed compacted buffer — the "
        "histogram tiers genuinely process fewer rows per dispatch, "
        "unlike sample_method='goss' which only zero-weights them.  "
        "Survivor weights carry the (1-a)/b amplification so split gains "
        "stay unbiased.  'goss' keeps ceil(top_rate*n) rows by |grad| "
        "plus exactly ceil(other_rate*n) uniform draws from the rest; "
        "'mvs' keeps an expected (top_rate+other_rate)*n rows with "
        "probability min(1, sqrt(g^2+mvs_lambda)/mu).  Composes with "
        "subsample_ratio and weight-mask CV folds (zero-weight rows "
        "never survive); single-device fits only",
    )
    mvs_lambda = Param(
        0.1, gt_eq(0.0),
        doc="MVS regularizer: sampling scores are sqrt(grad^2 + lambda) — "
        "larger values flatten the distribution toward uniform sampling",
    )
    leaf_model = Param(
        "constant", in_array(["constant", "linear"]),
        doc="'linear' swaps a plain DecisionTreeRegressor base learner "
        "for models/linear_tree.py's ridge-leaf tree (arXiv:1802.05640): "
        "piecewise-linear leaves express smooth trends in fewer boosting "
        "rounds; 'constant' is the pre-existing behavior, bit-identical",
    )
    replacement = Param(
        False, doc="subsample with replacement (Poisson weights)"
    )
    subspace_ratio = Param(
        1.0, in_range(0.0, 1.0, lower_inclusive=False),
        doc="per-round feature-subspace ratio (random subspaces mask "
        "split validity; predictions re-index through the mask)",
    )
    max_iter = Param(
        100, gt_eq(1), doc="line-search iteration cap per round"
    )
    tol = Param(1e-6, gt_eq(0.0), doc="line-search convergence tolerance")
    num_rounds = Param(
        1, gt_eq(1),
        doc="early-stop patience: stop after this many consecutive "
        "rounds without validation improvement > validation_tol",
    )
    validation_tol = Param(
        0.01, gt_eq(0.0),
        doc="minimum relative validation-loss improvement that resets "
        "the early-stop patience counter",
    )
    seed = Param(0, doc="PRNG seed for sampling plans")
    aggregation_depth = Param(2, gt_eq(1), doc="API parity; reductions are psum")
    scan_chunk = Param(
        16,
        gt_eq(1),
        doc="rounds fused into one lax.scan-ed XLA program per dispatch "
        "(single-chip, and under a mesh when no validation split needs "
        "per-round evaluation); amortizes dispatch overhead without "
        "changing round math (validation early-stop still applies per "
        "round, overshooting at most one chunk of compute)",
    )
    checkpoint_interval = Param(
        10, gt_eq(1), doc="rounds between training-state checkpoints"
    )
    checkpoint_dir = Param(
        None,
        doc="when set, training state (round, members, predictions, patience) "
        "is checkpointed every checkpoint_interval rounds and fit() resumes "
        "from the latest checkpoint — the TPU upgrade of the reference's "
        "lineage-only PeriodicRDDCheckpointer (SURVEY.md §5)",
    )

    def _base(self) -> BaseLearner:
        base = self.base_learner or DecisionTreeRegressor()
        if str(self.leaf_model).lower() == "linear":
            from spark_ensemble_tpu.models.linear_tree import (
                LinearTreeRegressor,
            )

            # swap happens HERE (not just in fit) so the fitted model's
            # predict paths — which rebuild the base from get_params() —
            # route the stored ridge-leaf params through the same learner
            if type(base) is DecisionTreeRegressor:
                base = LinearTreeRegressor(**base.get_params())
            elif not isinstance(base, LinearTreeRegressor):
                raise ValueError(
                    "leaf_model='linear' needs a DecisionTreeRegressor "
                    f"base learner (got {type(base).__name__}); pass a "
                    "LinearTreeRegressor base directly to customize its "
                    "leaf params"
                )
        return base

    @property
    def validation_history_(self) -> np.ndarray:
        """Per-round validation losses from a fit with a validation split
        (`GBMRegressor.scala:444-465` evaluates them; here they come back
        from inside the chunked program and are stored on the model).
        Includes every evaluated round — also the trailing patience rounds
        the final model trims."""
        params = getattr(self, "params", None)
        vh = params.get("val_hist") if isinstance(params, dict) else None
        if vh is None:
            raise AttributeError(
                "validation_history_ exists only on models fit with a "
                "validation split (validation_indicator=...)"
            )
        return np.asarray(vh)

    def _sampling_plan(self, n: int, d: int):
        """Per-member (bag-weight key, feature mask); member seeds mirror the
        reference's ``seed + i`` discipline (`GBMRegressor.scala:282-284`).

        One jitted program for the WHOLE plan: the eager per-member loop it
        replaces dispatched ~8 small ops per member — ~800 host->device
        round-trips before round 0 of a 100-round fit, measured at ~6.5 ms
        per round of host time on CPU and multi-ms per dispatch through the
        TPU tunnel.  Draws are bit-identical to the loop (same fold_in
        tree, ``subspace_mask`` vmapped)."""
        m = int(self.num_base_learners)
        ratio = float(self.subspace_ratio)

        def build():
            def per_member(root, i):
                k = jax.random.fold_in(root, i)
                return (
                    jax.random.fold_in(k, 2),
                    subspace_mask(jax.random.fold_in(k, 1), d, ratio),
                )

            return jax.jit(
                lambda root: jax.vmap(lambda i: per_member(root, i))(
                    jnp.arange(m)
                )
            )

        plan = cached_program(("gbm_sampling_plan", m, d, ratio), build)
        return plan(jax.random.PRNGKey(self.seed))

    @staticmethod
    def _patience_step(best: float, err: float, v: int, validation_tol: float):
        """Reference early-stop bookkeeping (`GBMRegressor.scala:457-465`)."""
        if best - err < validation_tol * max(err, 0.01):
            return best, v + 1
        return err, 0

    def _make_bag_many_fn(self, n: int, n_pad: int):
        """Vmapped bag draws for a chunk of rounds: [c, 2] keys -> [c, n_pad]
        weights, drawn over the ORIGINAL n rows (bit-identical to the
        single-device draw) then zero-padded to the sharded length.  One
        copy shared by both GBM flavors so their bagging draws can never
        silently diverge."""
        repl, sub_ratio = bool(self.replacement), float(self.subsample_ratio)
        return cached_program(
            ("gbm_bag_many", n, n_pad, repl, sub_ratio),
            lambda: jax.jit(
                jax.vmap(
                    lambda key: _pad_rows(
                        bootstrap_weights(key, n, repl, sub_ratio), n_pad
                    )
                )
            ),
        )

    def _resolved_sampling(self, n: int):
        """Host-side row-sampling plan, or None when ``sampling='none'``.

        GOSS rates resolve through autotune ONLY when not hand-set (the
        ``resolved_scan_chunk`` discipline — with autotune off they
        resolve to the configured values, so fits stay bit-identical).
        The plan's device scalars (``samp``) carry every rate-dependent
        quantity as traced operands; only the pow2 ``bucket`` is static."""
        method = str(self.sampling).lower()
        if method == "none":
            return None
        from spark_ensemble_tpu.autotune import resolve as _tuned

        top, other = float(self.top_rate), float(self.other_rate)
        if method == "goss":
            if "top_rate" not in self._param_values:
                top = float(_tuned("goss_top_rate", top, n=n))
            if "other_rate" not in self._param_values:
                other = float(_tuned("goss_other_rate", other, n=n))
            k_top = int(np.ceil(top * n))
            k_rand = int(np.ceil(other * n))
            amp = max(1.0 - top, 0.0) / max(other, 1e-9)  # (1-a)/b
            lam = 0.0
        else:  # mvs: expected sample size = (top_rate + other_rate) * n
            k_top = 0
            k_rand = int(np.ceil(min(top + other, 1.0) * n))
            amp = 0.0
            lam = float(self.mvs_lambda)
        floor = int(_tuned("sample_bucket_floor", 256, n=n))
        bucket = _sample_pow2_bucket(n, k_top + k_rand, floor)
        return {
            "method": method,
            "bucket": bucket,
            "samp": (
                jnp.asarray(k_top, jnp.int32),
                jnp.asarray(k_rand, jnp.int32),
                jnp.asarray(amp, jnp.float32),
                jnp.asarray(lam, jnp.float32),
            ),
            "top_rate": top,
            "other_rate": other,
            "mvs_lambda": lam,
            "k_top": k_top,
            "k_rand": k_rand,
            "amp": amp,
            "sampled_rows": min(k_top + k_rand, n),
        }

    def _check_streaming_supported(self) -> None:
        """Streaming fits reject features whose ctx the shard sweep cannot
        stage: the compacted row gather and the linear-leaf raw-row solves
        both need the resident matrix."""
        if str(self.sampling).lower() != "none":
            raise ValueError(
                "fit_streaming does not support gradient-based row "
                "sampling (sampling != 'none'): the compacted gather "
                "needs the resident row matrix"
            )
        if str(self.leaf_model).lower() == "linear":
            raise ValueError(
                "fit_streaming does not support leaf_model='linear': the "
                "leaf ridge solve reads raw rows the shard stream does "
                "not stage"
            )

    def _check_sampling_supported(self, plan, mesh) -> None:
        """Shared fit-entry gates for the compacted-sampling path."""
        if plan is None:
            return
        if mesh is not None:
            raise ValueError(
                "sampling != 'none' is single-device only for now: the "
                "compacted row gather has no shard_map story yet (rows "
                "would need a cross-shard gather); drop mesh= or set "
                "sampling='none'"
            )
        if str(self.sample_method).lower() == "goss":
            raise ValueError(
                "sampling != 'none' supersedes the legacy weight-mask "
                "sample_method='goss'; configure one of the two"
            )

    def _drive_rounds(
        self,
        ckpt,
        members_chunks: List[Any],
        weights_chunks: List[Any],
        run_chunk,  # (sl, step_scale) -> (params [c,...], weights [c,...], errs|None)
        save_state,  # (round_idx, v, best) -> None  (must self-gate)
        label: str,
        i: int,
        v: int,
        best: float,
        val_history: Optional[List[float]] = None,  # mutated: per-round val losses
        telem: Optional[FitTelemetry] = None,
        guard=None,  # NumericGuard | None
        snapshot=None,  # () -> opaque copy of the carried prediction state
        restore=None,  # (snap) -> None; rewind the carry to chunk start
        n_rows: Optional[int] = None,  # training rows (autotune shape class)
        round_cost=None,  # ops.tree.round_cost_est dict for telemetry
        span_fields=None,  # extra round_chunk span fields (execution.py)
    ):
        """The shared round-loop driver: scan-chunked dispatch (one program
        per `scan_chunk` rounds, single-chip AND under a mesh — validation
        losses come back per round from inside the chunk); patience
        bookkeeping, mid-chunk stop accounting, and periodic state saves are
        identical for both GBM flavors.  ``run_chunk`` owns the
        prediction-state updates (via closure); extra members computed past a
        mid-chunk validation stop are trimmed by the caller's final
        ``keep = i - v`` slice.

        Robustness (docs/robustness.md): each chunk dispatch runs inside the
        retry/backoff layer (transient RuntimeError/XLA errors re-dispatch
        the SAME pure program), and when the numeric guard flags a round the
        carry is rewound to the chunk start, the clean prefix is replayed
        (bit-identical: same absolute round keys), and the poisoned round is
        raised / skipped / step-halved / truncated per ``on_nonfinite``."""
        from spark_ensemble_tpu.robustness.chaos import controller
        from spark_ensemble_tpu.robustness.retry import retry_call

        chunk = resolved_scan_chunk(self, n_rows)
        retry_policy = self._retry_policy()
        ctl = controller()
        refresh_fit = self._is_refresh_fit
        guard_on = guard is not None and guard.active
        # lookahead window (docs/pipeline.md): chunks kept in flight past
        # the one being committed; 0 pins the fully synchronous pre-pipeline
        # path.  Speculation needs the carry rewind hooks to keep
        # checkpoints crash-consistent, so depth degrades to 0 without them.
        depth = (
            _execution.resolve_pipeline_depth(n_rows)
            if snapshot is not None and restore is not None
            else 0
        )
        # opt-in on-device patience recurrence (f32 — see execution.py)
        dp_on = _execution.device_patience_enabled()

        def dispatch(sl, step_scale=1.0):
            site = f"{label}:round:{sl.start}"

            def attempt():
                ctl.transient(site)
                return run_chunk(sl, step_scale)

            params_c, weights_c, errs = retry_call(
                attempt, retry_policy, op=f"{label}.round_chunk", telem=telem
            )
            weights_c = ctl.poison_array(site, weights_c)
            return params_c, weights_c, errs

        def process(i, c, t_chunk, params_c, weights_c, errs, v, best):
            """One clean chunk's bookkeeping -> (i, v, best, stopped)."""
            if telem is not None and telem.enabled:
                # fence on the chunk outputs before reading the clock:
                # dispatch is async and an unfenced stamp times the enqueue
                telem.round_chunk(
                    i, c, t_chunk,
                    fence=(params_c, weights_c, errs),
                    losses=errs, step_sizes=weights_c,
                    round_cost=round_cost,
                )
            members_chunks.append(params_c)
            weights_chunks.append(weights_c)
            stopped = False
            if errs is not None:
                if dp_on:
                    # device recurrence: the host reads four scalars per
                    # chunk instead of stepping the loop per round (the
                    # per-round log lines are skipped in this mode)
                    best, v, stopped, kept = _execution.device_patience_step(
                        errs, best, v, self.validation_tol, self.num_rounds,
                        telem=telem,
                    )
                    if val_history is not None:
                        val_history.extend(
                            float(e) for e in np.asarray(errs)[:kept]
                        )
                    if stopped:
                        i += kept
                else:
                    for j, err in enumerate(np.asarray(errs)):
                        if val_history is not None:
                            val_history.append(float(err))
                        best, v = self._patience_step(
                            best, float(err), v, self.validation_tol
                        )
                        logger.info(
                            "%s round %d: val_loss=%.6f patience=%d",
                            label, i + j, float(err), v,
                        )
                        if v >= self.num_rounds:
                            i += j + 1
                            stopped = True
                            break
            if not stopped:
                i += c
                save_state(i - 1, v, best)
            return i, v, best, stopped

        def part(tree, lo, hi):
            return jax.tree_util.tree_map(lambda x: x[lo:hi], tree)

        def sanitize(tree):
            return jax.tree_util.tree_map(
                lambda x: jnp.nan_to_num(x, nan=0.0, posinf=0.0, neginf=0.0)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
                else x,
                tree,
            )

        def recover(i0, c, bad, snap, params_c, weights_c, errs, v, best):
            """Apply ``on_nonfinite`` to a chunk whose first poisoned round
            is chunk-relative index ``bad`` -> (i, v, best, halt)."""
            rnd = i0 + bad
            if guard.policy == "raise" or snap is None:
                guard.raise_error(rnd)
            if guard.policy == "stop_early":
                # keep the clean prefix (its members/weights came back
                # finite; the poisoned carry is never used again — the
                # final model is assembled from members, not the carry)
                guard.record(rnd, "stop_early")
                i = i0
                if bad > 0:
                    i, v, best, _ = process(
                        i0, bad, time.perf_counter(),
                        part(params_c, 0, bad), part(weights_c, 0, bad),
                        None if errs is None else errs[:bad],
                        v, best,
                    )
                return i, v, best, True
            # skip_round / halve_step: rewind the carry and replay the clean
            # prefix (same absolute rounds -> same fold_in keys -> identical
            # outputs; injected faults fire at most once per site, and real
            # transient faults are gone by construction)
            restore(snap)
            i = i0
            if bad > 0:
                t0 = time.perf_counter()
                p_pre, w_pre, e_pre = dispatch(slice(i0, i0 + bad))
                i, v, best, stopped = process(
                    i0, bad, t0, p_pre, w_pre, e_pre, v, best
                )
                if stopped:
                    return i, v, best, False
            if guard.policy == "halve_step":
                for h in range(1, guard.max_halvings + 1):
                    scale = 0.5 ** h
                    snap2 = snapshot()
                    t0 = time.perf_counter()
                    p1, w1, e1 = dispatch(slice(i, i + 1), step_scale=scale)
                    if guard.first_nonfinite(p1, w1, e1) is None:
                        guard.record(i, "halve_step", step_scale=scale)
                        i, v, best, _ = process(i, 1, t0, p1, w1, e1, v, best)
                        return i, v, best, False
                    restore(snap2)
                # not recoverable by damping: fall through to a skip
            # skip: re-run the round at step_scale=0 — the carried
            # prediction state advances by EXACTLY zero (the chunk program
            # hard-zeroes the contribution, so even NaN directions cannot
            # leak through 0*NaN) while keys/masks/checkpoint cadence stay
            # aligned to absolute round indices
            guard.record(i, "skip_round")
            t0 = time.perf_counter()
            p1, w1, e1 = dispatch(slice(i, i + 1), step_scale=0.0)
            # the member fit itself may be the non-finite source: store a
            # sanitized zero-weight copy so predict never sees 0 * NaN
            p1, w1 = sanitize(p1), sanitize(w1)
            e1 = None if e1 is None else jnp.nan_to_num(e1)
            i, v, best, _ = process(i, 1, t0, p1, w1, e1, v, best)
            return i, v, best, False

        # -- the family adapter behind the shared RoundExecutor ------------
        #
        # (docs/pipeline.md) With ``depth == 0`` the executor never holds
        # more than one chunk in flight, reproducing the historical fully
        # synchronous driver (pinned bit-identical by
        # tests/test_pipeline_exec.py).  With ``depth > 0`` each pending
        # entry carries TWO carry snapshots: ``snap_pre`` (chunk start —
        # the guard's rewind point) and ``snap_post`` (chunk end — the
        # state ``save_state`` must see, so a speculative chunk is never
        # persisted before its predecessor's bookkeeping commits).
        drv = self

        class _Adapter(_execution.RoundAdapter):
            def __init__(self):
                self.depth = depth
                self.telem = telem  # executor traces chunk spans through it
                self.span_fields = span_fields
                self.i, self.v, self.best = i, v, best
                self.halt = False
                self.i_disp = i  # dispatch frontier (absolute round index)

            def should_continue(self):
                return (
                    not self.halt
                    and self.i < drv.num_base_learners
                    and self.v < drv.num_rounds
                )

            def can_launch(self):
                return self.i_disp < drv.num_base_learners

            def launch(self):
                c = min(chunk, drv.num_base_learners - self.i_disp)
                if ckpt.enabled:
                    # end the chunk exactly on the next save boundary:
                    # keeps periodic saves firing at any resume offset,
                    # including a resume under a CHANGED checkpoint_interval
                    c = min(c, ckpt.rounds_until_save(self.i_disp))
                snap_pre = (
                    snapshot()
                    if (guard_on and snapshot is not None)
                    else None
                )
                t0 = time.perf_counter()
                params_c, weights_c, errs = dispatch(
                    slice(self.i_disp, self.i_disp + c)
                )
                # the end-of-chunk snapshot only matters when later chunks
                # can speculate past this one
                snap_post = snapshot() if self.depth > 0 else None
                entry = (
                    self.i_disp, c, snap_pre, snap_post, t0,
                    params_c, weights_c, errs,
                )
                self.i_disp += c
                return entry

            def commit(self, entry, speculated):
                (i0, c, snap_pre, snap_post, t0,
                 params_c, weights_c, errs) = entry
                if telem is not None and telem.enabled:
                    # host-blocked accounting (pure fence — no math): the
                    # wait the pipeline exists to overlap, measured so the
                    # A/B is observable rather than inferred
                    telem.blocking_read((params_c, weights_c, errs))
                bad = (
                    guard.first_nonfinite(params_c, weights_c, errs)
                    if guard_on
                    else None
                )
                invalidate = False
                if bad is None:
                    frontier = snapshot() if speculated else None
                    if speculated:
                        # commit under the chunk's own end-state so
                        # save_state persists committed arrays, not the
                        # speculative frontier
                        restore(snap_post)
                    self.i, self.v, self.best, stopped = process(
                        i0, c, t0, params_c, weights_c, errs,
                        self.v, self.best,
                    )
                    if stopped:
                        # mid-chunk validation stop: in-flight chunks were
                        # dispatched for rounds that no longer exist
                        invalidate = True
                    elif speculated:
                        restore(frontier)
                else:
                    if speculated:
                        # rewind to the sync-equivalent carry (this chunk's
                        # dispatch output) before recovery; the speculative
                        # chunks built on the poisoned state are dropped
                        restore(snap_post)
                    self.i, self.v, self.best, self.halt = recover(
                        i0, c, bad, snap_pre, params_c, weights_c, errs,
                        self.v, self.best,
                    )
                    invalidate = True
                # chaos: a mid-training preemption lands here — after the
                # chunk's periodic save, so kill-and-resume tests exercise
                # a real checkpoint boundary
                ctl.preempt(f"{label}:after_round:{self.i}")
                if refresh_fit:
                    # refresh-only kill site: a background warm-start fit
                    # dies mid-round, the serving model must stay untouched
                    ctl.refresh_crash(f"{label}:refresh_round:{self.i}")
                return invalidate

            def reset_frontier(self):
                self.i_disp = self.i

            def finish(self):
                # the loop must not end with a dangling background write:
                # join the in-flight async save (and surface its failure)
                # before the model is assembled
                ckpt.wait()

        ad = _execution.RoundExecutor(_Adapter()).run()
        return ad.i, ad.v, ad.best


def _goss_multiplier(
    neg_grad, w, bag_w, key, top_rate, other_rate, axis_name
):
    """Gradient-based one-side sampling multiplier (the fast-sampling GTB
    family, arXiv:1911.08820 / LightGBM's GOSS; an extension — the
    reference has only uniform subbagging): keep every row in the
    top ``top_rate`` fraction by gradient magnitude, keep a Bernoulli
    sample of the rest sized ``other_rate`` of the FULL data and amplified
    by the reciprocal keep-rate so the small-gradient mass stays unbiased.
    Enters as a WEIGHT multiplier (static shapes — the framework's
    sampling-by-weights discipline); the magnitude threshold is the exact
    mesh-aware weighted quantile, so no device gathers the column."""
    score = jnp.sqrt(jnp.sum(neg_grad * neg_grad, axis=-1))  # [n]
    thr = weighted_quantile(
        score, 1.0 - top_rate, w * bag_w, axis_name=axis_name
    )
    if axis_name is not None:
        # decorrelate the Bernoulli draws across row shards (the same key
        # on every shard would repeat the pattern shard-to-shard)
        names = (
            (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        )
        for nm in names:
            key = jax.random.fold_in(key, jax.lax.axis_index(nm))
    # other_rate is a fraction of the FULL dataset (LightGBM semantics):
    # the keep-rate among the (1-top_rate) rest is other_rate/(1-top_rate)
    # and the amplifier is its reciprocal, so E[multiplier | rest] = 1 —
    # the small-gradient mass is unbiased (clipped when other_rate
    # already covers the whole rest)
    p = jnp.minimum(1.0, other_rate / jnp.maximum(1.0 - top_rate, 1e-9))
    keep = jax.random.bernoulli(key, p, score.shape)
    return jnp.where(score >= thr, 1.0, jnp.where(keep, 1.0 / p, 0.0))


def _sample_pow2_bucket(n: int, k_target: int, floor: int) -> int:
    """Host-side compaction bucket: the next power of two >= the expected
    survivor count (floored at ``sample_bucket_floor``), clamped to n.
    The pow2 ladder keeps the traced-program inventory O(log n) across
    sample ratios — ratios landing in the same bucket share one compiled
    program (pinned by analysis/contracts.py 'sampling')."""
    target = max(1, min(int(k_target), int(n)), int(floor))
    m = 1
    while m < target:
        m *= 2
    return min(int(n), m)


def _sample_compact(method, score, alive, key, m, samp):
    """On-device survivor selection -> (idx[m], mult[m]): the row indices
    gathered into the compacted buffer and their amplification weights.

    Every rate-dependent quantity enters TRACED through ``samp`` =
    ``(k_top, k_rand, amp, lam)`` — program identity depends only on the
    static bucket ``m``, never on the configured rates (the O(1)-programs
    contract).  Zero-weight rows (``alive`` False: masked-out CV folds,
    subsample zeros) sort behind every candidate and can only land in the
    buffer with multiplier 0.

    GOSS (arXiv:1911.08820): the ``k_top`` largest-|grad| alive rows keep
    multiplier 1; exactly ``k_rand`` uniform draws from the rest carry the
    amplifier ``amp = (1-a)/b`` so the small-gradient mass stays unbiased.
    Selection is RANK-based (stable argsort), so tied scores resolve
    deterministically by row index.

    MVS: scores ``s = sqrt(grad^2 + lam)``; the threshold ``mu`` solving
    ``sum(min(1, s/mu)) = k_rand`` comes from an on-device bisection, rows
    with ``s >= mu`` are kept deterministically and the rest keep with
    probability ``s/mu`` and weight ``mu/s`` (importance-corrected).  On
    the rare binomial overflow past ``m`` the lowest-priority random keeps
    are dropped."""
    k_top, k_rand, amp, lam = samp
    n = score.shape[0]
    u = jax.random.uniform(key, (n,))
    if method == "goss":
        s = jnp.where(alive, score, -jnp.inf)
        order_s = jnp.argsort(-s)
        rank = jnp.zeros((n,), jnp.int32).at[order_s].set(
            jnp.arange(n, dtype=jnp.int32)
        )
        is_top = (rank < k_top) & alive
        # composite priority: top rows first (by uniform tiebreak), then
        # the random remainder ordered by its uniform draw, dead rows last
        pri = jnp.where(is_top, 2.0 + u, jnp.where(alive, u, -1.0))
        idx = jnp.argsort(-pri)[:m]
        n_top = jnp.sum(is_top).astype(jnp.int32)
        pos = jnp.arange(m, dtype=jnp.int32)
        mult = jnp.where(
            pos < n_top,
            1.0,
            jnp.where((pos < n_top + k_rand) & alive[idx], amp, 0.0),
        )
        return idx, mult
    # mvs
    s = jnp.where(alive, jnp.sqrt(score * score + lam), 0.0)
    k_f = jnp.asarray(k_rand, jnp.float32)
    hi0 = jnp.maximum(jnp.max(s), 1e-30)

    def bisect(_, bracket):
        lo, hi = bracket
        mid = 0.5 * (lo + hi)
        # count decreases in mu: too many expected keeps -> raise the floor
        over = jnp.sum(jnp.minimum(1.0, s / mid)) >= k_f
        return jnp.where(over, mid, lo), jnp.where(over, hi, mid)

    lo, hi = jax.lax.fori_loop(0, 30, bisect, (hi0 * 1e-9, hi0))
    mu = jnp.maximum(0.5 * (lo + hi), 1e-30)
    keep_det = alive & (s >= mu)
    keep_rand = alive & ~keep_det & (u * mu < s)
    pri = jnp.where(keep_det, 2.0 + u, jnp.where(keep_rand, u, -1.0))
    idx = jnp.argsort(-pri)[:m]
    pri_g = pri[idx]
    mult = jnp.where(
        pri_g >= 2.0,
        1.0,
        jnp.where(pri_g >= 0.0, mu / jnp.maximum(s[idx], 1e-30), 0.0),
    )
    return idx, mult


def _pseudo_residuals_and_weights(
    loss, updates, y_enc, pred, bag_w, w, axis_name=None, goss=None,
    goss_key=None,
):
    """Targets/weights for the round's base fit (`GBMRegressor.scala:368-385`,
    `GBMClassifier.scala:337-375`).  Returns (labels[n, dim], fit_w[n, dim],
    bag_w) — ``bag_w`` comes back multiplied by the GOSS sampling weights
    when ``goss=(top_rate, other_rate)`` is set, so the round's line search
    and leaf statistics see the same sampled set the trees fit.
    With ``axis_name`` the hessian sum reduces across data shards (the
    reference's element-wise treeReduce, `GBMClassifier.scala:344-355`)."""
    neg_grad = loss.negative_gradient(y_enc, pred)
    if goss is not None:
        bag_w = bag_w * _goss_multiplier(
            neg_grad, w, bag_w, goss_key, goss[0], goss[1], axis_name
        )
    if updates == "newton" and loss.has_hessian:
        h = jnp.maximum(loss.hessian(y_enc, pred), 1e-2)
        sum_h = jnp.sum(bag_w[:, None] * h, axis=0, keepdims=True)
        if axis_name is not None:
            sum_h = jax.lax.psum(sum_h, axis_name)
        labels = neg_grad / h
        fit_w = 0.5 * h / jnp.maximum(sum_h, 1e-30) * (w * bag_w)[:, None]
    else:
        labels = neg_grad
        fit_w = jnp.broadcast_to((w * bag_w)[:, None], neg_grad.shape)
    return labels, fit_w, bag_w


def _make_reg_loss(loss_name, alpha_q, delta):
    """Loss factory snapshot shared by the sequential chunk programs and the
    megabatch sweep (models/gbm_sweep.py): pure-function so cached closures
    never read estimator state at (re)trace time."""
    if loss_name == "huber":
        return losses_mod.HuberLoss(delta)
    return losses_mod.get_regression_loss(
        loss_name, alpha=alpha_q, quantile=alpha_q
    )


def make_reg_round_core(
    base, loss_name, alpha_q, updates, optimized, goss, tol, max_iter,
    ax=None, sampling="none", sample_bucket=None,
):
    """One regressor boosting round as a pure function of traced inputs.

    ``lr`` enters as the LAST argument (not a closure constant): the
    multiply ``(lr * alpha_opt) * scale`` builds the identical f32
    expression tree either way, so the change is bit-exact — and it lets
    the megabatch sweep ``vmap`` one program over candidates that differ
    only in learning rate (and in the data-borne seed/subsample/subspace
    draws).  Single source of round math for the sequential fit, the mesh
    fit, and ``models/gbm_sweep.py``.

    With ``sampling`` in {'goss', 'mvs'} the returned core takes one extra
    trailing argument ``samp`` (traced rate scalars, ``_sample_compact``)
    and fits on a ``sample_bucket``-row compacted gather of the survivors
    instead of the full rows — the tree fit, the newton hessian sum, and
    the line search all run over ``sample_bucket`` rows; only the carried
    prediction update re-routes the full matrix.  ``sampling='none'``
    builds the EXACT pre-existing program (bit-identity pin,
    tests/test_sampling.py)."""

    if sampling != "none":
        assert ax is None, "compacted sampling is single-device only"

        def round_core_sampled(
            ctx, X, bag_w, key, mask, pred, delta, y, w, scale, lr, samp
        ):
            loss = _make_reg_loss(loss_name, alpha_q, delta)
            y_enc = loss.encode_label(y)
            score = loss.sampling_scores(y_enc, pred[:, None])
            alive = (w * bag_w) > 0
            idx, mult = _sample_compact(
                sampling, score, alive, jax.random.fold_in(key, 11),
                sample_bucket, samp,
            )
            # gather the survivors into the compacted buffer; the (1-a)/b
            # amplification folds into the bag weights so split gains and
            # the newton normalizer stay unbiased
            y_s, w_s, pred_s = y[idx], w[idx], pred[idx]
            bag_s = bag_w[idx] * mult
            labels, fit_w, bag_s = _pseudo_residuals_and_weights(
                loss, updates, loss.encode_label(y_s), pred_s[:, None],
                bag_s, w_s,
            )
            ctx_s = base.ctx_gather_rows(ctx, idx)
            params, direction = base.fit_gathered_and_direction(
                ctx_s, labels[:, 0], fit_w[:, 0], mask, key, X
            )
            dir_s = direction[idx]
            if optimized and loss_name == "squared":
                # closed-form quadratic minimizer over the SAMPLED rows
                # (amplified weights keep it unbiased for the full set)
                res_s = y_s - pred_s
                num = jnp.sum(bag_s * dir_s * res_s)
                den = jnp.sum(bag_s * dir_s * dir_s)
                alpha_opt = jnp.where(
                    den > 1e-30,
                    jnp.clip(num / jnp.maximum(den, 1e-30), 0.0, 100.0),
                    jnp.asarray(1.0, jnp.float32),
                )
            elif optimized:
                y_enc_s = loss.encode_label(y_s)

                def phi(a):
                    return jnp.sum(
                        bag_s
                        * loss.loss(y_enc_s, (pred_s + a * dir_s)[:, None])
                    )

                alpha_opt = brent_minimize(
                    phi, 0.0, 100.0, tol=tol, max_iter=max_iter
                )
            else:
                alpha_opt = jnp.asarray(1.0, jnp.float32)
            weight = jnp.where(scale > 0, lr * alpha_opt * scale, 0.0)
            new_pred = pred + jnp.where(scale > 0, weight * direction, 0.0)
            return params, weight, new_pred

        return round_core_sampled

    def round_core(ctx, X, bag_w, key, mask, pred, delta, y, w, scale, lr):
        loss = _make_reg_loss(loss_name, alpha_q, delta)
        y_enc = loss.encode_label(y)
        labels, fit_w, bag_w = _pseudo_residuals_and_weights(
            loss, updates, y_enc, pred[:, None], bag_w, w,
            axis_name=ax, goss=goss,
            goss_key=jax.random.fold_in(key, 7),
        )
        # fit + same-row predictions in one protocol call: tree
        # learners reuse the leaf ids their fit computed instead
        # of re-routing every row (models/tree.py)
        params, direction = base.fit_and_direction(
            ctx, labels[:, 0], fit_w[:, 0], mask, key, X,
            axis_name=ax,
        )
        if optimized and loss_name == "squared":
            # phi(a) = sum bw*(res - a*dir)^2/2 is EXACTLY quadratic:
            # the minimizer is one data pass, not ~max_iter
            # sequential Brent evaluations (the reference runs Brent
            # even here, `GBMRegressor.scala:311,413` — same
            # minimizer, found in closed form), clamped to Brent's
            # [0, 100] bracket
            res = y - pred
            num = jnp.sum(bag_w * direction * res)
            den = jnp.sum(bag_w * direction * direction)
            if ax is not None:
                num = jax.lax.psum(num, ax)
                den = jax.lax.psum(den, ax)
            alpha_opt = jnp.where(
                den > 1e-30,
                jnp.clip(num / jnp.maximum(den, 1e-30), 0.0, 100.0),
                # zero direction: any weight is a no-op; keep 1.0
                jnp.asarray(1.0, jnp.float32),
            )
        elif optimized:
            def phi(a):
                # bag-multiplicity weighting only (`GBMLoss.scala:50-74`)
                v = jnp.sum(
                    bag_w * loss.loss(y_enc, (pred + a * direction)[:, None])
                )
                return jax.lax.psum(v, ax) if ax is not None else v
            alpha_opt = brent_minimize(
                phi, 0.0, 100.0, tol=tol, max_iter=max_iter
            )
        else:
            alpha_opt = jnp.asarray(1.0, jnp.float32)
        # `scale` is the numeric guard's step damper (1.0 on the
        # clean path — a multiplicative identity, bit-exact).  At
        # scale == 0 (skip_round replay) the contribution is
        # HARD-zeroed so a NaN direction/step cannot leak through
        # 0 * NaN into the carried prediction state.
        weight = jnp.where(scale > 0, lr * alpha_opt * scale, 0.0)
        new_pred = pred + jnp.where(
            scale > 0, weight * direction, 0.0
        )
        return params, weight, new_pred

    return round_core


def make_reg_chunk_fn(
    base, loss_name, alpha_q, updates, optimized, goss, tol, max_iter,
    huber, with_validation, sampling="none", sample_bucket=None,
):
    """The UNJITTED single-chip chunk function: lax.scan of the round core
    over a chunk of rounds (huber's adaptive delta and the validation loss
    computed in-program, in the same per-round order as the host loop).
    The sequential fit jits it directly; the megabatch sweep jits
    ``vmap`` of it over a candidate axis — so sweep round math is the
    sequential program by construction, not by parallel maintenance.
    With ``sampling`` != 'none' the chunk takes one extra trailing
    ``samp`` argument (see :func:`make_reg_round_core`)."""
    round_core = make_reg_round_core(
        base, loss_name, alpha_q, updates, optimized, goss, tol, max_iter,
        sampling=sampling, sample_bucket=sample_bucket,
    )

    def chunk(ctx, X, y, w, valid_w, pred, pred_val, delta,
              X_val_a, y_val_a, bag_ws, keys, masks, scales, lr,
              *samp_args):
        def body(carry, xs):
            pred, pred_val, delta = carry
            bag_w, key, mask, scale = xs
            if huber:
                delta = weighted_quantile(
                    jnp.abs(y - pred), alpha_q, weights=valid_w
                )
            params, weight, new_pred = round_core(
                ctx, X, bag_w, key, mask, pred, delta, y, w, scale, lr,
                *samp_args,
            )
            if with_validation:
                dir_val = base.predict_fn(params, X_val_a)
                # same hard-zero-at-scale-0 guard as the train-side
                # update: 0 * NaN must not poison the val carry
                new_pred_val = pred_val + jnp.where(
                    scale > 0, weight * dir_val, 0.0
                )
                l = _make_reg_loss(loss_name, alpha_q, delta)
                err = jnp.mean(
                    l.loss(l.encode_label(y_val_a), new_pred_val[:, None])
                )
            else:
                new_pred_val = pred_val
                err = jnp.float32(0)
            return (new_pred, new_pred_val, delta), (params, weight, err)

        (pred, pred_val, delta), (params_all, weights_all, errs) = (
            jax.lax.scan(
                body, (pred, pred_val, delta),
                (bag_ws, keys, masks, scales),
            )
        )
        return params_all, weights_all, errs, pred, pred_val, delta

    return chunk


def make_cls_round_core(
    base, loss, dim, updates, optimized, goss, tol, max_iter,
    ax=None, member_size=1, dim_blk=None, sampling="none",
    sample_bucket=None,
):
    """Classifier boosting round as a pure function; see
    :func:`make_reg_round_core` for the traced-``lr`` contract (here the
    step is ``lr * alpha_opt * scale`` over the class-dim vector) and for
    the compacted-sampling variant (``sampling`` != 'none' adds a trailing
    ``samp`` argument; rows rank by the l2 gradient norm over the class
    dims and ALL dim trees fit on the same gathered buffer)."""
    dim_blk = dim if dim_blk is None else dim_blk
    k_local = dim_blk // member_size

    if sampling != "none":
        assert ax is None and member_size == 1, (
            "compacted sampling is single-device only"
        )

        def round_core_sampled(ctx, X, y_enc, w, bag_w, key, mask, pred,
                               alpha_ws, scale, lr, samp):
            score = loss.sampling_scores(y_enc, pred)
            alive = (w * bag_w) > 0
            idx, mult = _sample_compact(
                sampling, score, alive, jax.random.fold_in(key, 11),
                sample_bucket, samp,
            )
            y_enc_s, w_s, pred_s = y_enc[idx], w[idx], pred[idx]
            bag_s = bag_w[idx] * mult
            labels, fit_w, bag_s = _pseudo_residuals_and_weights(
                loss, updates, y_enc_s, pred_s, bag_s, w_s
            )
            ctx_s = base.ctx_gather_rows(ctx, idx)
            params, directions = base.fit_gathered_many_and_directions(
                ctx_s, labels, fit_w, mask, key, X
            )
            dirs_s = directions[idx]
            if optimized:
                def phi(a):
                    return jnp.sum(
                        bag_s
                        * loss.loss(y_enc_s, pred_s + a[None, :] * dirs_s)
                    )

                if loss.has_hessian:
                    gh = lambda a: loss.linesearch_grad_hess(
                        y_enc_s, pred_s + a[None, :] * dirs_s, dirs_s,
                        bag_s,
                    )
                else:
                    gh = None
                alpha_opt = projected_newton_box(
                    phi, alpha_ws, max_iter=min(max_iter, 25), tol=tol,
                    grad_hess=gh,
                )
            else:
                alpha_opt = jnp.ones((dim,), jnp.float32)
            weight = jnp.where(scale > 0, lr * alpha_opt * scale, 0.0)
            new_pred = pred + jnp.where(
                scale > 0, weight[None, :] * directions, 0.0
            )
            alpha_carry = jnp.where(
                jnp.isfinite(alpha_opt), alpha_opt,
                jnp.ones_like(alpha_opt),
            )
            return params, weight, new_pred, alpha_carry

        return round_core_sampled

    def round_core(ctx, X, y_enc, w, bag_w, key, mask, pred,
                   alpha_ws, scale, lr):
        labels, fit_w, bag_w = _pseudo_residuals_and_weights(
            loss, updates, y_enc, pred, bag_w, w, axis_name=ax,
            goss=goss, goss_key=jax.random.fold_in(key, 7),
        )
        if member_size > 1:
            # each member shard fits its block of class dims — the
            # SPMD replacement for the reference's per-dim Futures;
            # phantom tail dims carry zero labels AND zero weights
            if dim_blk != dim:
                pad = [(0, 0), (0, dim_blk - dim)]
                labels = jnp.pad(labels, pad)
                fit_w = jnp.pad(fit_w, pad)
            sl = jax.lax.axis_index("member") * k_local
            labels_blk = jax.lax.dynamic_slice_in_dim(
                labels, sl, k_local, axis=1
            )
            fitw_blk = jax.lax.dynamic_slice_in_dim(
                fit_w, sl, k_local, axis=1
            )
        else:
            labels_blk, fitw_blk = labels, fit_w
        # one fused multi-member fit replaces the reference's
        # per-dim Futures (trees: the class dims fold into a single
        # histogram matmul per level — ops/tree.py fit_forest)
        # fused fit + same-row predictions (leaf-id reuse for
        # trees — the per-round forest predict re-route disappears)
        params, directions = base.fit_many_and_directions(
            ctx, labels_blk, fitw_blk, mask, key, X, axis_name=ax
        )
        if member_size > 1:
            directions = jax.lax.all_gather(
                directions, "member", axis=1, tiled=True
            )[:, :dim]
        if optimized:
            # SHARD-LOCAL objective; projected_newton_box psums
            # value/grad/hessian over `ax` itself (psum inside the
            # objective would break its autodiff — see linesearch.py)
            def phi(a):
                return jnp.sum(
                    bag_w * loss.loss(y_enc, pred + a[None, :] * directions)
                )

            # one-pass closed-form grad/hessian (ops/losses.py)
            # instead of dim forward passes of jax.hessian per
            # Newton iteration — the dominant round cost at K=26
            if loss.has_hessian:
                gh = lambda a: loss.linesearch_grad_hess(
                    y_enc, pred + a[None, :] * directions, directions, bag_w
                )
            else:
                gh = None
            # warm start from the previous round's converged step
            # sizes (carried through the scan): consecutive rounds'
            # objectives are near-identical, so Newton typically
            # re-converges in 1-2 iterations instead of ~5 from
            # all-ones — the line-search small-op tail is a
            # measured slice of the device round (BASELINE.md)
            alpha_opt = projected_newton_box(
                phi,
                alpha_ws,
                max_iter=min(max_iter, 25),
                tol=tol,
                axis_name=ax,
                grad_hess=gh,
            )
        else:
            alpha_opt = jnp.ones((dim,), jnp.float32)
        # `scale` is the numeric guard's step damper (1.0 on the
        # clean path — multiplicative identity).  At scale == 0 the
        # contribution is HARD-zeroed (0 * NaN must not leak), and
        # the warm-start carry resets to ones when the line search
        # itself went non-finite so later rounds restart clean.
        weight = jnp.where(
            scale > 0, lr * alpha_opt * scale, 0.0
        )
        new_pred = pred + jnp.where(
            scale > 0, weight[None, :] * directions, 0.0
        )
        alpha_carry = jnp.where(
            jnp.isfinite(alpha_opt), alpha_opt,
            jnp.ones_like(alpha_opt),
        )
        return params, weight, new_pred, alpha_carry

    return round_core


def make_cls_chunk_fn(
    base, loss, dim, updates, optimized, goss, tol, max_iter,
    with_validation, sampling="none", sample_bucket=None,
):
    """UNJITTED single-chip classifier chunk (see :func:`make_reg_chunk_fn`
    for the sequential/megabatch single-source contract and the trailing
    ``samp`` argument under ``sampling`` != 'none')."""
    round_core = make_cls_round_core(
        base, loss, dim, updates, optimized, goss, tol, max_iter,
        sampling=sampling, sample_bucket=sample_bucket,
    )

    def chunk(ctx, X, y_enc, w, pred, pred_val, alpha_ws, X_val_a,
              y_enc_val_a, bag_ws, keys, masks, scales, lr, *samp_args):
        def body(carry, xs):
            pred, pred_val, alpha_ws = carry
            bag_w, key, mask, scale = xs
            params, weight, new_pred, alpha_ws = round_core(
                ctx, X, y_enc, w, bag_w, key, mask, pred, alpha_ws,
                scale, lr, *samp_args,
            )
            if with_validation:
                dirs_val = jax.vmap(
                    lambda p: base.predict_fn(p, X_val_a)
                )(params).T
                new_pred_val = pred_val + jnp.where(
                    scale > 0, weight[None, :] * dirs_val, 0.0
                )
                err = jnp.mean(loss.loss(y_enc_val_a, new_pred_val))
            else:
                new_pred_val = pred_val
                err = jnp.float32(0)
            return (new_pred, new_pred_val, alpha_ws), (params, weight, err)

        (pred, pred_val, alpha_ws), (params_all, weights_all, errs) = (
            jax.lax.scan(
                body, (pred, pred_val, alpha_ws),
                (bag_ws, keys, masks, scales),
            )
        )
        return params_all, weights_all, errs, pred, pred_val, alpha_ws

    return chunk


def _probe_classifier_phases(
    telem, loss, updates, base, ctx, X, y_enc, w, bag_w, key, mask, pred,
    alpha_ws, optimized, lr, tol, max_iter, goss,
):
    """Opt-in fine-phase probe (``SE_TPU_TELEMETRY_PHASES=1``): runs the
    round's pieces as SEPARATE jitted programs on round-0 inputs and emits
    a ``phase_probe`` event with each piece's device time.  The production
    round fuses everything into one scan-chunked program where these
    boundaries do not exist on the host — so the probe pays one extra
    compile+execute per piece and its times are representative, not
    additive with the round stream.  ``tree_fit`` covers the fused
    histogram build + split search + leaf solve inside
    ``fit_many_and_directions`` (op-level splits: utils/profiling.py on a
    profiler trace).  Arrays enter as jit ARGUMENTS — closing over them
    would constant-fold the inputs and time a different program."""

    def time_once(fn, *args):
        out = fn(*args)  # compile + warmup execution
        # graftlint: ignore[unfenced-blocking-read] -- warmup sync before the timed rep, deliberately untimed
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        return time.perf_counter() - t0, out

    f_gh = jax.jit(
        lambda y_enc, pred, bag_w, w, key: _pseudo_residuals_and_weights(
            loss, updates, y_enc, pred, bag_w, w, goss=goss,
            goss_key=jax.random.fold_in(key, 7),
        )
    )
    f_fit = jax.jit(
        lambda ctx, labels, fit_w, mask, key, X: base.fit_many_and_directions(
            ctx, labels, fit_w, mask, key, X
        )
    )
    f_up = jax.jit(
        lambda pred, weight, directions: pred + weight[None, :] * directions
    )

    durations = {}
    dt, (labels, fit_w, bag_w) = time_once(f_gh, y_enc, pred, bag_w, w, key)
    durations["grad_hess"] = dt
    dt, (params, directions) = time_once(
        f_fit, ctx, labels, fit_w, mask, key, X
    )
    durations["tree_fit"] = dt
    if optimized:

        def _ls(y_enc, pred, directions, bag_w, alpha_ws):
            def phi(a):
                return jnp.sum(
                    bag_w * loss.loss(y_enc, pred + a[None, :] * directions)
                )

            gh = None
            if loss.has_hessian:
                gh = lambda a: loss.linesearch_grad_hess(
                    y_enc, pred + a[None, :] * directions, directions, bag_w
                )
            return projected_newton_box(
                phi, alpha_ws, max_iter=min(max_iter, 25), tol=tol,
                grad_hess=gh,
            )

        dt, alpha = time_once(
            jax.jit(_ls), y_enc, pred, directions, bag_w, alpha_ws
        )
        durations["line_search"] = dt
    else:
        alpha = jnp.ones_like(alpha_ws)
    dt, _ = time_once(f_up, pred, lr * alpha, directions)
    durations["update"] = dt
    telem.phase_probe(
        durations,
        note="tree_fit fuses histogram build + split search + leaf solve; "
        "single-round unsharded probe, times representative not additive",
    )


class GBMRegressor(_GBMParams):
    """Friedman GBM regressor (reference `GBMRegressor.scala`)."""

    loss = Param(
        "squared",
        in_array(
            ["squared", "absolute", "huber", "quantile", "logcosh", "scaledlogcosh"]
        ),
        doc="reference-supported: squared|absolute|huber|quantile; logcosh and "
        "scaledlogcosh are exposed as extensions (present in GBMLoss.scala "
        "but not surfaced by GBMRegressorParams)",
    )
    alpha = Param(
        0.9, in_range(0.0, 1.0),
        doc="huber/quantile shape parameter (adaptive huber delta "
        "re-quantiles the residuals each round)",
    )
    init_strategy = Param(
        "constant", in_array(["constant", "zero", "base"]),
        doc="round-0 prediction: weighted target constant, zero, or a "
        "fitted copy of the base learner",
    )

    is_classifier = False

    def _make_loss(self, delta):
        name = self.loss.lower()
        if name == "huber":
            return losses_mod.HuberLoss(delta)
        if name == "quantile":
            return losses_mod.QuantileLoss(self.alpha)
        if name == "scaledlogcosh":
            return losses_mod.ScaledLogCoshLoss(self.alpha)
        return losses_mod.get_regression_loss(name)

    def _fit_init(self, X, y, w, mesh=None):
        """Init model (`GBMRegressor.scala:287-303`); with ``mesh`` the init
        fit distributes through the base learner's standalone mesh path —
        no single-device island before the distributed rounds."""
        strategy = self.init_strategy.lower()
        if strategy == "base":
            base = self._base()
            return base.fit(
                X, y, sample_weight=w, **mesh_fit_kwargs(base, mesh)
            )
        if strategy == "zero":
            dummy = DummyRegressor(strategy="constant", constant=0.0)
            return dummy.fit(X, y, w, **mesh_fit_kwargs(dummy, mesh))
        name = self.loss.lower()
        if name in ("absolute", "huber"):
            dummy = DummyRegressor(strategy="median")
        elif name == "quantile":
            dummy = DummyRegressor(strategy="quantile", quantile=self.alpha)
        else:
            dummy = DummyRegressor(strategy="mean")
        return dummy.fit(
            X, y, sample_weight=w, **mesh_fit_kwargs(dummy, mesh)
        )

    @instrumented_fit
    def fit(self, X, y, sample_weight=None, validation_indicator=None, mesh=None):
        """Fit; with ``mesh`` (axes ("data",) or ("data", "member")) the whole
        round step runs as ONE shard_map-ed SPMD program with rows sharded
        over "data" — histograms/hessian-sums/line-search objectives reduce
        via psum, the XLA replacement for the reference's executor-side
        treeAggregate (`GBMRegressor.scala:373`, `GBMClassifier.scala:344-355`).
        """
        X = as_f32(X)
        y = as_f32(y)
        self._validate_fit_inputs(X, y)
        w_all = resolve_weights(y, sample_weight)
        if validation_indicator is not None:
            vi = np.asarray(validation_indicator, bool)
            X_val, y_val = X[vi], y[vi]
            X, y, w = X[~vi], y[~vi], w_all[~vi]
        else:
            X_val = y_val = None
            w = w_all
        n, d = X.shape
        instr = Instrumentation("GBMRegressor.fit")
        instr.log_params(self.get_params())
        instr.log_dataset(n, d)
        telem = FitTelemetry.start(self, n=n, d=d)
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = make_shared_fit_ctx(base, X)
        # training-time drift reference (telemetry/quality.py): thresholds +
        # per-feature bin occupancy, read from the binned ctx BEFORE row
        # sharding pads it — pure host bincounts, no extra compiled program
        drift_ref = drift_reference_from_ctx(ctx)
        bag_keys, masks = self._sampling_plan(n, d)

        init_model = self._fit_init(X, y, w, mesh=mesh)
        huber = self.loss.lower() == "huber"
        # initial huber delta: alpha-quantile of the label over the full
        # input (reference `GBMRegressor.scala:305-308` uses `dataset`)
        if huber:
            full_y = jnp.concatenate([y, y_val]) if y_val is not None else y
            delta = weighted_quantile(full_y, self.alpha)
        else:
            delta = jnp.asarray(0.0, jnp.float32)

        # ---- mesh setup: pad rows to the data-axis size, shard arrays ----
        ax = None
        n_pad = n
        valid_w = jnp.ones((n,), jnp.float32)
        if mesh is not None:
            ctx, X, ax, n_pad, (y, w, valid_w) = setup_row_sharding(
                mesh, base, ctx, X, n, (y, w, valid_w)
            )
        pred = init_model.predict(X)

        updates = self.updates.lower()
        optimized = bool(self.optimized_weights)
        lr = float(self.learning_rate)
        goss = (
            (float(self.top_rate), float(self.other_rate))
            if self.sample_method.lower() == "goss"
            else None
        )
        sub_ratio = float(self.subsample_ratio)
        repl = bool(self.replacement)
        tol = float(self.tol)
        max_iter = int(self.max_iter)
        alpha_q = float(self.alpha)
        loss_name = self.loss.lower()
        base_key = base.config_key()
        sample_plan = self._resolved_sampling(n)
        self._check_sampling_supported(sample_plan, mesh)
        samp_method = sample_plan["method"] if sample_plan else "none"
        sample_bucket = sample_plan["bucket"] if sample_plan else None
        samp_args = (sample_plan["samp"],) if sample_plan else ()
        if sample_plan is not None:
            telem.emit(
                "sampling_config",
                method=samp_method,
                top_rate=sample_plan["top_rate"],
                other_rate=sample_plan["other_rate"],
                mvs_lambda=sample_plan["mvs_lambda"],
                sampled_rows=sample_plan["sampled_rows"],
                sample_bucket=sample_bucket,
                amp=sample_plan["amp"],
            )

        with_validation = X_val is not None

        # all data flows through arguments so the jitted programs are
        # reusable across fits with the same config (no per-fit retrace);
        # round math lives in the module-level factories shared with the
        # megabatch sweep (models/gbm_sweep.py)
        def build_chunk_step():
            return jax.jit(make_reg_chunk_fn(
                base, loss_name, alpha_q, updates, optimized, goss, tol,
                max_iter, huber, with_validation,
                sampling=samp_method, sample_bucket=sample_bucket,
            ))

        def build_chunk_step_mesh():
            """Scan-chunked rounds as ONE shard_map-ed SPMD program — the
            distributed path gets the same dispatch amortization as the
            single-chip path.  The validation split rides the same program:
            X_val/pred_val shard over the row axis and each round's val loss
            is a psum-ed weighted mean over the valid (non-padding) val rows
            — the reference evaluates validation loss distributed per round
            the same way (`GBMRegressor.scala:444-465`)."""
            round_core = make_reg_round_core(
                base, loss_name, alpha_q, updates, optimized, goss, tol,
                max_iter, ax=ax,
            )

            def chunk(ctx, X, y, w, valid_w, pred, pred_val, delta,
                      X_val_a, y_val_a, valid_val, bag_ws, keys, masks,
                      scales, lr):
                def body(carry, xs):
                    pred, pred_val, delta = carry
                    bag_w, key, mask, scale = xs
                    if huber:
                        # psum-ed histogram refinement inside the quantile
                        # (no all_gather): identical global delta on every
                        # shard with O(bins) communicated state
                        delta = weighted_quantile(
                            jnp.abs(y - pred), alpha_q, weights=valid_w,
                            axis_name=ax,
                        )
                    params, weight, new_pred = round_core(
                        ctx, X, bag_w, key, mask, pred, delta, y, w, scale,
                        lr,
                    )
                    if with_validation:
                        dir_val = base.predict_fn(params, X_val_a)
                        new_pred_val = pred_val + jnp.where(
                            scale > 0, weight * dir_val, 0.0
                        )
                        l = _make_reg_loss(loss_name, alpha_q, delta)
                        le = l.loss(
                            l.encode_label(y_val_a), new_pred_val[:, None]
                        )
                        err = jax.lax.psum(
                            jnp.sum(valid_val * jnp.reshape(le, (-1,))), ax
                        ) / jax.lax.psum(jnp.sum(valid_val), ax)
                    else:
                        new_pred_val = pred_val
                        err = jnp.float32(0)
                    return (new_pred, new_pred_val, delta), (
                        params, weight, err,
                    )

                (pred, pred_val, delta), (params_all, weights_all, errs) = (
                    jax.lax.scan(
                        body, (pred, pred_val, delta),
                        (bag_ws, keys, masks, scales),
                    )
                )
                return params_all, weights_all, errs, pred, pred_val, delta

            return jax.jit(
                shard_map(
                    chunk,
                    mesh=mesh,
                    in_specs=(
                        base.ctx_specs(ctx, ax),
                        P(ax, None),  # X
                        P(ax),  # y
                        P(ax),  # w
                        P(ax),  # valid_w
                        P(ax),  # pred
                        P(ax),  # pred_val
                        P(),  # delta
                        P(ax, None),  # X_val
                        P(ax),  # y_val
                        P(ax),  # valid_val
                        P(None, ax),  # bag_ws [c, n_pad]
                        P(),  # keys [c, 2]
                        P(),  # masks [c, d]
                        P(),  # scales [c]
                        P(),  # lr
                    ),
                    out_specs=(P(), P(), P(), P(ax), P(ax), P()),
                    check_vma=False,
                )
            )

        # NOTE: learning_rate is deliberately ABSENT — it enters the chunk
        # programs as a traced argument, so fits differing only in lr share
        # one compiled program (and the megabatch sweep batches over it)
        round_key = (
            "gbm_reg_round",
            loss_name,
            alpha_q,
            updates,
            optimized,
            goss,
            sub_ratio,
            repl,
            tol,
            max_iter,
            base_key,
            mesh,
        )
        if sample_plan is not None:
            # the sampling RATES are deliberately absent — they enter the
            # program as traced scalars, so two ratios landing in the same
            # pow2 bucket share one compiled program (contract: 'sampling')
            round_key = round_key + ("sampling", samp_method, sample_bucket)
        bag_many = self._make_bag_many_fn(n, n_pad)
        if mesh is not None:
            chunk_step = cached_program(
                round_key + ("chunk_mesh", huber, with_validation),
                build_chunk_step_mesh,
            )
        else:
            chunk_step = cached_program(
                round_key + ("chunk", huber, with_validation),
                build_chunk_step,
            )

        eval_loss = cached_program(
            ("gbm_reg_eval", loss_name, alpha_q),
            lambda: jax.jit(
                lambda pred_v, delta, y_v: jnp.mean(
                    _make_reg_loss(loss_name, alpha_q, delta).loss(
                        _make_reg_loss(loss_name, alpha_q, delta)
                        .encode_label(y_v),
                        pred_v[:, None],
                    )
                )
            ),
        )

        best = 0.0
        pred_val = None
        nv_pad = 0
        valid_val = val_dummy = jnp.zeros((0,), jnp.float32)
        val_dummy2 = jnp.zeros((0, 1), jnp.float32)
        if with_validation:
            X_val = jnp.asarray(X_val)
            y_val = jnp.asarray(y_val)
            pred_val = init_model.predict(X_val)
            best = float(eval_loss(pred_val, delta, y_val))
            nv_pad = X_val.shape[0]
            if mesh is not None:
                # shard the validation split over the same row axis: its
                # per-round loss is computed inside the chunked SPMD program
                nv_pad, valid_val, (y_val, pred_val), (X_val,) = (
                    shard_validation_rows(
                        mesh, nv_pad, (y_val, pred_val), (X_val,)
                    )
                )

        members_chunks: List[Any] = []
        weights_chunks: List[Any] = []
        val_history: List[float] = []
        i, v = 0, 0

        # n_pad AND nv_pad are part of the identity: checkpointed `pred` /
        # `pred_val` are padded to the mesh's data-axis size, so a resume
        # under a different mesh (different padding) must start fresh rather
        # than load wrong-length prediction state
        ckpt = self._checkpointer(n, d, n_pad, nv_pad, telem=telem)
        resumed = ckpt.load_latest()
        warm = False
        if resumed is None:
            # warm-start resume from a served PackedModel prefix (fit_resume
            # in serving/export.py); a real checkpoint always wins
            resumed = self._take_warm_resume()
            warm = resumed is not None
        if resumed is not None:
            last_round, st = resumed
            detail = ckpt.last_load_detail or {}
            telem.emit(
                "resume_from_checkpoint",
                round=last_round + 1,
                source="warm_start" if warm else detail.get("source", "latest"),
                fallback=bool(detail.get("fallback", False)),
            )
            i, v, best = last_round + 1, int(st["v"]), float(st["best"])
            val_history[:] = [float(x) for x in np.asarray(st.get("val_hist", []))]
            pred = jnp.asarray(st["pred"])
            if mesh is not None:
                pred = jax.device_put(
                    pred, NamedSharding(mesh, P(_mesh_row_spec(mesh)))
                )
            pred_val = st.get("pred_val")
            if pred_val is not None:
                pred_val = jnp.asarray(pred_val)
                if mesh is not None:
                    pred_val = jax.device_put(
                        pred_val, NamedSharding(mesh, P(_mesh_row_spec(mesh)))
                    )
            members_chunks, weights_chunks = self._resume_chunks(st)
            delta = jnp.asarray(st["delta"])
            logger.info("GBMRegressor resuming from round %d", i)

        def save_state(round_idx, v, best):
            # gate BEFORE building the state: the full-history concat below
            # must not run every round when checkpointing is off
            if not ckpt.should_save(round_idx):
                return
            ckpt.save(
                round_idx,
                {
                    "v": v,
                    "best": best,
                    "val_hist": jnp.asarray(val_history, jnp.float32),
                    "pred": pred,
                    "pred_val": pred_val,
                    "members_layout": self.MEMBERS_LAYOUT,
                    "members": concat_pytrees(members_chunks),
                    "weights": concat_pytrees(weights_chunks),
                    "delta": delta,
                },
            )

        def run_chunk(sl, step_scale=1.0):
            nonlocal pred, pred_val, delta
            scales = jnp.full(
                (sl.stop - sl.start,), step_scale, jnp.float32
            )
            if mesh is not None:
                params_c, weights_c, errs, pred, pred_val_new, delta = (
                    chunk_step(
                        ctx, X, y, w, valid_w, pred,
                        pred_val if with_validation else val_dummy,
                        delta,
                        X_val if with_validation else val_dummy2,
                        y_val if with_validation else val_dummy,
                        valid_val,
                        bag_many(bag_keys[sl]), bag_keys[sl], masks[sl],
                        scales, jnp.float32(lr),
                    )
                )
            else:
                params_c, weights_c, errs, pred, pred_val_new, delta = (
                    chunk_step(
                        ctx, X, y, w, valid_w, pred,
                        pred_val if with_validation else val_dummy,
                        delta,
                        X_val if with_validation else val_dummy,
                        y_val if with_validation else val_dummy,
                        bag_many(bag_keys[sl]), bag_keys[sl], masks[sl],
                        scales, jnp.float32(lr), *samp_args,
                    )
                )
            if with_validation:
                pred_val = pred_val_new
            return params_c, weights_c, errs if with_validation else None

        def snapshot():
            return pred, pred_val, delta

        def restore(snap):
            nonlocal pred, pred_val, delta
            pred, pred_val, delta = snap

        telem.phase_mark("setup")
        i, v, best = self._drive_rounds(
            ckpt, members_chunks, weights_chunks,
            run_chunk, save_state, "GBMRegressor", i, v, best,
            val_history=val_history, telem=telem,
            guard=self._numeric_guard(telem),
            snapshot=snapshot, restore=restore, n_rows=n,
            round_cost=_round_cost(base, n, d, 1, sample_plan=sample_plan),
            span_fields=(
                {
                    "sampling": samp_method,
                    "sample_bucket": sample_bucket,
                }
                if sample_plan
                else None
            ),
        )
        ckpt.delete()

        keep = i - v
        instr.log_outcome(rounds=i, kept_members=keep)
        all_members = concat_pytrees(members_chunks) if members_chunks else None
        all_weights = (
            jnp.concatenate(weights_chunks) if weights_chunks else None
        )
        model = GBMRegressionModel(
            params={
                "members": slice_pytree(all_members, keep) if keep > 0 else None,
                "weights": all_weights[:keep] if keep > 0 else jnp.zeros((0,)),
                "masks": masks[:keep],
                "init": init_model.params,
                "val_hist": jnp.asarray(val_history, jnp.float32)
                if with_validation
                else None,
            },
            num_features=d,
            init_model=init_model,
            num_members=keep,
            **self.get_params(),
        )
        if drift_ref is not None:
            model.drift_ref_ = drift_ref
        telem.finish(model=model, rounds=i, kept_members=keep)
        return model

    @instrumented_fit
    def fit_streaming(self, store, y, sample_weight=None, X_val=None,
                      y_val=None, mesh=None, reduce="ordered"):
        """Out-of-core fit over a sealed ``ShardStore`` (data/shards.py):
        the packed bin matrix streams from disk shard-by-shard, never
        resident on device at once — bit-identical to ``fit`` with a
        ``hist="stream"`` base learner at matched chunk rows (see
        data/streaming.py for the argument).

        ``mesh`` distributes the shard sweeps across the mesh's row
        positions (pod-scale training, parallel/elastic.py): each host
        streams only its round-robin slice of the manifest and
        histogram contributions reduce over ``{dcn_data, data}`` before
        split selection.  ``reduce="ordered"`` (default) keeps the fit
        bit-identical to the single-host one; ``reduce="psum"`` trades
        that for cheaper cross-host traffic (allclose results).  Wrap
        the call in an ``ElasticCoordinator`` to survive host
        preemptions."""
        self._check_streaming_supported()
        from spark_ensemble_tpu.data.streaming import fit_streaming_regressor

        return fit_streaming_regressor(
            self, store, y, sample_weight=sample_weight,
            X_val=X_val, y_val=y_val, mesh=mesh, reduce=reduce,
        )


def _check_resume_args(model, k: int, n_new: int, X) -> None:
    """Shared ``fit_resume`` argument gate (GBM + Boosting families)."""
    if k < 1:
        raise ValueError(
            "fit_resume needs at least one committed member to resume from"
        )
    if n_new < 1:
        raise ValueError(f"n_new_rounds must be >= 1; got {n_new}")
    d = np.shape(X)[1] if np.ndim(X) == 2 else -1
    if d != model.num_features:
        raise ValueError(
            f"fit_resume requires the original training matrix "
            f"(num_features={model.num_features}); got shape {np.shape(X)}"
        )


def _stagewise_replay_program(base):
    """Jitted replay of a stagewise regression carry: scan the stored
    (member, weight) stack, accumulating ``pred += w * predict_fn(m, X)``
    in the exact per-round f32 order the fit used.  Bit-identity leans on
    the tree learners' routing contract: the predict re-route selects the
    same leaf values ``fit_and_direction`` contracted at fit time."""

    def build():
        def replay(members, weights, pred, X):
            def body(p, xs):
                m, w = xs
                return p + w * base.predict_fn(m, X), None

            out, _ = jax.lax.scan(body, pred, (members, weights))
            return out

        return jax.jit(replay)

    return cached_program(("gbm_reg_warm_replay", base.config_key()), build)


def _stagewise_replay_program_dims(base):
    """Class-dim variant: members are a [rounds, dim] grid, weights
    [rounds, dim]; each round adds ``w[None, :] * dirs`` with ``dirs`` the
    per-dim predict re-route — the same expression the fit's validation
    path stages (bit-identical to the train-side directions)."""

    def build():
        def replay(members, weights, pred, X):
            def body(p, xs):
                m, w = xs
                dirs = jax.vmap(lambda t: base.predict_fn(t, X))(m).T
                return p + w[None, :] * dirs, None

            out, _ = jax.lax.scan(body, pred, (members, weights))
            return out

        return jax.jit(replay)

    return cached_program(("gbm_cls_warm_replay", base.config_key()), build)


class GBMRegressionModel(RegressionModel, GBMRegressor):
    """predict = init + sum_i w_i * m_i(x)  (`GBMRegressor.scala:531-539`)."""

    def __init__(self, init_model=None, num_members=0, **kwargs):
        super().__init__(**kwargs)
        self.init_model = init_model
        self.num_members = num_members

    def predict(self, X):
        X = as_f32(X)
        out = self.init_model.predict(X)
        if self.num_members == 0:
            return out
        base = self._base()
        leaves = member_leaves(base)

        def pred(members, weights, Xq):
            return predict_chunked_rows(
                lambda Xc: jnp.einsum(
                    "m,mn->n", weights, base.predict_many_fn(members, Xc)
                ),
                Xq, weights.shape[0], leaves,
            )

        return out + self._predict_program(
            "predict", pred, (self.params["members"], self.params["weights"]), X
        )

    def take(self, k: int) -> "GBMRegressionModel":
        """Prefix model from the first k members (test harness parity with
        the reference's rebuilt `new GBMRegressionModel(take(i), ...)`)."""
        k = min(k, self.num_members)
        return GBMRegressionModel(
            params={
                "members": slice_pytree(self.params["members"], k),
                "weights": self.params["weights"][:k],
                "masks": self.params["masks"][:k],
                "init": self.params["init"],
                # the prefix model's curve is exactly the first k entries
                "val_hist": vh[:k] if (vh := self.params.get("val_hist")) is not None else None,
            },
            num_features=self.num_features,
            init_model=self.init_model,
            num_members=k,
            **self.get_params(),
        )

    def fit_resume(self, X, y, n_new_rounds, sample_weight=None):
        """Continue this fitted model for ``n_new_rounds`` more rounds on
        the SAME training data — bit-identical to a single
        ``num_members + n_new_rounds``-round fit (:meth:`take`'s
        absolute-round-index prefix contract run forward; round keys and
        feature masks derive from ``fold_in(root, i)``, so a larger
        sampling plan is prefix-stable).  The committed prediction state is
        replayed host-free from the stored members (the tree learners'
        predict re-route is bit-identical to the fit-time leaf values —
        ``fit_and_direction``'s contract), then installed as a warm-resume
        state the fresh fit consumes exactly like a loaded checkpoint.

        Scope: single-device fits without a validation split (the serving
        refresh path, docs/autopilot.md); a background refresh crash leaves
        this model untouched and the resume retryable."""
        k, n_new = int(self.num_members), int(n_new_rounds)
        _check_resume_args(self, k, n_new, X)
        X32, y32 = as_f32(X), as_f32(y)
        base = self._base().copy()
        members = self.params["members"]
        weights = jnp.asarray(self.params["weights"], jnp.float32)
        pred0 = jnp.asarray(self.init_model.predict(X32), jnp.float32)
        pred = _stagewise_replay_program(base)(members, weights, pred0, X32)
        if self.loss.lower() == "huber":
            # carry seed only: the chunk body recomputes huber's delta from
            # the carried pred before every round
            delta = weighted_quantile(y32, self.alpha)
        else:
            delta = jnp.asarray(0.0, jnp.float32)
        est = GBMRegressor(
            **{**self.get_params(), "num_base_learners": k + n_new}
        )
        est._set_warm_resume(
            k - 1,
            {
                "v": 0,
                "best": 0.0,
                "val_hist": [],
                "pred": pred,
                "pred_val": None,
                "members_layout": self.MEMBERS_LAYOUT,
                "members": members,
                "weights": weights,
                "delta": delta,
            },
        )
        return est.fit(X, y, sample_weight=sample_weight)


class GBMClassifier(_GBMParams):
    """Multiclass GBM (reference `GBMClassifier.scala`): dim regressors per
    round (class-dim vmap), K-dim box-constrained line search, raw-score
    prediction state."""

    loss = Param(
        "logloss", in_array(["logloss", "exponential", "bernoulli"]),
        doc="K-class softmax cross-entropy, or the reference's binary "
        "exponential / bernoulli losses on (-f, f) raw scores",
    )
    init_strategy = Param(
        "prior", in_array(["prior", "uniform"]),
        doc="round-0 raw scores: class-prior log-odds or zeros",
    )

    is_classifier = True

    def _make_loss(self, num_classes):
        return losses_mod.get_classification_loss(self.loss.lower(), num_classes)

    def _init_raw_scores(self, X, y, w, num_classes, dim, mesh=None):
        """Init model + round-0 raw scores (`GBMClassifier.scala:275-288`);
        ``num_classes`` is passed explicitly — the train split may be
        missing the top class (validation indicator or CV fold), and the
        init prior must still be K-dimensional.  Shared by ``fit`` and the
        megabatch sweep (models/gbm_sweep.py) so the two paths can never
        diverge on round-0 state."""
        init_dummy = DummyClassifier(strategy=self.init_strategy)
        init_model = init_dummy.fit(
            X, y, sample_weight=w, num_classes=num_classes,
            **mesh_fit_kwargs(init_dummy, mesh),
        )
        if dim == 1 and num_classes == 2 and self.init_strategy.lower() == "prior":
            # clamp BOTH sides: with explicit num_classes a train split can
            # contain zero positives (p1 == 0), and log(0) = -inf would
            # poison every raw prediction
            p1 = init_model.params["proba"][1]
            logodds = jnp.log(
                jnp.maximum(p1, 1e-30) / jnp.maximum(1.0 - p1, 1e-30)
            )
            init_raw = logodds[None]
        elif dim == 1:
            init_raw = jnp.zeros((1,), jnp.float32)
        else:
            init_raw = init_model.params["raw"]
        return init_model, init_raw

    @instrumented_fit
    def fit(
        self,
        X,
        y,
        sample_weight=None,
        validation_indicator=None,
        mesh=None,
        num_classes=None,
    ):
        """Fit; with ``mesh`` the round runs as one shard_map-ed SPMD program:
        rows sharded over "data" (psum histograms/hessians/objectives), class
        dims block-sharded over "member" with an all_gather to rejoin
        directions — the XLA replacement for the reference's executor
        treeAggregate + per-class driver Futures
        (`GBMClassifier.scala:344-355,377-411`)."""
        X = as_f32(X)
        y = as_f32(y)
        self._validate_fit_inputs(X, y)
        w_all = resolve_weights(y, sample_weight)
        # validate over the FULL label set (train + validation) so a
        # validation fold missing the top class cannot shrink the model
        num_classes = infer_num_classes(y, num_classes)
        if validation_indicator is not None:
            vi = np.asarray(validation_indicator, bool)
            X_val, y_val = X[vi], y[vi]
            X, y, w = X[~vi], y[~vi], w_all[~vi]
        else:
            X_val = y_val = None
            w = w_all
        n, d = X.shape
        instr = Instrumentation("GBMClassifier.fit")
        instr.log_params(self.get_params())
        instr.log_dataset(n, d, num_classes)
        telem = FitTelemetry.start(self, n=n, d=d, num_classes=int(num_classes))
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = make_shared_fit_ctx(base, X)
        # training-time drift reference (telemetry/quality.py): captured
        # before row sharding pads the binned ctx; host-side bincounts only
        drift_ref = drift_reference_from_ctx(ctx)
        bag_keys, masks = self._sampling_plan(n, d)
        loss = self._make_loss(num_classes)
        dim = loss.dim

        ax = None
        member_size = 1
        n_pad = n
        if mesh is not None:
            data_size, member_size = _mesh_sizes(mesh)
            ax = _mesh_row_spec(mesh)
            n_pad = n + (-n) % data_size
        # class dims round up to equal member-shard blocks; the tail block
        # holds zero-weight phantom dims whose trees fit to all-zero labels
        # (guarded leaf denominators -> 0-valued trees), are trimmed from
        # the fitted params right after each chunk, and are sliced off the
        # all_gather-ed directions BEFORE the loss/line-search ever sees
        # them — any (K, member) combination works, like the reference's
        # per-dim Futures (`GBMClassifier.scala:377-411`)
        dim_blk = dim + (-dim) % member_size

        init_model, init_raw = self._init_raw_scores(
            X, y, w, num_classes, dim, mesh=mesh
        )

        updates = self.updates.lower()
        optimized = bool(self.optimized_weights)
        lr = float(self.learning_rate)
        goss = (
            (float(self.top_rate), float(self.other_rate))
            if self.sample_method.lower() == "goss"
            else None
        )
        sub_ratio = float(self.subsample_ratio)
        repl = bool(self.replacement)
        tol = float(self.tol)
        max_iter = int(self.max_iter)
        loss_name = self.loss.lower()
        base_key = base.config_key()
        sample_plan = self._resolved_sampling(n)
        self._check_sampling_supported(sample_plan, mesh)
        samp_method = sample_plan["method"] if sample_plan else "none"
        sample_bucket = sample_plan["bucket"] if sample_plan else None
        samp_args = (sample_plan["samp"],) if sample_plan else ()
        if sample_plan is not None:
            telem.emit(
                "sampling_config",
                method=samp_method,
                top_rate=sample_plan["top_rate"],
                other_rate=sample_plan["other_rate"],
                mvs_lambda=sample_plan["mvs_lambda"],
                sampled_rows=sample_plan["sampled_rows"],
                sample_bucket=sample_bucket,
                amp=sample_plan["amp"],
            )

        y_enc = loss.encode_label(y)

        # ---- mesh: pad rows, shard row-indexed arrays over "data" --------
        if mesh is not None:
            ctx, X = shard_fit_rows(mesh, base, ctx, X, n_pad)
            y_enc = jax.device_put(
                _pad_rows(y_enc, n_pad), NamedSharding(mesh, P(ax, None))
            )
            w = jax.device_put(_pad_rows(w, n_pad), NamedSharding(mesh, P(ax)))
        pred = jnp.broadcast_to(init_raw[None, :], (n_pad, dim)).astype(jnp.float32)
        if mesh is not None:
            pred = jax.device_put(
                pred, NamedSharding(mesh, P(_mesh_row_spec(mesh), None))
            )

        with_validation = X_val is not None
        if with_validation:
            y_enc_val = loss.encode_label(y_val)

        # round math lives in the module-level factories shared with the
        # megabatch sweep (models/gbm_sweep.py); see make_cls_round_core
        def build_chunk_step():
            return jax.jit(make_cls_chunk_fn(
                base, loss, dim, updates, optimized, goss, tol, max_iter,
                with_validation,
                sampling=samp_method, sample_bucket=sample_bucket,
            ))

        def build_chunk_step_mesh():
            """Scan-chunked rounds as ONE shard_map-ed SPMD program (see
            GBMRegressor.build_chunk_step_mesh).  The validation split rides
            the same program: per-round val losses are psum-ed weighted
            means over valid (non-padding) val rows, with each member
            shard's class-dim directions all_gather-ed before the update —
            the reference's distributed per-round validation evaluation
            (`GBMRegressor.scala:444-465`)."""
            round_core = make_cls_round_core(
                base, loss, dim, updates, optimized, goss, tol, max_iter,
                ax=ax, member_size=member_size, dim_blk=dim_blk,
            )

            def chunk(ctx, X, y_enc, w, pred, pred_val, alpha_ws, X_val_a,
                      y_enc_val_a, valid_val, bag_ws, keys, masks, scales,
                      lr):
                def body(carry, xs):
                    pred, pred_val, alpha_ws = carry
                    bag_w, key, mask, scale = xs
                    params, weight, new_pred, alpha_ws = round_core(
                        ctx, X, y_enc, w, bag_w, key, mask, pred, alpha_ws,
                        scale, lr,
                    )
                    if with_validation:
                        dirs_val = jax.vmap(
                            lambda p: base.predict_fn(p, X_val_a)
                        )(params).T
                        if member_size > 1:
                            dirs_val = jax.lax.all_gather(
                                dirs_val, "member", axis=1, tiled=True
                            )[:, :dim]
                        new_pred_val = pred_val + jnp.where(
                            scale > 0, weight[None, :] * dirs_val, 0.0
                        )
                        le = jnp.reshape(
                            loss.loss(y_enc_val_a, new_pred_val), (-1,)
                        )
                        err = jax.lax.psum(jnp.sum(valid_val * le), ax) / (
                            jax.lax.psum(jnp.sum(valid_val), ax)
                        )
                    else:
                        new_pred_val = pred_val
                        err = jnp.float32(0)
                    return (new_pred, new_pred_val, alpha_ws), (params, weight, err)

                (pred, pred_val, alpha_ws), (params_all, weights_all, errs) = (
                    jax.lax.scan(
                        body, (pred, pred_val, alpha_ws),
                        (bag_ws, keys, masks, scales),
                    )
                )
                return params_all, weights_all, errs, pred, pred_val, alpha_ws

            return jax.jit(
                shard_map(
                    chunk,
                    mesh=mesh,
                    in_specs=(
                        base.ctx_specs(ctx, ax),
                        P(ax, None),  # X
                        P(ax, None),  # y_enc
                        P(ax),  # w
                        P(ax, None),  # pred
                        P(ax, None),  # pred_val
                        P(),  # alpha_ws (replicated; psum-consistent)
                        P(ax, None),  # X_val
                        P(ax, None),  # y_enc_val
                        P(ax),  # valid_val
                        P(None, ax),  # bag_ws [c, n_pad]
                        P(),  # keys [c, 2]
                        P(),  # masks [c, d]
                        P(),  # scales [c]
                        P(),  # lr
                    ),
                    out_specs=(
                        P(None, "member") if member_size > 1 else P(),
                        P(),
                        P(),
                        P(ax, None),
                        P(ax, None),
                        P(),  # alpha_ws
                    ),
                    check_vma=False,
                )
            )

        # learning_rate is a traced chunk argument, not part of the program
        # identity (see the regressor's round_key note)
        round_key = (
            "gbm_cls_round",
            loss_name,
            num_classes,
            updates,
            optimized,
            goss,
            sub_ratio,
            repl,
            tol,
            max_iter,
            base_key,
            mesh,
        )
        if sample_plan is not None:
            # rates traced, bucket static — see the regressor's note
            round_key = round_key + ("sampling", samp_method, sample_bucket)
        bag_many = self._make_bag_many_fn(n, n_pad)
        if mesh is not None:
            chunk_step = cached_program(
                round_key + ("chunk_mesh", with_validation),
                build_chunk_step_mesh,
            )
        else:
            chunk_step = cached_program(
                round_key + ("chunk", with_validation), build_chunk_step
            )

        eval_loss = cached_program(
            ("gbm_cls_eval", loss_name, num_classes),
            lambda: jax.jit(lambda pred_v, y_enc_v: jnp.mean(loss.loss(y_enc_v, pred_v))),
        )

        best = 0.0
        pred_val = None
        nv_pad = 0
        valid_val = val_dummy = jnp.zeros((0,), jnp.float32)
        val_dummy2 = jnp.zeros((0, 1), jnp.float32)
        if with_validation:
            X_val = jnp.asarray(X_val)
            pred_val = jnp.broadcast_to(
                init_raw[None, :], (X_val.shape[0], dim)
            ).astype(jnp.float32)
            best = float(eval_loss(pred_val, y_enc_val))
            nv_pad = X_val.shape[0]
            if mesh is not None:
                # shard the validation split over the row axis (per-round
                # losses come from inside the chunked SPMD program)
                nv_pad, valid_val, _, (X_val, y_enc_val, pred_val) = (
                    shard_validation_rows(
                        mesh, nv_pad, (), (X_val, y_enc_val, pred_val)
                    )
                )

        # member params/weights accumulate as round-stacked chunks
        # (leading axis = rounds), concatenated once at the end
        members_chunks: List[Any] = []
        weights_chunks: List[Any] = []
        val_history: List[float] = []
        i, v = 0, 0
        # line-search warm start, carried across rounds AND checkpoints
        # (a resume must replay the same Newton trajectory as an
        # uninterrupted fit)
        alpha_ws = jnp.ones((dim,), jnp.float32)

        # n_pad AND nv_pad in the identity: see GBMRegressor — padded
        # `pred`/`pred_val` must not be resumed under a different topology
        ckpt = self._checkpointer(n, d, num_classes, n_pad, nv_pad, telem=telem)
        resumed = ckpt.load_latest()
        warm = False
        if resumed is None:
            # warm-start resume from a served PackedModel prefix (fit_resume
            # in serving/export.py); a real checkpoint always wins
            resumed = self._take_warm_resume()
            warm = resumed is not None
        if resumed is not None:
            last_round, st = resumed
            detail = ckpt.last_load_detail or {}
            telem.emit(
                "resume_from_checkpoint",
                round=last_round + 1,
                source="warm_start" if warm else detail.get("source", "latest"),
                fallback=bool(detail.get("fallback", False)),
            )
            i, v, best = last_round + 1, int(st["v"]), float(st["best"])
            val_history[:] = [float(x) for x in np.asarray(st.get("val_hist", []))]
            if "alpha_ws" in st:
                alpha_ws = jnp.asarray(st["alpha_ws"])
            pred = jnp.asarray(st["pred"])
            if mesh is not None:
                pred = jax.device_put(
                    pred, NamedSharding(mesh, P(_mesh_row_spec(mesh), None))
                )
            pred_val = st.get("pred_val")
            if pred_val is not None:
                pred_val = jnp.asarray(pred_val)
                if mesh is not None:
                    pred_val = jax.device_put(
                        pred_val,
                        NamedSharding(mesh, P(_mesh_row_spec(mesh), None)),
                    )
            members_chunks, weights_chunks = self._resume_chunks(st)
            logger.info("GBMClassifier resuming from round %d", i)

        def save_state(round_idx, v, best):
            # gate BEFORE building the state (see GBMRegressor.save_state)
            if not ckpt.should_save(round_idx):
                return
            ckpt.save(
                round_idx,
                {
                    "v": v,
                    "best": best,
                    "val_hist": jnp.asarray(val_history, jnp.float32),
                    "pred": pred,
                    "pred_val": pred_val,
                    "alpha_ws": alpha_ws,
                    "members_layout": self.MEMBERS_LAYOUT,
                    "members": concat_pytrees(members_chunks),
                    "weights": concat_pytrees(weights_chunks),
                },
            )

        def run_chunk(sl, step_scale=1.0):
            nonlocal pred, pred_val, alpha_ws
            scales = jnp.full(
                (sl.stop - sl.start,), step_scale, jnp.float32
            )
            if mesh is not None:
                params_c, weights_c, errs, pred, pred_val_new, alpha_ws = (
                    chunk_step(
                        ctx, X, y_enc, w, pred,
                        pred_val if with_validation else val_dummy2,
                        alpha_ws,
                        X_val if with_validation else val_dummy2,
                        y_enc_val if with_validation else val_dummy2,
                        valid_val,
                        bag_many(bag_keys[sl]), bag_keys[sl], masks[sl],
                        scales, jnp.float32(lr),
                    )
                )
                if dim_blk != dim:
                    # drop the phantom tail trees: the fitted model's
                    # [round, class-dim] grid must be exactly dim wide
                    params_c = jax.tree_util.tree_map(
                        lambda x: x[:, :dim], params_c
                    )
            else:
                params_c, weights_c, errs, pred, pred_val_new, alpha_ws = (
                    chunk_step(
                        ctx, X, y_enc, w, pred,
                        pred_val if with_validation else val_dummy,
                        alpha_ws,
                        X_val if with_validation else val_dummy,
                        y_enc_val if with_validation else val_dummy,
                        bag_many(bag_keys[sl]), bag_keys[sl], masks[sl],
                        scales, jnp.float32(lr), *samp_args,
                    )
                )
            if with_validation:
                pred_val = pred_val_new
            return params_c, weights_c, errs if with_validation else None

        def snapshot():
            return pred, pred_val, alpha_ws

        def restore(snap):
            nonlocal pred, pred_val, alpha_ws
            pred, pred_val, alpha_ws = snap

        telem.phase_mark("setup")
        if (
            telem.enabled and telem.phases_enabled() and mesh is None
            and sample_plan is None
        ):
            _probe_classifier_phases(
                telem, loss, updates, base, ctx, X, y_enc, w,
                bag_many(bag_keys[:1])[0], bag_keys[0], masks[0], pred,
                alpha_ws, optimized, lr, tol, max_iter, goss,
            )
            telem.phase_mark("probe")
        i, v, best = self._drive_rounds(
            ckpt, members_chunks, weights_chunks,
            run_chunk, save_state, "GBMClassifier", i, v, best,
            val_history=val_history, telem=telem,
            guard=self._numeric_guard(telem),
            snapshot=snapshot, restore=restore, n_rows=n,
            round_cost=_round_cost(
                base, n, d, dim, sample_plan=sample_plan
            ),
            span_fields=(
                {
                    "sampling": samp_method,
                    "sample_bucket": sample_bucket,
                }
                if sample_plan
                else None
            ),
        )
        ckpt.delete()

        keep = i - v
        instr.log_outcome(rounds=i, kept_members=keep)
        all_members = concat_pytrees(members_chunks) if members_chunks else None
        all_weights = (
            jnp.concatenate(weights_chunks) if weights_chunks else None
        )
        model = GBMClassificationModel(
            params={
                "members": slice_pytree(all_members, keep) if keep > 0 else None,
                "weights": all_weights[:keep]
                if keep > 0
                else jnp.zeros((0, dim)),
                "masks": masks[:keep],
                "init_raw": init_raw,
                "val_hist": jnp.asarray(val_history, jnp.float32)
                if with_validation
                else None,
            },
            num_features=d,
            num_classes=num_classes,
            num_members=keep,
            dim=dim,
            **self.get_params(),
        )
        if drift_ref is not None:
            model.drift_ref_ = drift_ref
        telem.finish(model=model, rounds=i, kept_members=keep)
        return model

    @instrumented_fit
    def fit_streaming(self, store, y, sample_weight=None, X_val=None,
                      y_val=None, num_classes=None, mesh=None,
                      reduce="ordered"):
        """Out-of-core fit over a sealed ``ShardStore`` (data/shards.py);
        see ``GBMRegressor.fit_streaming`` — including the ``mesh``/
        ``reduce`` distributed-sweep knobs."""
        self._check_streaming_supported()
        from spark_ensemble_tpu.data.streaming import fit_streaming_classifier

        return fit_streaming_classifier(
            self, store, y, sample_weight=sample_weight,
            X_val=X_val, y_val=y_val, num_classes=num_classes,
            mesh=mesh, reduce=reduce,
        )


class GBMClassificationModel(ClassificationModel, GBMClassifier):
    """raw = init_raw + sum_ij w_ij m_ij(x); binary dim=1 raw = (-f, f)
    (`GBMClassifier.scala:567-589`); probabilities via the loss's
    raw->probability mapping (`:562-565`)."""

    def __init__(self, num_members=0, dim=1, **kwargs):
        super().__init__(**kwargs)
        self.num_members = num_members
        self.dim = dim

    def _raw_state(self, X):
        out = jnp.broadcast_to(
            self.params["init_raw"][None, :], (X.shape[0], self.dim)
        ).astype(jnp.float32)
        if self.num_members == 0:
            return out
        base = self._base()
        def raw(members, weights, Xq):
            # [R, dim] member grid flattened so the base learner's fused
            # multi-member predict covers every (round, class-dim) tree in
            # one kernel (ops/tree.py predict_forest)
            r, dim = weights.shape
            flat = jax.tree_util.tree_map(
                lambda x: x.reshape((r * dim,) + x.shape[2:]), members
            )

            def one(Xc):
                preds = base.predict_many_fn(flat, Xc).reshape(r, dim, -1)
                return jnp.einsum("md,mdn->nd", weights, preds)

            return predict_chunked_rows(one, Xq, r * dim, member_leaves(base))

        return out + self._predict_program(
            "raw", raw, (self.params["members"], self.params["weights"]), X
        )

    def predict_raw(self, X):
        X = as_f32(X)
        f = self._raw_state(X)
        if self.dim == 1 and self.num_classes == 2:
            return jnp.concatenate([-f, f], axis=1)
        return f

    def predict_proba(self, X):
        loss = self._make_loss(self.num_classes)
        return loss.raw2probability(self.predict_raw(X))

    def predict(self, X):
        # Spark computes `prediction` from the RAW column (argmax) when
        # rawPredictionCol is set — the evaluated behavior in every test
        return jnp.argmax(self.predict_raw(X), axis=-1).astype(jnp.float32)

    def member(self, i: int, dim: int = 0):
        """Round ``i``'s regressor for class dimension ``dim`` (the member
        grid is [round, class-dim], `GBMClassifier.scala:377-411`)."""
        members = self.params["members"]
        if members is None:
            raise IndexError("model kept zero members")
        # explicit bounds checks: jax clamps out-of-range integer indices
        rounds, dims = jax.tree_util.tree_leaves(members)[0].shape[:2]
        if not 0 <= i < rounds:
            raise IndexError(f"round index {i} out of range [0, {rounds})")
        if not 0 <= dim < dims:
            raise IndexError(f"class-dim index {dim} out of range [0, {dims})")
        params_i = jax.tree_util.tree_map(lambda x: x[i, dim], members)
        return self._base().model_from_params(params_i, self.num_features)

    def take(self, k: int) -> "GBMClassificationModel":
        k = min(k, self.num_members)
        return GBMClassificationModel(
            params={
                "members": slice_pytree(self.params["members"], k),
                "weights": self.params["weights"][:k],
                "masks": self.params["masks"][:k],
                "init_raw": self.params["init_raw"],
                "val_hist": vh[:k] if (vh := self.params.get("val_hist")) is not None else None,
            },
            num_features=self.num_features,
            num_classes=self.num_classes,
            num_members=k,
            dim=self.dim,
            **self.get_params(),
        )

    def fit_resume(self, X, y, n_new_rounds, sample_weight=None):
        """Continue for ``n_new_rounds`` more rounds on the SAME training
        data — the classifier analogue of
        :meth:`GBMRegressionModel.fit_resume` (see there for the contract).
        The raw-score carry replays from ``init_raw`` over the stored
        [round, class-dim] member grid; the line-search warm start is
        recovered from the last committed round's weights
        (``weights[-1] / learning_rate`` — exact whenever the learning
        rate is a power of two, including the default 1.0)."""
        k, n_new = int(self.num_members), int(n_new_rounds)
        _check_resume_args(self, k, n_new, X)
        X32 = as_f32(X)
        base = self._base().copy()
        members = self.params["members"]
        weights = jnp.asarray(self.params["weights"], jnp.float32)
        pred0 = jnp.broadcast_to(
            self.params["init_raw"][None, :], (X32.shape[0], self.dim)
        ).astype(jnp.float32)
        pred = _stagewise_replay_program_dims(base)(
            members, weights, pred0, X32
        )
        if bool(self.optimized_weights):
            # weight = lr * alpha_opt on the clean path, and the carried
            # warm start is alpha_opt itself (finite on a committed round)
            alpha_ws = weights[-1] / jnp.float32(self.learning_rate)
        else:
            alpha_ws = jnp.ones((self.dim,), jnp.float32)
        est = GBMClassifier(
            **{**self.get_params(), "num_base_learners": k + n_new}
        )
        est._set_warm_resume(
            k - 1,
            {
                "v": 0,
                "best": 0.0,
                "val_hist": [],
                "pred": pred,
                "pred_val": None,
                "members_layout": self.MEMBERS_LAYOUT,
                "members": members,
                "weights": weights,
                "alpha_ws": alpha_ws,
            },
        )
        return est.fit(
            X, y, sample_weight=sample_weight, num_classes=self.num_classes
        )
