"""Linear base learners: ridge regression (closed form) and multinomial
logistic regression (LBFGS).

Fill the roles Spark MLlib's ``LinearRegression`` / ``LogisticRegression``
play in the reference's stacking tests (stacker and base members,
`StackingClassifierSuite.scala`, `StackingRegressorSuite.scala`).  Both are
pure-functional members of the BaseLearner protocol:

- LinearRegression solves the weighted normal equations
  ``(X'WX + reg·I) beta = X'Wy`` with a Cholesky solve — one MXU-friendly
  matmul pair, no iterative loop.
- LogisticRegression minimizes weighted multinomial cross-entropy with
  ``optax.lbfgs`` inside a ``lax.while_loop`` (the JAX analogue of breeze
  LBFGS that Spark uses underneath).

Feature subspace masks multiply into X at fit *and* predict (params carry the
mask), matching the reference's slice-projection semantics
(`HasSubBag.scala:81-84`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from spark_ensemble_tpu.ops.collective import preduce
from spark_ensemble_tpu.models.base import (
    Static,
    static_value,
    BaseLearner,
    ClassificationModel,
    RegressionModel,
    as_f32,
)
from spark_ensemble_tpu.params import Param, gt_eq


def _apply_mask(X, feature_mask):
    if feature_mask is None:
        return X
    return X * feature_mask.astype(X.dtype)[None, :]


def _feature_stats(X, w, axis_name=None):
    """Weighted per-feature mean and std (std floored; constant/masked
    columns get sd=1 so they contribute nothing and stay solvable).  With
    ``axis_name`` the moments are psum-ed over the mesh data axis."""
    wsum = jnp.maximum(preduce(jnp.sum(w), axis_name), 1e-30)
    mu = preduce(jnp.sum(w[:, None] * X, axis=0), axis_name) / wsum
    var = preduce(
        jnp.sum(w[:, None] * (X - mu[None, :]) ** 2, axis=0), axis_name
    ) / wsum
    sd = jnp.sqrt(var)
    sd = jnp.where(sd > 1e-7 * (1.0 + jnp.abs(mu)), sd, 1.0)
    return mu, sd


class LinearRegression(BaseLearner):
    reg_param = Param(1e-6, gt_eq(0.0))
    fit_intercept = Param(True)

    is_classifier = False

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        X = _apply_mask(ctx, feature_mask)
        n, d = X.shape
        # standardize features (Spark LinearRegression standardizes
        # internally too); essential for f32 normal equations on raw-scale
        # data like cpusmall (feature magnitudes up to ~1e6)
        mu, sd = _feature_stats(X, w, axis_name)
        Xs = (X - mu[None, :]) / sd[None, :]
        if self.fit_intercept:
            Xs = jnp.concatenate([Xs, jnp.ones((n, 1), X.dtype)], axis=1)
        Xw = Xs * w[:, None]
        A = preduce(Xs.T @ Xw, axis_name) + (self.reg_param + 1e-6) * jnp.eye(
            Xs.shape[1], dtype=X.dtype
        )
        b = preduce(Xw.T @ y, axis_name)
        beta = jax.scipy.linalg.solve(A, b, assume_a="pos")
        coef_s = beta[:d] if self.fit_intercept else beta
        icpt_s = beta[d] if self.fit_intercept else jnp.asarray(0.0, X.dtype)
        coef = coef_s / sd
        intercept = icpt_s - jnp.sum(coef * mu)
        mask = (
            feature_mask.astype(jnp.float32)
            if feature_mask is not None
            else jnp.ones((d,), jnp.float32)
        )
        return {"coef": coef, "intercept": intercept, "mask": mask}

    def predict_fn(self, params, X):
        return (X * params["mask"][None, :]) @ params["coef"] + params["intercept"]

    def model_from_params(self, params, num_features, num_classes=None):
        return LinearRegressionModel(
            params=params, num_features=num_features, **self.get_params()
        )


class LinearRegressionModel(RegressionModel, LinearRegression):
    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))


def _lbfgs_minimize(fun, init_params, max_iter: int, tol: float):
    """Run optax LBFGS to convergence inside a ``lax.while_loop``."""
    opt = optax.lbfgs()
    value_and_grad = optax.value_and_grad_from_state(fun)

    def step(carry):
        params, state = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(
            grad, state, params, value=value, grad=grad, value_fn=fun
        )
        params = optax.apply_updates(params, updates)
        return params, state

    def cont(carry):
        _, state = carry
        i = optax.tree_utils.tree_get(state, "count")
        grad = optax.tree_utils.tree_get(state, "grad")
        err = optax.tree_utils.tree_norm(grad)
        return (i == 0) | ((i < max_iter) & (err >= tol))

    init_state = opt.init(init_params)
    params, _ = jax.lax.while_loop(cont, step, (init_params, init_state))
    return params


class LogisticRegression(BaseLearner):
    reg_param = Param(1e-6, gt_eq(0.0), doc="L2 penalty")
    fit_intercept = Param(True)
    max_iter = Param(100, gt_eq(1))
    tol = Param(1e-6, gt_eq(0.0))

    is_classifier = True

    def make_fit_ctx(self, X, num_classes=None):
        return {"X": as_f32(X), "num_classes": Static(num_classes)}

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        X = _apply_mask(ctx["X"], feature_mask)
        k = static_value(ctx["num_classes"])
        n, d = X.shape
        mu, sd = _feature_stats(X, w, axis_name)
        Xs = (X - mu[None, :]) / sd[None, :]
        onehot = jax.nn.one_hot(y.astype(jnp.int32), k)
        w_norm = w / jnp.maximum(preduce(jnp.sum(w), axis_name), 1e-30)

        def objective(theta):
            logits = Xs @ theta["coef"] + theta["intercept"][None, :]
            ce = -jnp.sum(onehot * jax.nn.log_softmax(logits, axis=-1), axis=-1)
            reg = 0.5 * self.reg_param * jnp.sum(theta["coef"] ** 2)
            return preduce(jnp.sum(w_norm * ce), axis_name) + reg

        init = {
            "coef": jnp.zeros((d, k), jnp.float32),
            "intercept": jnp.zeros((k,), jnp.float32),
        }
        theta = _lbfgs_minimize(objective, init, self.max_iter, self.tol)
        coef = theta["coef"] / sd[:, None]
        intercept = theta["intercept"] - (mu / sd) @ theta["coef"]
        if not self.fit_intercept:
            intercept = jnp.zeros((k,), jnp.float32)
        mask = (
            feature_mask.astype(jnp.float32)
            if feature_mask is not None
            else jnp.ones((d,), jnp.float32)
        )
        return {"coef": coef, "intercept": intercept, "mask": mask}

    def predict_raw_fn(self, params, X):
        return (X * params["mask"][None, :]) @ params["coef"] + params["intercept"][
            None, :
        ]

    def predict_proba_fn(self, params, X):
        return jax.nn.softmax(self.predict_raw_fn(params, X), axis=-1)

    def predict_fn(self, params, X):
        return jnp.argmax(self.predict_raw_fn(params, X), axis=-1).astype(jnp.float32)

    def model_from_params(self, params, num_features, num_classes=None):
        return LogisticRegressionModel(
            params=params,
            num_features=num_features,
            num_classes=num_classes or 2,
            **self.get_params(),
        )


class LogisticRegressionModel(ClassificationModel, LogisticRegression):
    def predict_proba(self, X):
        return self.predict_proba_fn(self.params, as_f32(X))

    def predict_raw(self, X):
        return self.predict_raw_fn(self.params, as_f32(X))

    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))
