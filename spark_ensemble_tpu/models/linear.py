"""Linear base learners: ridge regression (closed form) and multinomial
logistic regression (LBFGS).

Fill the roles Spark MLlib's ``LinearRegression`` / ``LogisticRegression``
play in the reference's stacking tests (stacker and base members,
`StackingClassifierSuite.scala`, `StackingRegressorSuite.scala`).  Both are
pure-functional members of the BaseLearner protocol:

- LinearRegression solves the weighted normal equations
  ``(X'WX + reg·I) beta = X'Wy`` with a Cholesky solve — one MXU-friendly
  matmul pair, no iterative loop.
- LogisticRegression minimizes weighted multinomial cross-entropy with
  ``optax.lbfgs`` inside a ``lax.while_loop`` (the JAX analogue of breeze
  LBFGS that Spark uses underneath).

Feature subspace masks multiply into X at fit *and* predict (params carry the
mask), matching the reference's slice-projection semantics
(`HasSubBag.scala:81-84`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from spark_ensemble_tpu.ops.collective import preduce
from spark_ensemble_tpu.models.base import (
    Static,
    static_value,
    BaseLearner,
    ClassificationModel,
    RegressionModel,
    as_f32,
)
from spark_ensemble_tpu.params import Param, gt_eq, in_array


def _apply_mask(X, feature_mask):
    if feature_mask is None:
        return X
    return X * feature_mask.astype(X.dtype)[None, :]


def _feature_stats(X, w, axis_name=None):
    """Weighted per-feature mean and std (std floored; constant/masked
    columns get sd=1 so they contribute nothing and stay solvable).  With
    ``axis_name`` the moments are psum-ed over the mesh data axis."""
    wsum = jnp.maximum(preduce(jnp.sum(w), axis_name), 1e-30)
    mu = preduce(jnp.sum(w[:, None] * X, axis=0), axis_name) / wsum
    var = preduce(
        jnp.sum(w[:, None] * (X - mu[None, :]) ** 2, axis=0), axis_name
    ) / wsum
    sd = jnp.sqrt(var)
    sd = jnp.where(sd > 1e-7 * (1.0 + jnp.abs(mu)), sd, 1.0)
    return mu, sd


class LinearRegression(BaseLearner):
    reg_param = Param(1e-6, gt_eq(0.0), doc="L2 ridge strength")
    fit_intercept = Param(True, doc="learn a bias column")

    is_classifier = False

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        X = _apply_mask(ctx, feature_mask)
        n, d = X.shape
        # standardize features (Spark LinearRegression standardizes
        # internally too); essential for f32 normal equations on raw-scale
        # data like cpusmall (feature magnitudes up to ~1e6)
        mu, sd = _feature_stats(X, w, axis_name)
        Xs = (X - mu[None, :]) / sd[None, :]
        if self.fit_intercept:
            Xs = jnp.concatenate([Xs, jnp.ones((n, 1), X.dtype)], axis=1)
        Xw = Xs * w[:, None]
        A = preduce(Xs.T @ Xw, axis_name) + (self.reg_param + 1e-6) * jnp.eye(
            Xs.shape[1], dtype=X.dtype
        )
        b = preduce(Xw.T @ y, axis_name)
        beta = jax.scipy.linalg.solve(A, b, assume_a="pos")
        coef_s = beta[:d] if self.fit_intercept else beta
        icpt_s = beta[d] if self.fit_intercept else jnp.asarray(0.0, X.dtype)
        coef = coef_s / sd
        intercept = icpt_s - jnp.sum(coef * mu)
        mask = (
            feature_mask.astype(jnp.float32)
            if feature_mask is not None
            else jnp.ones((d,), jnp.float32)
        )
        return {"coef": coef, "intercept": intercept, "mask": mask}

    def predict_fn(self, params, X):
        return (X * params["mask"][None, :]) @ params["coef"] + params["intercept"]

    def model_from_params(self, params, num_features, num_classes=None):
        return LinearRegressionModel(
            params=params, num_features=num_features, **self.get_params()
        )


class LinearRegressionModel(RegressionModel, LinearRegression):
    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))


def _lbfgs_minimize(fun, init_params, max_iter: int, tol: float):
    """Run optax LBFGS to convergence inside a ``lax.while_loop``."""
    opt = optax.lbfgs()
    value_and_grad = optax.value_and_grad_from_state(fun)

    def step(carry):
        params, state = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(
            grad, state, params, value=value, grad=grad, value_fn=fun
        )
        params = optax.apply_updates(params, updates)
        return params, state

    def cont(carry):
        _, state = carry
        i = optax.tree_utils.tree_get(state, "count")
        grad = optax.tree_utils.tree_get(state, "grad")
        # optax renamed tree_l2_norm -> tree_norm; support both spellings
        norm = getattr(
            optax.tree_utils, "tree_norm", None
        ) or optax.tree_utils.tree_l2_norm
        err = norm(grad)
        return (i == 0) | ((i < max_iter) & (err >= tol))

    init_state = opt.init(init_params)
    params, _ = jax.lax.while_loop(cont, step, (init_params, init_state))
    return params


def _damped_newton(fval, grad_step, x0, max_iter: int, tol: float):
    """Shared damped-Newton driver: Armijo backtracking, gradient-norm
    convergence, no-decrease stop.  ``grad_step(x) -> (g, step)`` supplies
    the gradient (for the stopping rule) and the Newton step."""

    def body(carry):
        x, f, it, done = carry
        g, step = grad_step(x)
        converged = jnp.linalg.norm(g) <= tol * (1.0 + jnp.abs(f))

        def bt_cond(b):
            t, fc, j = b
            return ~(fc < f) & (j < 20)

        def bt_body(b):
            t, fc, j = b
            t2 = 0.5 * t
            return (t2, fval(x + t2 * step), j + 1)

        t, fc, _ = jax.lax.while_loop(bt_cond, bt_body, (1.0, fval(x + step), 1))
        accepted = fc < f
        ok = accepted & ~converged
        return (
            jnp.where(ok, x + t * step, x),
            jnp.where(ok, fc, f),
            it + 1,
            converged | ~accepted,
        )

    def cond(carry):
        _, _, it, done = carry
        return (~done) & (it < max_iter)

    x, _, _, _ = jax.lax.while_loop(cond, body, (x0, fval(x0), 0, False))
    return x


def _solve_ridged(H, g, reg_vec):
    """Newton step from a (possibly ill-conditioned) f32 Hessian: the
    softmax over-parameterization leaves a null direction (a constant shift
    of every class's logits) and standardized rare binary columns put ~1e4
    diagonal entries next to ~0 ones — an f32 Cholesky NaNs on this, so add
    a diagonal-scaled ridge and use an LU solve (measured: full Newton
    steps, ~6 iterations to 1e-5 gradient norm on adult)."""
    dim = H.shape[0]
    ridge = 1e-5 * jnp.diag(H) + 1e-7 * jnp.trace(H) / dim
    H = H + jnp.diag(reg_vec + ridge)
    return -jnp.linalg.solve(H, g)


def _newton_multinomial(
    Xs, onehot, w_norm, reg, max_iter, tol, fit_intercept, axis_name=None
):
    """Damped Newton for weighted multinomial cross-entropy.

    The softmax-CE Hessian is exact and cheap to assemble when the parameter
    count ``d1*k`` is small (the linear-learner regime):
    ``H = sum_i w_i x_i x_i' (x) (diag(p_i) - p_i p_i')`` — one GEMM pair
    over rows.  Converges in a handful of iterations where LBFGS needs
    ~100 line-searched steps (~3-10x wall-clock on the adult stacker).
    With ``fit_intercept`` the caller appends a ones column to ``Xs`` and
    the last row of ``theta`` is the (unpenalized) intercept.
    """
    n, d1 = Xs.shape
    k = onehot.shape[1]
    red = lambda v: preduce(v, axis_name)

    if fit_intercept:
        reg_diag = jnp.concatenate(
            [jnp.full((d1 - 1,), reg, jnp.float32), jnp.zeros((1,), jnp.float32)]
        )  # no penalty on the intercept row
    else:
        reg_diag = jnp.full((d1,), reg, jnp.float32)

    if k == 2:
        # binary reduces to sigmoid logistic on d1 params (theta column 0
        # pinned at 0): 4x less Hessian work than the softmax form.  The
        # softmax optimum splits the decision vector symmetrically
        # (c1 = -c0 = beta/2), so its effective penalty on beta = c1 - c0
        # is reg/4 * |beta|^2 — match it exactly so solvers agree at any
        # reg_param
        reg_b = 0.5 * reg_diag
        y1 = onehot[:, 1]

        def fval_b(beta):
            f = Xs @ beta
            ce = jax.nn.softplus(f) - y1 * f  # -log sigmoid likelihood
            return red(jnp.sum(w_norm * ce)) + 0.5 * jnp.sum(reg_b * beta**2)

        def grad_step_b(beta):
            p1 = jax.nn.sigmoid(Xs @ beta)
            g = red(Xs.T @ (w_norm * (p1 - y1))) + reg_b * beta
            s = w_norm * p1 * (1.0 - p1)
            H = red((Xs * s[:, None]).T @ Xs)
            return g, _solve_ridged(H, g, reg_b)

        beta = _damped_newton(
            fval_b, grad_step_b, jnp.zeros((d1,), jnp.float32), max_iter, tol
        )
        # report the symmetric softmax solution so downstream
        # standardization unfolding treats both solvers identically
        return jnp.stack([-0.5 * beta, 0.5 * beta], axis=1)

    def fval(theta):
        logits = Xs @ theta
        ce = -jnp.sum(onehot * jax.nn.log_softmax(logits, axis=-1), axis=-1)
        return red(jnp.sum(w_norm * ce)) + 0.5 * jnp.sum(
            reg_diag[:, None] * theta**2
        )

    def grad_step(theta):
        p = jax.nn.softmax(Xs @ theta, axis=-1)  # [n, k]
        g = red(Xs.T @ (w_norm[:, None] * (p - onehot))) + reg_diag[:, None] * theta
        # H[(a,c),(b,e)] = sum_i w x_a x_b (d_ce p_c - p_c p_e); assembled
        # with plain GEMMs (an einsum with 4 free indices does not lower to
        # one) — contraction over rows is the only large dimension
        Xw = Xs * w_norm[:, None]
        U = (Xs[:, :, None] * p[:, None, :]).reshape(n, d1 * k)
        Uw = (Xw[:, :, None] * p[:, None, :]).reshape(n, d1 * k)
        M = (Xw.T @ U).reshape(d1, d1, k)  # [d1, d1, k] diag(c=e) part
        H = -(Uw.T @ U).reshape(d1, k, d1, k)
        ii = jnp.arange(k)
        H = H.at[:, ii, :, ii].add(jnp.moveaxis(M, 2, 0))  # [k, d1, d1] add
        H = red(H.reshape(d1 * k, d1 * k))
        reg_vec = jnp.broadcast_to(reg_diag[:, None], (d1, k)).reshape(-1)
        step = _solve_ridged(H, g.reshape(-1), reg_vec).reshape(d1, k)
        return g, step

    return _damped_newton(
        fval, grad_step, jnp.zeros((d1, k), jnp.float32), max_iter, tol
    )


# parameter-count ceiling for the exact-Hessian Newton path under
# solver="auto": above this the (d1*k)^2 Hessian assembly/solve outgrows
# its convergence advantage and LBFGS takes over
_NEWTON_MAX_PARAMS = 1024


class LogisticRegression(BaseLearner):
    reg_param = Param(1e-6, gt_eq(0.0), doc="L2 penalty")
    fit_intercept = Param(True, doc="learn a bias column")
    max_iter = Param(100, gt_eq(1), doc="solver iteration cap")
    tol = Param(1e-6, gt_eq(0.0), doc="gradient-norm convergence tolerance")
    solver = Param(
        "auto",
        in_array(["auto", "newton", "lbfgs"]),
        doc="auto | newton | lbfgs: newton assembles the exact softmax-CE "
        "Hessian (fast for small d*k, e.g. stackers); auto picks newton "
        "when (d+1)*k <= 1024",
    )

    is_classifier = True

    def make_fit_ctx(self, X, num_classes=None):
        return {"X": as_f32(X), "num_classes": Static(num_classes)}

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        X = _apply_mask(ctx["X"], feature_mask)
        k = static_value(ctx["num_classes"])
        n, d = X.shape
        fit_icpt = bool(self.fit_intercept)
        mu, sd = _feature_stats(X, w, axis_name)
        if not fit_icpt:
            # scale-only standardization: centering would smuggle an
            # implicit intercept into a no-intercept model
            mu = jnp.zeros_like(mu)
        Xs = (X - mu[None, :]) / sd[None, :]
        onehot = jax.nn.one_hot(y.astype(jnp.int32), k)
        w_norm = w / jnp.maximum(preduce(jnp.sum(w), axis_name), 1e-30)

        solver = self.solver.lower()
        if solver == "auto":
            solver = "newton" if (d + 1) * k <= _NEWTON_MAX_PARAMS else "lbfgs"
        if solver == "newton":
            if fit_icpt:
                Xn = jnp.concatenate([Xs, jnp.ones((n, 1), Xs.dtype)], axis=1)
            else:
                Xn = Xs
            th = _newton_multinomial(
                Xn, onehot, w_norm, float(self.reg_param),
                self.max_iter, self.tol, fit_icpt, axis_name=axis_name,
            )
            theta = {
                "coef": th[:d],
                "intercept": th[d] if fit_icpt else jnp.zeros((k,), jnp.float32),
            }
        else:
            icpt_scale = 1.0 if fit_icpt else 0.0

            def objective(theta):
                logits = Xs @ theta["coef"] + icpt_scale * theta["intercept"][None, :]
                ce = -jnp.sum(
                    onehot * jax.nn.log_softmax(logits, axis=-1), axis=-1
                )
                reg = 0.5 * self.reg_param * jnp.sum(theta["coef"] ** 2)
                return preduce(jnp.sum(w_norm * ce), axis_name) + reg

            init = {
                "coef": jnp.zeros((d, k), jnp.float32),
                "intercept": jnp.zeros((k,), jnp.float32),
            }
            theta = _lbfgs_minimize(objective, init, self.max_iter, self.tol)
        coef = theta["coef"] / sd[:, None]
        intercept = (
            theta["intercept"] - (mu / sd) @ theta["coef"]
            if fit_icpt
            else jnp.zeros((k,), jnp.float32)
        )
        mask = (
            feature_mask.astype(jnp.float32)
            if feature_mask is not None
            else jnp.ones((d,), jnp.float32)
        )
        return {"coef": coef, "intercept": intercept, "mask": mask}

    def predict_raw_fn(self, params, X):
        return (X * params["mask"][None, :]) @ params["coef"] + params["intercept"][
            None, :
        ]

    def predict_proba_fn(self, params, X):
        return jax.nn.softmax(self.predict_raw_fn(params, X), axis=-1)

    def predict_fn(self, params, X):
        return jnp.argmax(self.predict_raw_fn(params, X), axis=-1).astype(jnp.float32)

    def model_from_params(self, params, num_features, num_classes=None):
        return LogisticRegressionModel(
            params=params,
            num_features=num_features,
            num_classes=num_classes or 2,
            **self.get_params(),
        )


class LogisticRegressionModel(ClassificationModel, LogisticRegression):
    def predict_proba(self, X):
        return self.predict_proba_fn(self.params, as_f32(X))

    def predict_raw(self, X):
        return self.predict_raw_fn(self.params, as_f32(X))

    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))
