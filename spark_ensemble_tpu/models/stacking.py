"""Stacked generalization (reference `StackingRegressor.scala`,
`StackingClassifier.scala`).

Heterogeneous base learners are fitted as separate jit programs (a Python
loop — the analogue of the reference's parallel driver Futures at
`StackingClassifier.scala:174-186`; each fit is itself a fully-compiled XLA
program, and XLA overlaps dispatch).  Meta-features are assembled on device:

- regression: the vector of base predictions (`StackingRegressor.scala:155-163`)
- classification, by ``stack_method`` (`StackingClassifier.scala:60-74,190-202`):
  ``class`` -> member predicted class (1 column per member),
  ``raw`` -> member raw scores (K columns per member),
  ``proba`` -> member probabilities (K columns per member).

The stacker (meta-learner) trains on the meta-feature matrix; prediction
routes a fresh meta-feature row through the stacker
(`StackingClassifier.scala:260-270`).  Base learners that don't support
sample weights get them dropped with a warning
(`StackingClassifier.scala:147-150`).
"""

from __future__ import annotations

import logging
import time
from typing import List

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.models.base import (
    BaseLearner,
    ClassificationModel,
    Estimator,
    Model,
    RegressionModel,
    as_f32,
    infer_num_classes,
    mesh_fit_kwargs,
    resolve_weights,
)
from spark_ensemble_tpu.models.linear import LinearRegression, LogisticRegression
from spark_ensemble_tpu.models.tree import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)
from spark_ensemble_tpu.params import Param, in_array
from spark_ensemble_tpu.telemetry.events import FitTelemetry
from spark_ensemble_tpu.utils.instrumentation import (
    block_on_arrays,
    instrumented_fit,
)

logger = logging.getLogger(__name__)


class _StackingParams(Estimator):
    """Reference `StackingParams.scala:22-27`."""

    base_learners = Param(
        None, is_estimator=True,
        doc="heterogeneous level-0 learner list (each fitted on the full "
        "training split); defaults per task in fit()",
    )
    stacker = Param(
        None, is_estimator=True,
        doc="level-1 meta-learner fitted on the members' outputs; "
        "defaults to a linear/logistic model",
    )
    parallelism = Param(
        1,
        doc="max concurrent base-learner fits — the analogue of the "
        "reference's driver thread-pool Futures "
        "(`StackingClassifier.scala:174-186`); heterogeneous members "
        "trace/compile in parallel threads and XLA overlaps their "
        "device programs",
    )
    seed = Param(0, doc="PRNG seed (member fits are deterministic)")

    def _fit_bases(
        self, bases, X, y, w, sample_weight, num_classes=None, mesh=None,
        telem=None,
    ):
        """Fit the heterogeneous base learners, concurrently when
        ``parallelism > 1`` (order-preserving).

        With ``mesh``, member fits round-robin across the mesh's devices
        (member i on device i mod n): each fit's arrays land and its
        programs execute on its own chip, so heterogeneous members train
        simultaneously on different devices — the TPU mapping of the
        reference scheduling member fits as concurrent cluster jobs from
        driver Futures (`StackingClassifier.scala:174-186`).  Combine with
        ``parallelism > 1`` so dispatch threads overlap the per-device
        executions; without it devices still pipeline dispatch-by-dispatch.
        """
        from spark_ensemble_tpu.robustness.chaos import controller
        from spark_ensemble_tpu.robustness.retry import retry_call

        ctl = controller()
        retry_policy = self._retry_policy()
        label = type(self).__name__
        # only THIS process's devices are bindable via jax.default_device;
        # on a multi-host pod each host round-robins over its own slice of
        # the mesh (the fits themselves are single-device programs)
        devices = (
            [
                d
                for d in mesh.devices.flat
                if d.process_index == jax.process_index()
            ]
            if mesh is not None
            else [None]
        ) or [None]

        def fit_one(job):
            idx, base, device = job
            sw = w if base.supports_weight else None
            if not base.supports_weight and sample_weight is not None:
                logger.warning(
                    "base learner %s does not support weights; ignoring",
                    type(base).__name__,
                )

            def run():
                if num_classes is not None and base.is_classifier:
                    return base.fit(
                        X, y, sample_weight=sw, num_classes=num_classes
                    )
                return base.fit(X, y, sample_weight=sw)

            site = f"{label}:member:{idx}"

            def attempt():
                ctl.transient(site)
                return run()

            def guarded_run():
                # per-member transient-fault surface: one member's device
                # dying must not kill the other concurrent member fits
                return retry_call(
                    attempt, policy=retry_policy,
                    op=f"{label}.member_fit", telem=telem,
                )

            t0 = time.perf_counter()
            if device is None:
                model = guarded_run()
            else:
                # jax.default_device is thread-local: every array this fit
                # creates (and thus every program it dispatches) binds to
                # this member's device
                with jax.default_device(device):
                    model = guarded_run()
            if getattr(model, "params", None) is not None:
                model.params = ctl.poison_tree(site, model.params)
            if telem is not None and telem.enabled:
                # fence before stamping: the member fit returns with work
                # still in flight (with parallelism>1 member durations
                # overlap in wall time — see docs/telemetry.md)
                block_on_arrays(model)
                telem.member_fit(
                    idx, time.perf_counter() - t0,
                    family=type(base).__name__,
                )
            return model

        jobs = [
            (i, b, devices[i % len(devices)]) for i, b in enumerate(bases)
        ]
        par = int(self.parallelism or 1)
        if par > 1 and len(bases) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=min(par, len(bases))) as ex:
                return list(ex.map(fit_one, jobs))
        return [fit_one(j) for j in jobs]

    def _drop_bad_base_models(self, models, guard):
        """Apply ``on_nonfinite`` to the fitted level-0 members: a member
        whose params picked up NaN is dropped (the stacker then trains on
        the surviving members' meta-features only — the model's prediction
        path uses the same member list, so layouts stay consistent).
        ``stop_early`` keeps the prefix before the first bad member;
        ``skip_round``/``halve_step`` keep every finite member; at least
        one member must survive."""
        if guard is None or not guard.active:
            return models
        from spark_ensemble_tpu.robustness.guards import tree_any_nan

        bad = [
            i for i, m in enumerate(models)
            if tree_any_nan(getattr(m, "params", None))
        ]
        if not bad:
            return models
        first = bad[0]
        if guard.policy == "raise":
            guard.raise_error(first, what="base model params")
        if guard.policy == "stop_early":
            kept = models[:first]
            action = "stop_early"
        else:
            bad_set = set(bad)
            kept = [m for i, m in enumerate(models) if i not in bad_set]
            action = "skip_round"
        if not kept:
            guard.raise_error(first, what="every base model's params")
        guard.record(
            first, action,
            members_dropped=len(models) - len(kept),
            members_kept=len(kept),
        )
        return kept

    def _check_stacker(self, stack_model, n_members, guard):
        """The level-1 meta-learner has no drop/skip fallback — a non-finite
        stacker is always fatal when the guard is active (every prediction
        routes through it)."""
        if guard is None or not guard.active:
            return
        from spark_ensemble_tpu.robustness.guards import tree_any_nan

        if tree_any_nan(getattr(stack_model, "params", None)):
            guard.raise_error(n_members, what="stacker params")


class StackingRegressor(_StackingParams):
    is_classifier = False

    def _bases(self) -> List[BaseLearner]:
        return list(self.base_learners or [DecisionTreeRegressor(), LinearRegression()])

    def _stacker(self) -> BaseLearner:
        return self.stacker or LinearRegression()

    @instrumented_fit
    def fit(self, X, y, sample_weight=None, mesh=None) -> "StackingRegressionModel":
        """Fit; with ``mesh`` heterogeneous member fits are placed
        round-robin on the mesh's devices (see ``_fit_bases``)."""
        X, y = as_f32(X), as_f32(y)
        self._validate_fit_inputs(X, y)
        w = resolve_weights(y, sample_weight)
        telem = FitTelemetry.start(self, n=X.shape[0], d=X.shape[1])
        telem.phase_mark("setup")
        guard = self._numeric_guard(telem)
        models = self._fit_bases(
            self._bases(), X, y, w, sample_weight, mesh=mesh, telem=telem
        )
        models = self._drop_bad_base_models(models, guard)
        meta = jnp.stack([m.predict(X) for m in models], axis=1)  # [n, num_bases]
        stacker = self._stacker()
        from spark_ensemble_tpu.robustness.chaos import controller
        from spark_ensemble_tpu.robustness.retry import retry_call

        ctl = controller()
        site = f"{type(self).__name__}:stacker"

        def fit_stacker():
            ctl.transient(site)
            return stacker.fit(
                meta, y, sample_weight=w, **mesh_fit_kwargs(stacker, mesh)
            )

        stack_model = retry_call(
            fit_stacker, policy=self._retry_policy(),
            op=f"{type(self).__name__}.stacker_fit", telem=telem,
        )
        self._check_stacker(stack_model, len(models), guard)
        if telem.enabled:
            block_on_arrays(stack_model)
            telem.phase_mark("stacker")
        model = StackingRegressionModel(
            base_models=models,
            stack_model=stack_model,
            num_features=X.shape[1],
            **self.get_params(),
        )
        telem.finish(model=model, members=len(models))
        return model


class StackingRegressionModel(RegressionModel, StackingRegressor):
    def __init__(self, base_models=None, stack_model=None, **kwargs):
        super().__init__(**kwargs)
        self.base_models = base_models or []
        self.stack_model = stack_model

    def predict(self, X):
        X = as_f32(X)
        meta = jnp.stack([m.predict(X) for m in self.base_models], axis=1)
        return self.stack_model.predict(meta)


class StackingClassifier(_StackingParams):
    stack_method = Param(
        "class", in_array(["class", "raw", "proba"]),
        doc="meta-features fed to the stacker: predicted classes, raw "
        "scores, or class probabilities (reference StackingParams)",
    )

    is_classifier = True

    def _bases(self) -> List[BaseLearner]:
        return list(
            self.base_learners or [DecisionTreeClassifier(), LogisticRegression()]
        )

    def _stacker(self) -> BaseLearner:
        return self.stacker or LogisticRegression()

    def _meta_features(self, models: List[Model], X) -> jax.Array:
        method = self.stack_method.lower()
        cols = []
        for m in models:
            if method == "raw":
                cols.append(m.predict_raw(X))
            elif method == "proba":
                cols.append(m.predict_proba(X))
            else:
                cols.append(m.predict(X)[:, None])
        return jnp.concatenate(cols, axis=1)

    @instrumented_fit
    def fit(
        self, X, y, sample_weight=None, num_classes=None, mesh=None
    ) -> "StackingClassificationModel":
        """Fit; with ``mesh`` heterogeneous member fits are placed
        round-robin on the mesh's devices (see ``_fit_bases``)."""
        X, y = as_f32(X), as_f32(y)
        self._validate_fit_inputs(X, y)
        w = resolve_weights(y, sample_weight)
        num_classes = infer_num_classes(y, num_classes)
        telem = FitTelemetry.start(
            self, n=X.shape[0], d=X.shape[1], num_classes=int(num_classes)
        )
        telem.phase_mark("setup")
        guard = self._numeric_guard(telem)
        models = self._fit_bases(
            self._bases(), X, y, w, sample_weight, num_classes=num_classes,
            mesh=mesh, telem=telem,
        )
        models = self._drop_bad_base_models(models, guard)
        meta = self._meta_features(models, X)
        stacker = self._stacker()
        kw = mesh_fit_kwargs(stacker, mesh)
        from spark_ensemble_tpu.robustness.chaos import controller
        from spark_ensemble_tpu.robustness.retry import retry_call

        ctl = controller()
        site = f"{type(self).__name__}:stacker"

        def fit_stacker():
            ctl.transient(site)
            if stacker.is_classifier:
                return stacker.fit(
                    meta, y, sample_weight=w, num_classes=num_classes, **kw
                )
            return stacker.fit(meta, y, sample_weight=w, **kw)

        stack_model = retry_call(
            fit_stacker, policy=self._retry_policy(),
            op=f"{type(self).__name__}.stacker_fit", telem=telem,
        )
        self._check_stacker(stack_model, len(models), guard)
        if telem.enabled:
            block_on_arrays(stack_model)
            telem.phase_mark("stacker")
        model = StackingClassificationModel(
            base_models=models,
            stack_model=stack_model,
            num_features=X.shape[1],
            num_classes=num_classes,
            **self.get_params(),
        )
        telem.finish(model=model, members=len(models))
        return model


class StackingClassificationModel(ClassificationModel, StackingClassifier):
    def __init__(self, base_models=None, stack_model=None, **kwargs):
        super().__init__(**kwargs)
        self.base_models = base_models or []
        self.stack_model = stack_model

    def predict_raw(self, X):
        meta = self._meta_features(self.base_models, as_f32(X))
        return self.stack_model.predict_raw(meta)

    def predict_proba(self, X):
        meta = self._meta_features(self.base_models, as_f32(X))
        return self.stack_model.predict_proba(meta)

    def predict(self, X):
        return jnp.argmax(self.predict_raw(X), axis=-1).astype(jnp.float32)
