"""Linear-leaf regression trees: piece-wise linear base learner.

"Gradient Boosting With Piece-Wise Linear Regression Trees" (Shi et al.,
arXiv:1802.05640, PAPERS.md): constant leaves force many boosting rounds
to express smooth trends; fitting a small ridge regression IN each leaf
captures them directly, so GBM needs far fewer rounds for the same loss.
The reference has no such learner — it is an extension the TPU mapping
makes nearly free, because every step is an MXU contraction:

1. fit the histogram tree exactly as ``DecisionTreeRegressor`` does
   (`ops/tree.py fit_tree` — same splits, same distributed psum story);
2. route rows to leaves with the exact one-hot matmul
   (`ops.tree.leaf_one_hot`);
3. accumulate EVERY leaf's weighted normal equations in two einsum
   contractions (``[leaves, d+1, d+1]`` and ``[leaves, d+1]``; psum-ed
   over the mesh data axis under SPMD), and solve them as one batched
   Cholesky — there is no per-leaf loop anywhere;
4. leaves with too little weight to determine a d+1-parameter model fall
   back to the tree's constant leaf value.

Prediction: leaf one-hot selects the row's coefficient vector (one-term
exact matmul), then a dot with the standardized features.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.models.base import (
    BaseLearner,
    RegressionModel,
    as_f32,
)
from spark_ensemble_tpu.models.linear import _apply_mask, _feature_stats
from spark_ensemble_tpu.models.tree import DecisionTreeRegressor
from spark_ensemble_tpu.ops.collective import preduce
from spark_ensemble_tpu.ops.linesearch import chol_solve_psd
from spark_ensemble_tpu.ops.tree import (
    _F32_MAX,
    Tree,
    feature_gains,
    leaf_one_hot,
    leaf_one_hot_forest,
    predict_chunked_rows,
)
from spark_ensemble_tpu.params import Param, gt_eq, in_range


class LinearTreeRegressor(DecisionTreeRegressor):
    """Histogram tree with ridge-regression leaves (regressor only — GBM
    members are regressors, `GBMParams.scala:29-44`)."""

    reg_param = Param(1e-3, gt_eq(0.0), doc="leaf ridge strength")
    min_leaf_weight = Param(
        8.0,
        gt_eq(0.0),
        doc="minimum EFFECTIVE row support for a linear leaf: leaves whose "
        "weight is below min_leaf_weight times the mean positive row "
        "weight keep the constant tree value (a d+1-parameter model needs "
        "that much support).  Relative to the mean weight so normalized "
        "weight vectors (boosting's w/sum(w)) behave like unit weights",
    )
    # the leaf one-hot materializes [n, 2^depth] and the path matrix grows
    # 4^depth (ops.tree leaf_one_hot); cap at the matmul-predict depth
    max_depth = Param(
        5, in_range(1, 10),
        doc="tree depth (shallower cap than constant-leaf trees: every "
        "leaf carries a d+1-dim ridge model)",
    )

    def make_fit_ctx(self, X, num_classes=None):
        ctx = super().make_fit_ctx(X, num_classes)
        ctx["X"] = as_f32(X)  # raw features for the leaf models
        return ctx

    def ctx_gather_rows(self, ctx, idx):
        """Leaf ridge solves read the raw rows too — gather both matrices."""
        return {**super().ctx_gather_rows(ctx, idx), "X": ctx["X"][idx]}

    def ctx_specs(self, ctx, data_axis):
        from jax.sharding import PartitionSpec as P

        specs = super().ctx_specs(ctx, data_axis)
        specs["X"] = P(data_axis, None)
        return specs

    def _leaf_models(self, ctx, tree: Tree, y, w, feature_mask, axis_name):
        """The leaf-regression stage on a fitted constant-leaf tree."""
        X = _apply_mask(ctx["X"], feature_mask)
        n, d = X.shape
        mu, sd = _feature_stats(X, w, axis_name)
        Xs = jnp.concatenate(
            [(X - mu[None, :]) / sd[None, :], jnp.ones((n, 1), X.dtype)],
            axis=1,
        )  # [n, d+1]
        oh = leaf_one_hot(tree, ctx["Xb"], binned=True)  # [n, leaves] exact
        Xw = Xs * w[:, None]
        # every leaf's normal equations in two contractions (psum-ed); the
        # batched Cholesky's inputs must not round to bf16 on TPU, so the
        # statistics side runs at HIGHEST (the one-hot operand is exact at
        # any precision, but 3-operand einsums take a single setting)
        A = preduce(
            jnp.einsum(
                "nl,nd,ne->lde", oh, Xw, Xs,
                precision=jax.lax.Precision.HIGHEST,
            ),
            axis_name,
        )
        b = preduce(
            jnp.einsum(
                "nl,nd,n->ld", oh, Xw, y,
                precision=jax.lax.Precision.HIGHEST,
            ),
            axis_name,
        )
        leaf_w = preduce(
            jnp.einsum(
                "nl,n->l", oh, w,
                precision=(jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST),
            ),
            axis_name,
        )
        # penalize SLOPES only: an unpenalized intercept means a feature
        # that is constant WITHIN a leaf (collinear with the bias column)
        # gets slope exactly 0 instead of an arbitrary bias/slope split
        # that explodes under extrapolation
        ridge = jnp.diag(
            jnp.concatenate(
                [
                    jnp.full((d,), self.reg_param + 1e-6, X.dtype),
                    jnp.asarray([1e-8], X.dtype),
                ]
            )
        )
        # hand-rolled SPD solve (ops/linesearch.py): LAPACK's batched
        # Cholesky is not bit-stable under vmap, and GBM's piecewise-linear
        # leaves (leaf_model="linear") run this solve inside vmapped /
        # scan-chunked round programs where lane-independence is load-bearing
        beta = jax.vmap(
            lambda Ai, bi: chol_solve_psd(Ai + ridge, bi)
        )(A, b)  # [leaves, d+1]
        # underdetermined leaves keep the constant tree value; the support
        # bar is in EFFECTIVE rows (weight / mean positive weight), so a
        # normalized weight vector (boosting's w/sum(w)) behaves exactly
        # like unit weights
        present = (w > 0).astype(jnp.float32)
        n_present = jnp.maximum(preduce(jnp.sum(present), axis_name), 1.0)
        w_bar = preduce(jnp.sum(w), axis_name) / n_present
        const = jnp.concatenate(
            [
                jnp.zeros((tree.leaf_value.shape[0], d), X.dtype),
                tree.leaf_value[:, :1],
            ],
            axis=1,
        )
        # STRICT inequality: with min_leaf_weight=0 a training-empty leaf
        # (leaf_w == 0) must still fall back to the tree's parent-fallback
        # value, not to an all-zero solve
        ok = (leaf_w > self.min_leaf_weight * w_bar)[:, None]
        beta = jnp.where(ok & jnp.isfinite(beta).all(1, keepdims=True), beta, const)
        mask = (
            feature_mask.astype(jnp.float32)
            if feature_mask is not None
            else jnp.ones((d,), jnp.float32)
        )
        return {
            "tree": tree,
            "beta": beta,
            "x_mu": mu,
            "x_sd": sd,
            "mask": mask,
        }

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        tree: Tree = super().fit_from_ctx(
            ctx, y, w, feature_mask, key, axis_name=axis_name
        )
        return self._leaf_models(ctx, tree, y, w, feature_mask, axis_name)

    # the _TreeLearner leaf-reuse shortcuts return a bare Tree with
    # CONSTANT-leaf directions — wrong params type and wrong predictions
    # for linear leaves; keep the generic fit-then-predict compose
    fit_and_direction = BaseLearner.fit_and_direction
    fit_many_and_directions = BaseLearner.fit_many_and_directions

    def fit_many_from_ctx(self, ctx, ys, ws, feature_masks, keys, axis_name=None):
        """Member fits keep the FUSED forest histogram build (one matmul per
        level for every member, `_TreeLearner.fit_many_from_ctx`); only the
        cheap leaf-regression stage — two einsums and a batched Cholesky
        per member — runs vmapped on top."""
        trees = super().fit_many_from_ctx(
            ctx, ys, ws, feature_masks, keys, axis_name=axis_name
        )
        M = ys.shape[1]
        if feature_masks is None:
            return jax.vmap(
                lambda tree, y, w: self._leaf_models(
                    ctx, tree, y, w, None, axis_name
                ),
                in_axes=(0, 1, 1),
            )(trees, ys, ws)
        if feature_masks.ndim == 1:
            feature_masks = jnp.broadcast_to(
                feature_masks[None, :], (M,) + feature_masks.shape
            )
        return jax.vmap(
            lambda tree, y, w, m: self._leaf_models(
                ctx, tree, y, w, m, axis_name
            ),
            in_axes=(0, 1, 1, 0),
        )(trees, ys, ws, feature_masks)

    def predict_fn(self, params, X):
        X = as_f32(X)
        # rows with any non-finite feature take the tree's CONSTANT leaf
        # value — the predict_tree contract; a clamped 3e38 would still
        # explode through the linear term
        finite_row = jnp.isfinite(X).all(axis=1)
        Xc = jnp.nan_to_num(
            X, nan=_F32_MAX, posinf=_F32_MAX, neginf=-_F32_MAX
        )
        Xm = _apply_mask(Xc, params["mask"])
        oh = leaf_one_hot(params["tree"], Xm, binned=False)
        # one-term exact selection of each row's coefficients
        beta_row = jax.lax.dot_general(
            oh,
            params["beta"],
            (((1,), (0,)), ((), ())),
            precision=(jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST),
        )  # [n, d+1]
        Xs = (Xm - params["x_mu"][None, :]) / params["x_sd"][None, :]
        lin = jnp.sum(Xs * beta_row[:, :-1], axis=1) + beta_row[:, -1]
        # keep the selected constants exact: one-hot side single-pass, value
        # side HIGHEST — same discipline as beta_row / _predict_dense
        const = jax.lax.dot_general(
            oh,
            params["tree"].leaf_value[:, 0],
            (((1,), (0,)), ((), ())),
            precision=(jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST),
        )
        return jnp.where(finite_row, lin, const)

    def predict_many_fn(self, params, X):
        """Fused member predict: ONE column-select matmul routes every
        member (``leaf_one_hot_forest``); only the small per-member linear
        term remains batched elementwise — vmapping ``predict_fn`` would
        re-stream X per member (the pattern ``predict_forest`` documents as
        bandwidth-bound)."""
        X = as_f32(X)
        M = params["tree"].split_feature.shape[0]
        L = params["tree"].leaf_value.shape[1]

        def rows(Xr):
            finite_row = jnp.isfinite(Xr).all(axis=1)  # [n]
            Xc = jnp.nan_to_num(
                Xr, nan=_F32_MAX, posinf=_F32_MAX, neginf=-_F32_MAX
            )
            oh = leaf_one_hot_forest(
                params["tree"], Xc, binned=False
            )  # [n,M,L]
            beta_row = jnp.einsum(
                "nml,mlD->nmD",
                oh,
                params["beta"],
                precision=(
                    jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST
                ),
            )  # [n, M, d+1]
            Xs = (
                Xc[:, None, :] * params["mask"][None, :, :]
                - params["x_mu"][None, :, :]
            ) / params["x_sd"][None, :, :]  # [n, M, d]
            lin = (
                jnp.sum(Xs * beta_row[:, :, :-1], axis=-1)
                + beta_row[:, :, -1]
            )
            const = jnp.einsum(
                "nml,ml->nm",
                oh,
                params["tree"].leaf_value[:, :, 0],
                precision=(
                    jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST
                ),
            )
            return jnp.where(finite_row[:, None], lin, const)  # [n, M]

        # row-chunked past the one-hot budget (see ops/tree.py
        # predict_chunked_rows; same guard as predict_forest)
        return predict_chunked_rows(rows, X, M, L).T  # [M, n]

    def feature_gains_fn(self, params, d: int):
        # importances come from the tree's split gains (the leaf models
        # refine within leaves; they do not re-rank features)
        return feature_gains(params["tree"], d)

    def model_from_params(self, params, num_features, num_classes=None):
        return LinearTreeRegressionModel(
            params=params, num_features=num_features, **self.get_params()
        )


class LinearTreeRegressionModel(RegressionModel, LinearTreeRegressor):
    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))
