"""AdaBoost meta-estimators: SAMME / SAMME.R classification, Drucker R2
regression.

Re-designs `BoostingClassifier.scala:135-282` and
`BoostingRegressor.scala:173-282`.  The sequential reweighting loop stays on
the host (data-dependent aborts), but each round — weight normalization,
weighted base fit, error/loss computation, estimator-weight formula, sample
reweighting — is ONE jitted XLA program; the boosting weight vector lives on
device across rounds (the reference carries it as an RDD with
``treeReduce`` sums and periodic lineage checkpoints, all unnecessary here).

Formula parity:
- SAMME ("discrete"): err = sum(w_norm * 1[miss]); beta =
  err / ((1-err)(K-1)); estimator weight log(1/beta) (1.0 if beta == 0);
  w <- w_norm * (1/beta)^miss; abort-and-drop round if err >= 1 - 1/K
  (`BoostingClassifier.scala:231-260`).
- SAMME.R ("real"): estimator weight 1.0; w <- w_norm *
  exp(-((K-1)/K) * sum_c code_c * log(max(p_c, EPS))), code_c = 1 for the
  true class else -1/(K-1), EPS = 2^-52 (`BoostingClassifier.scala:198-230`).
- Drucker R2: err_i = |y_i - pred_i| / maxError; loss shaping
  exponential (1 - e^-e) | linear | squared; estErr = sum(w_norm * loss);
  stop at estErr >= 0.5 (model dropped — the reference's dead `best = i - 1`
  shows the intent) or maxError == 0 (model kept, weight 1.0);
  beta = estErr/(1-estErr); w <- w_norm * beta^(1-loss)
  (`BoostingRegressor.scala:97-106,208-260`).

Prediction:
- discrete raw: +weight for the member's predicted class, -weight/(K-1)
  elsewhere (`BoostingClassifier.scala:366-382`);
- real raw: sum over members of (K-1) * (log p - mean_c log p)
  (`:348-364`); probability = softmax(raw / (K-1)) (`:342-346`);
- regression: weighted median (default) or weighted mean over members
  (`BoostingRegressor.scala:333-347`).
"""

from __future__ import annotations

import logging
from typing import Any, List

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.models.base import (
    BaseLearner,
    CheckpointableParams,
    ClassificationModel,
    Estimator,
    RegressionModel,
    as_f32,
    cached_program,
    infer_num_classes,
    resolve_weights,
)
from spark_ensemble_tpu.models.gbm import slice_pytree, stack_pytrees
from spark_ensemble_tpu.models.tree import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)
from spark_ensemble_tpu.params import Param, gt_eq, in_array
from spark_ensemble_tpu.utils.instrumentation import (
    Instrumentation,
    instrumented_fit,
)
from spark_ensemble_tpu.utils.quantile import weighted_median

logger = logging.getLogger(__name__)

EPSILON = 2.220446049250313e-16  # Spark MLUtils.EPSILON (double ulp of 1.0)


class _BoostingParams(CheckpointableParams, Estimator):
    """Reference `BoostingParams.scala:26-37`."""

    base_learner = Param(None, is_estimator=True)
    num_base_learners = Param(10, gt_eq(1))
    checkpoint_interval = Param(10, gt_eq(1))
    checkpoint_dir = Param(
        None,
        doc="when set, training state (round, members, boosting weights) is "
        "checkpointed every checkpoint_interval rounds and fit() resumes "
        "from the latest checkpoint — the TPU upgrade of the reference's "
        "lineage-only PeriodicRDDCheckpointer (`BoostingRegressor.scala:"
        "202-206`, SURVEY.md §5)",
    )
    aggregation_depth = Param(2, gt_eq(1), doc="API parity; reductions are psum")
    seed = Param(0)


class BoostingClassifier(_BoostingParams):
    algorithm = Param("discrete", in_array(["discrete", "real"]))

    is_classifier = True

    def _base(self) -> BaseLearner:
        return self.base_learner or DecisionTreeClassifier()

    @instrumented_fit
    def fit(
        self, X, y, sample_weight=None, num_classes=None
    ) -> "BoostingClassificationModel":
        X, y = as_f32(X), as_f32(y)
        w = resolve_weights(y, sample_weight)
        num_classes = infer_num_classes(y, num_classes)
        n, d = X.shape
        instr = Instrumentation("BoostingClassifier.fit")
        instr.log_params(self.get_params())
        instr.log_dataset(n, d, num_classes)
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = base.make_fit_ctx(X, num_classes)
        algorithm = self.algorithm.lower()
        k = num_classes
        root = jax.random.PRNGKey(self.seed)

        def build_step():
            def round_discrete(ctx, X, y, bw, key):
                w_norm = bw / jnp.maximum(jnp.sum(bw), 1e-30)
                params = base.fit_from_ctx(ctx, y, w_norm, None, key)
                miss = (base.predict_fn(params, X) != y).astype(jnp.float32)
                err = jnp.sum(w_norm * miss)
                beta = err / jnp.maximum((1.0 - err) * (k - 1.0), 1e-30)
                est_weight = jnp.where(
                    beta == 0.0, 1.0, jnp.log(1.0 / jnp.maximum(beta, 1e-300))
                )
                new_bw = w_norm * jnp.power(1.0 / jnp.maximum(beta, 1e-300), miss)
                return params, err, est_weight, new_bw

            def round_real(ctx, X, y, bw, key):
                w_norm = bw / jnp.maximum(jnp.sum(bw), 1e-30)
                params = base.fit_from_ctx(ctx, y, w_norm, None, key)
                proba = base.predict_proba_fn(params, X)  # [n, k]
                miss = (jnp.argmax(proba, axis=-1) != y.astype(jnp.int32)).astype(
                    jnp.float32
                )
                err = jnp.sum(w_norm * miss)
                codes = jnp.where(
                    jax.nn.one_hot(y.astype(jnp.int32), k) > 0, 1.0, -1.0 / (k - 1.0)
                )
                ll = jnp.sum(codes * jnp.log(jnp.maximum(proba, EPSILON)), axis=-1)
                new_bw = w_norm * jnp.exp(-((k - 1.0) / k) * ll)
                return params, err, jnp.asarray(1.0, jnp.float32), new_bw

            return jax.jit(round_real if algorithm == "real" else round_discrete)

        step = cached_program(
            ("boosting_cls_round", algorithm, k, base.config_key()), build_step
        )

        bw = w
        members: List[Any] = []
        est_weights: List[float] = []
        i = 0
        ckpt = self._checkpointer(n, d, num_classes)
        resumed = ckpt.load_latest()
        if resumed is not None:
            last_round, st = resumed
            i = last_round + 1
            bw = jnp.asarray(st["bw"])
            members = list(st["members"])
            est_weights = [float(x) for x in st["est_weights"]]
            logger.info("BoostingClassifier resuming from round %d", i)
        while i < self.num_base_learners and float(jnp.sum(bw)) > 0:
            params, err, est_weight, new_bw = step(
                ctx, X, y, bw, jax.random.fold_in(root, i)
            )
            err = float(err)
            if algorithm == "discrete" and err >= 1.0 - 1.0 / k:
                # abort round, drop model (`BoostingClassifier.scala:252`)
                logger.info("BoostingClassifier round %d aborted: err=%.4f", i, err)
                break
            members.append(params)
            est_weights.append(float(est_weight))
            bw = new_bw
            logger.info("BoostingClassifier round %d: err=%.4f", i, err)
            if err <= 0:
                break
            ckpt.maybe_save(
                i, {"bw": bw, "members": members, "est_weights": list(est_weights)}
            )
            i += 1
        ckpt.delete()
        instr.log_outcome(members=len(members))
        return BoostingClassificationModel(
            params={
                "members": stack_pytrees(members) if members else None,
                "weights": jnp.asarray(est_weights, jnp.float32),
            },
            num_features=d,
            num_classes=num_classes,
            num_members=len(members),
            **self.get_params(),
        )


class BoostingClassificationModel(ClassificationModel, BoostingClassifier):
    def __init__(self, num_members=0, **kwargs):
        super().__init__(**kwargs)
        self.num_members = num_members

    def predict_raw(self, X):
        base = self._base()
        k = self.num_classes
        if self.num_members == 0:
            # reference predictRaw over zero models: zero raw vector
            return jnp.zeros((as_f32(X).shape[0], k), jnp.float32)
        if self.algorithm.lower() == "real":

            def raw_real(members, weights, Xq):
                probas = jax.vmap(lambda p: base.predict_proba_fn(p, Xq))(members)
                logp = jnp.log(jnp.maximum(probas, EPSILON))
                decisions = logp - jnp.mean(logp, axis=-1, keepdims=True)
                return (k - 1.0) * jnp.sum(decisions, axis=0)

            fn = self._cached_jit("raw_real", raw_real)
        else:

            def raw_discrete(members, weights, Xq):
                preds = jax.vmap(lambda p: base.predict_fn(p, Xq))(members)
                onehot = jax.nn.one_hot(preds.astype(jnp.int32), k)
                votes = jnp.where(onehot > 0, 1.0, -1.0 / (k - 1.0))
                return jnp.einsum("m,mnk->nk", weights, votes)

            fn = self._cached_jit("raw_discrete", raw_discrete)
        return fn(self.params["members"], self.params["weights"], as_f32(X))

    def predict_proba(self, X):
        return jax.nn.softmax(self.predict_raw(X) / (self.num_classes - 1.0), axis=-1)

    def predict(self, X):
        return jnp.argmax(self.predict_raw(X), axis=-1).astype(jnp.float32)

    def take(self, m: int) -> "BoostingClassificationModel":
        m = min(m, self.num_members)
        return BoostingClassificationModel(
            params={
                "members": slice_pytree(self.params["members"], m),
                "weights": self.params["weights"][:m],
            },
            num_features=self.num_features,
            num_classes=self.num_classes,
            num_members=m,
            **self.get_params(),
        )


class BoostingRegressor(_BoostingParams):
    loss = Param("exponential", in_array(["exponential", "linear", "squared"]))
    voting_strategy = Param("median", in_array(["median", "mean"]))

    is_classifier = False

    def _base(self) -> BaseLearner:
        return self.base_learner or DecisionTreeRegressor()

    @instrumented_fit
    def fit(self, X, y, sample_weight=None) -> "BoostingRegressionModel":
        X, y = as_f32(X), as_f32(y)
        w = resolve_weights(y, sample_weight)
        n, d = X.shape
        instr = Instrumentation("BoostingRegressor.fit")
        instr.log_params(self.get_params())
        instr.log_dataset(n, d)
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = base.make_fit_ctx(X)
        root = jax.random.PRNGKey(self.seed)
        # snapshot the loss name: the cached closure must not read `self.loss`
        # at (re)trace time — set_params(loss=...) after fit would otherwise
        # run the wrong shaping under the original cache key
        loss_name = self.loss.lower()

        def build_step():
            def shape_loss(e):
                if loss_name == "exponential":
                    return 1.0 - jnp.exp(-e)
                if loss_name == "squared":
                    return e * e
                return e

            def step(ctx, X, y, bw, key):
                w_norm = bw / jnp.maximum(jnp.sum(bw), 1e-30)
                params = base.fit_from_ctx(ctx, y, w_norm, None, key)
                errors = jnp.abs(y - base.predict_fn(params, X))
                max_error = jnp.max(errors)
                rel = jnp.where(
                    max_error > 0, errors / jnp.maximum(max_error, 1e-30), errors
                )
                losses = shape_loss(rel)
                est_err = jnp.sum(w_norm * losses)
                beta = est_err / jnp.maximum(1.0 - est_err, 1e-30)
                est_weight = jnp.where(
                    beta == 0.0, 1.0, jnp.log(1.0 / jnp.maximum(beta, 1e-300))
                )
                new_bw = w_norm * jnp.power(jnp.maximum(beta, 1e-300), 1.0 - losses)
                new_bw = jnp.where(beta == 0.0, jnp.zeros_like(new_bw), new_bw)
                return params, max_error, est_err, est_weight, new_bw

            return jax.jit(step)

        step = cached_program(
            ("boosting_reg_round", loss_name, base.config_key()), build_step
        )

        bw = w
        members: List[Any] = []
        est_weights: List[float] = []
        i = 0
        ckpt = self._checkpointer(n, d)
        resumed = ckpt.load_latest()
        if resumed is not None:
            last_round, st = resumed
            i = last_round + 1
            bw = jnp.asarray(st["bw"])
            members = list(st["members"])
            est_weights = [float(x) for x in st["est_weights"]]
            logger.info("BoostingRegressor resuming from round %d", i)
        while i < self.num_base_learners and float(jnp.sum(bw)) > 0:
            params, max_error, est_err, est_weight, new_bw = step(
                ctx, X, y, bw, jax.random.fold_in(root, i)
            )
            est_err = float(est_err)
            if float(max_error) == 0.0:
                # degenerate perfect fit: keep model, stop
                # (`BoostingRegressor.scala:236-239`)
                members.append(params)
                est_weights.append(float(est_weight))
                logger.info("BoostingRegressor round %d: maxError=0, stopping", i)
                break
            if est_err >= 0.5:
                # drop model and stop (`BoostingRegressor.scala:251`)
                logger.info(
                    "BoostingRegressor round %d dropped: est_err=%.4f", i, est_err
                )
                break
            members.append(params)
            est_weights.append(float(est_weight))
            bw = new_bw
            logger.info("BoostingRegressor round %d: est_err=%.4f", i, est_err)
            ckpt.maybe_save(
                i, {"bw": bw, "members": members, "est_weights": list(est_weights)}
            )
            i += 1
        ckpt.delete()
        instr.log_outcome(members=len(members))
        return BoostingRegressionModel(
            params={
                "members": stack_pytrees(members) if members else None,
                "weights": jnp.asarray(est_weights, jnp.float32),
            },
            num_features=d,
            num_members=len(members),
            **self.get_params(),
        )


class BoostingRegressionModel(RegressionModel, BoostingRegressor):
    def __init__(self, num_members=0, **kwargs):
        super().__init__(**kwargs)
        self.num_members = num_members

    def member_predictions(self, X):
        base = self._base()
        fn = self._cached_jit(
            "members",
            lambda members, Xq: jax.vmap(lambda p: base.predict_fn(p, Xq))(members),
        )
        return fn(self.params["members"], as_f32(X))  # [m, n]

    def predict(self, X):
        if self.num_members == 0:
            return jnp.zeros((as_f32(X).shape[0],), jnp.float32)
        preds = self.member_predictions(X)
        weights = self.params["weights"]
        if self.voting_strategy.lower() == "mean":
            return jnp.einsum("m,mn->n", weights, preds) / jnp.maximum(
                jnp.sum(weights), 1e-30
            )
        fn = self._cached_jit(
            "median", jax.vmap(weighted_median, in_axes=(1, None))
        )
        return fn(preds, weights)

    def take(self, m: int) -> "BoostingRegressionModel":
        m = min(m, self.num_members)
        return BoostingRegressionModel(
            params={
                "members": slice_pytree(self.params["members"], m),
                "weights": self.params["weights"][:m],
            },
            num_features=self.num_features,
            num_members=m,
            **self.get_params(),
        )
