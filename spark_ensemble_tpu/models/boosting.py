"""AdaBoost meta-estimators: SAMME / SAMME.R classification, Drucker R2
regression.

Re-designs `BoostingClassifier.scala:135-282` and
`BoostingRegressor.scala:173-282`.  The sequential reweighting loop stays on
the host (data-dependent aborts), but each round — weight normalization,
weighted base fit, error/loss computation, estimator-weight formula, sample
reweighting — is ONE jitted XLA program; the boosting weight vector lives on
device across rounds (the reference carries it as an RDD with
``treeReduce`` sums and periodic lineage checkpoints, all unnecessary here).

Distributed: ``fit(..., mesh=...)`` shards rows (and the boosting weight
vector) over the mesh's "data" axis and runs each scan-chunk of rounds as a
single shard_map-ed SPMD program — weight-mass/error sums become psum,
Drucker's ``maxError`` becomes pmax, and the base fit psums its sufficient
statistics over the same axis.  This is the XLA mapping of the reference's
executor-side round reductions (`BoostingClassifier.scala:175,235-242`,
`BoostingRegressor.scala:232-249`) with the host replay of aborts unchanged.

Formula parity:
- SAMME ("discrete"): err = sum(w_norm * 1[miss]); beta =
  err / ((1-err)(K-1)); estimator weight log(1/beta) (1.0 if beta == 0);
  w <- w_norm * (1/beta)^miss; abort-and-drop round if err >= 1 - 1/K
  (`BoostingClassifier.scala:231-260`).
- SAMME.R ("real"): estimator weight 1.0; w <- w_norm *
  exp(-((K-1)/K) * sum_c code_c * log(max(p_c, EPS))), code_c = 1 for the
  true class else -1/(K-1), EPS = 2^-52 (`BoostingClassifier.scala:198-230`).
- Drucker R2: err_i = |y_i - pred_i| / maxError; loss shaping
  exponential (1 - e^-e) | linear | squared; estErr = sum(w_norm * loss);
  stop at estErr >= 0.5 (model dropped — the reference's dead `best = i - 1`
  shows the intent) or maxError == 0 (model kept, weight 1.0);
  beta = estErr/(1-estErr); w <- w_norm * beta^(1-loss)
  (`BoostingRegressor.scala:97-106,208-260`).

Prediction:
- discrete raw: +weight for the member's predicted class, -weight/(K-1)
  elsewhere (`BoostingClassifier.scala:366-382`);
- real raw: sum over members of (K-1) * (log p - mean_c log p)
  (`:348-364`); probability = softmax(raw / (K-1)) (`:342-346`);
- regression: weighted median (default) or weighted mean over members
  (`BoostingRegressor.scala:333-347`).
"""

from __future__ import annotations

import logging
import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_ensemble_tpu.compat import shard_map

from spark_ensemble_tpu import execution as _execution
from spark_ensemble_tpu.models.base import (
    BaseLearner,
    CheckpointableParams,
    ClassificationModel,
    Estimator,
    RegressionModel,
    as_f32,
    cached_program,
    infer_num_classes,
    make_shared_fit_ctx,
    resolve_weights,
    resolved_scan_chunk,
)
from spark_ensemble_tpu.models.gbm import (
    _check_resume_args,
    concat_pytrees,
    slice_pytree,
)
from spark_ensemble_tpu.parallel.mesh import (
    mesh_row_spec as _mesh_row_spec,
    setup_row_sharding,
)
from spark_ensemble_tpu.models.tree import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)
from spark_ensemble_tpu.ops.collective import pmax_reduce, preduce
from spark_ensemble_tpu.params import Param, gt_eq, in_array
from spark_ensemble_tpu.telemetry.events import FitTelemetry
from spark_ensemble_tpu.utils.instrumentation import (
    Instrumentation,
    instrumented_fit,
)
from spark_ensemble_tpu.utils.quantile import weighted_median

logger = logging.getLogger(__name__)

EPSILON = 2.220446049250313e-16  # Spark MLUtils.EPSILON (double ulp of 1.0)


class _BoostingParams(CheckpointableParams, Estimator):
    """Reference `BoostingParams.scala:26-37`."""

    base_learner = Param(
        None, is_estimator=True,
        doc="weak learner fitted per round on reweighted rows; defaults "
        "to a depth-5 histogram decision tree",
    )
    num_base_learners = Param(
        10, gt_eq(1),
        doc="maximum boosting rounds (fits may stop early on a round-0 "
        "abort, reference Boosting.scala semantics)",
    )
    scan_chunk = Param(
        16,
        gt_eq(1),
        doc="max rounds fused into one lax.scan-ed XLA program per dispatch; "
        "the data-dependent aborts (SAMME err >= 1-1/K, Drucker "
        "est_err >= 0.5, zero weight mass, perfect fit) are replayed on the "
        "host after each chunk, reproducing the per-round stopping exactly "
        "(post-stop rounds in the chunk are discarded).  Abort-prone "
        "flavors probe with a single-round first chunk before jumping to "
        "this cap (see _drive_boosting_rounds and the ramp param)",
    )
    ramp = Param(
        "auto",
        in_array(["auto", "off"]),
        doc="chunk schedule for abort-prone flavors (discrete SAMME, "
        "Drucker): 'auto' dispatches a single-round probe chunk first — an "
        "abort on round 0 (the dominant abort case: a base learner too "
        "weak or perfect on the ORIGINAL weights) then discards nothing — "
        "and jumps straight to scan_chunk once the probe survives; 'off' "
        "always dispatches full chunks (no probe overhead, up to "
        "scan_chunk - 1 discarded fits on an abort).  SAMME.R has no "
        "error-threshold abort and always runs full chunks",
    )
    checkpoint_interval = Param(
        10, gt_eq(1), doc="rounds between training-state checkpoints"
    )
    checkpoint_dir = Param(
        None,
        doc="when set, training state (round, members, boosting weights) is "
        "checkpointed every checkpoint_interval rounds and fit() resumes "
        "from the latest checkpoint — the TPU upgrade of the reference's "
        "lineage-only PeriodicRDDCheckpointer (`BoostingRegressor.scala:"
        "202-206`, SURVEY.md §5)",
    )
    aggregation_depth = Param(2, gt_eq(1), doc="API parity; reductions are psum")
    seed = Param(0, doc="PRNG seed for the weighted resampling plans")

    def _drive_boosting_rounds(
        self,
        ckpt,
        bw,
        root,
        members_chunks,
        weights_chunks,
        run_chunk,  # (keys [c,2], bw) -> (params [c,...], est_ws [c], sum_bws [c], bw, extras)
        replay,  # (extras, sum_bws, c, i) -> (#rounds kept, stop?)
        start_i: int,
        ramp: bool = False,
        telem: Optional[FitTelemetry] = None,
        guard=None,
    ) -> int:
        """Shared chunked round driver for both boosting flavors: chunk
        clamping to checkpoint boundaries, per-chunk key fan-out, host
        replay of the flavor's stopping rules, slice-append of kept rounds,
        and gated periodic saves.  Mutates the chunk lists; returns the
        final round count.

        Robustness (docs/robustness.md): each chunk dispatch runs under
        retry/backoff for transient ``RuntimeError``s, a ``NumericGuard``
        checks the chunk's member params (NaN) and weight scalars
        (non-finite), and recovery rewinds ``bw`` to the chunk start and
        deterministically replays the clean prefix (same absolute-round
        ``fold_in`` keys -> identical rounds).  Boosting members are
        TRUE-dropped on ``skip_round`` — SAMME.R prediction ignores
        estimator weights, so a zero-weight poisoned member would still
        vote — and ``halve_step`` degrades to ``skip_round`` (no scalable
        step size in the boosting round).

        ``ramp``: abort-prone flavors (discrete SAMME, Drucker R2 — their
        stopping rules fire routinely on weak learners) dispatch a
        single-round PROBE chunk first, then jump straight to
        ``scan_chunk``.  An abort ends the fit and discards the rest of the
        in-flight chunk; aborts overwhelmingly fire on round 0 (the base
        learner is too weak — or perfect — on the original weights), so
        the probe catches them with zero discard while abort-free runs pay
        exactly ONE extra dispatch (the round-3 geometric 1,2,4,... ramp
        cost ~log2(scan_chunk) dispatches on every abort-free fit — a
        measured +15% on 10-round CPU stump boosting — for protection the
        probe alone provides where it matters).  ``ramp='off'`` skips the
        probe.  SAMME.R has no error-threshold abort and never probes."""
        from spark_ensemble_tpu.robustness.chaos import controller
        from spark_ensemble_tpu.robustness.retry import retry_call

        ctl = controller()
        retry_policy = self._retry_policy()
        label = type(self).__name__
        guard_on = guard is not None and guard.active

        def dispatch(keys, bw_in, i0):
            # transient-fault surface: device dispatch of the whole chunk.
            # Chaos faults are at-most-once per site, so the retry (and the
            # recovery replay below) always sees a clean re-run.
            site = f"{label}:round:{i0}"

            def attempt():
                ctl.transient(site)
                return run_chunk(keys, bw_in)

            params_c, est_ws, sum_bws, bw_out, extras = retry_call(
                attempt, policy=retry_policy,
                op=f"{label}.round_chunk", telem=telem,
            )
            params_c = ctl.poison_member_stack(site, params_c)
            return params_c, est_ws, sum_bws, bw_out, extras

        i = start_i
        chunk = resolved_scan_chunk(self, int(bw.shape[0]))
        # lookahead window past the committing chunk (docs/pipeline.md);
        # the boosting carry is just ``bw``, which run_chunk threads
        # explicitly, so speculation chains the weight futures directly
        depth = _execution.resolve_pipeline_depth(int(bw.shape[0]))
        # a checkpoint resume starts at the full chunk: start_i kept rounds
        # already outweigh the worst-case discard of one fixed-size chunk
        probe = ramp and self.ramp == "auto" and start_i == 0
        cur = 1 if probe else chunk
        stop = float(jnp.sum(bw)) <= 0

        def to_host(sum_bws, extras):
            # extras stay on device through dispatch so a speculative chunk
            # is never read; the commit path converts exactly once
            sum_bws = np.asarray(sum_bws)
            if isinstance(extras, tuple):
                extras = tuple(np.asarray(e) for e in extras)
            elif extras is not None:
                extras = np.asarray(extras)
            return sum_bws, extras

        def commit_chunk(i, c, keys, bw_prev, t_chunk,
                         params_c, est_ws, sum_bws, bw_out, extras):
            """One dispatched chunk's host bookkeeping (guard scan, abort
            replay, telemetry, slice-append, gated save, preemption point)
            -> (i, bw, stop, rewound)."""
            bw = bw_out
            stop = False
            skip_after = 0  # guard-dropped rounds: consume the index, no member
            halt = False
            rewound = False
            if telem is not None and telem.enabled:
                # host-blocked accounting: the read this chunk's commit
                # waits on (docs/pipeline.md)
                telem.blocking_read((params_c, est_ws, sum_bws, extras))
            bad = (
                guard.first_nonfinite(params_c, est_ws, sum_bws, extras)
                if guard_on
                else None
            )
            if bad is not None:
                rewound = True
                if guard.policy == "raise":
                    guard.raise_error(i + bad)
                action = (
                    "stop_early" if guard.policy == "stop_early"
                    else "skip_round"
                )
                extra = (
                    {"degraded_from": "halve_step"}
                    if guard.policy == "halve_step"
                    else {}
                )
                guard.record(i + bad, action, member_dropped=True, **extra)
                # rewind to the chunk-start weights and deterministically
                # replay the clean prefix (same keys -> same rounds)
                bw = bw_prev
                c = bad
                if c > 0:
                    params_c, est_ws, sum_bws, bw, extras = dispatch(
                        keys[:c], bw, i
                    )
                if action == "stop_early":
                    halt = True
                else:
                    skip_after = 1
            if c > 0:
                sum_bws, extras = to_host(sum_bws, extras)
                kept, stop = replay(extras, sum_bws, c, i)
                if telem is not None and telem.enabled:
                    # classifier extras = per-round errs; Drucker extras =
                    # (max_errs, est_errs) — the estimator error is the loss
                    losses = extras[1] if isinstance(extras, tuple) else extras
                    telem.round_chunk(
                        i, kept, t_chunk,
                        fence=(params_c, est_ws),
                        losses=None if losses is None else np.asarray(losses)[:kept],
                        step_sizes=np.asarray(est_ws)[:kept] if kept > 0 else None,
                        divisor=c,
                    )
                if not stop:
                    # sequential loop guard for the NEXT round: weight mass
                    # after this chunk's last kept round must stay positive
                    stop = float(sum_bws[c - 1]) <= 0
                if kept > 0:
                    members_chunks.append(slice_pytree(params_c, kept))
                    weights_chunks.append(est_ws[:kept])
                i += kept
            if halt:
                stop = True
            if not stop:
                i += skip_after
            if not stop and i > start_i and ckpt.should_save(i - 1):
                ckpt.save(
                    i - 1,
                    {
                        "bw": bw,
                        "members_layout": self.MEMBERS_LAYOUT,
                        "members": concat_pytrees(members_chunks),
                        "est_weights": concat_pytrees(weights_chunks),
                    },
                )
            if not stop:
                ctl.preempt(f"{label}:after_round:{i}")
                if self._is_refresh_fit:
                    # refresh-only kill site: a background warm-start fit
                    # dies mid-round, the serving model must stay untouched
                    ctl.refresh_crash(f"{label}:refresh_round:{i}")
            return i, bw, stop, rewound

        # -- the family adapter behind the shared RoundExecutor: chunk j+1
        # is enqueued on chunk j's weight futures before any host read of
        # chunk j.  An abort, a guard rewind or a weight-mass stop
        # invalidates everything still in flight (speculative outputs are
        # discarded unread; fold_in keys derive from absolute round
        # indices, so any replay is bit-identical).  The probe chunk
        # commits alone first — it exists because round-0 aborts are the
        # common case, and speculating past it would waste a full chunk on
        # every such abort.
        drv = self

        class _Adapter(_execution.RoundAdapter):
            def __init__(self):
                self.depth = depth
                self.telem = telem  # executor traces chunk spans through it
                self.i, self.bw, self.stop = i, bw, stop
                self.i_disp = i
                self.bw_frontier = bw
                self.cur = cur
                self.probe_pending = probe

            def should_continue(self):
                return self.i < drv.num_base_learners and not self.stop

            def can_launch(self):
                return self.i_disp < drv.num_base_learners

            def window(self):
                return 1 if self.probe_pending else self.depth + 1

            def launch(self):
                c = min(self.cur, drv.num_base_learners - self.i_disp)
                self.cur = chunk  # probe planned (or no probe): full chunks
                if ckpt.enabled:
                    c = min(c, ckpt.rounds_until_save(self.i_disp))
                keys = jax.vmap(lambda j: jax.random.fold_in(root, j))(
                    jnp.arange(self.i_disp, self.i_disp + c)
                )
                t0 = time.perf_counter()
                bw_prev = self.bw_frontier
                out = dispatch(keys, bw_prev, self.i_disp)
                entry = (self.i_disp, c, keys, bw_prev, t0) + out
                self.i_disp += c
                self.bw_frontier = out[3]
                return entry

            def commit(self, entry, speculated):
                self.probe_pending = False
                (i0, c, keys, bw_prev, t0,
                 params_c, est_ws, sum_bws, bw_out, extras) = entry
                self.i, self.bw, self.stop, rewound = commit_chunk(
                    i0, c, keys, bw_prev, t0,
                    params_c, est_ws, sum_bws, bw_out, extras,
                )
                return rewound or self.stop

            def reset_frontier(self):
                self.i_disp = self.i
                self.bw_frontier = self.bw

            def finish(self):
                # join the in-flight async save before the model assembles
                ckpt.wait()

        return _execution.RoundExecutor(_Adapter()).run().i


class BoostingClassifier(_BoostingParams):
    algorithm = Param(
        "discrete", in_array(["discrete", "real"]),
        doc="'discrete' = SAMME (class votes), 'real' = SAMME.R "
        "(probability-weighted log-odds votes)",
    )

    is_classifier = True

    def _base(self) -> BaseLearner:
        return self.base_learner or DecisionTreeClassifier()

    @instrumented_fit
    def fit(
        self, X, y, sample_weight=None, num_classes=None, mesh=None
    ) -> "BoostingClassificationModel":
        """Fit; with ``mesh`` (a "data" axis, optionally hybrid
        ``("dcn_data", "data")``) every round runs as ONE shard_map-ed SPMD
        program with rows sharded over "data": the normalized weight mass,
        the weighted error, and the base fit's sufficient statistics all
        reduce via psum — the XLA replacement for the reference's
        executor-side ``treeAggregate`` round reductions
        (`BoostingClassifier.scala:175,235-242`)."""
        X, y = as_f32(X), as_f32(y)
        self._validate_fit_inputs(X, y)
        w = resolve_weights(y, sample_weight)
        num_classes = infer_num_classes(y, num_classes)
        n, d = X.shape
        instr = Instrumentation("BoostingClassifier.fit")
        instr.log_params(self.get_params())
        instr.log_dataset(n, d, num_classes)
        telem = FitTelemetry.start(self, n=n, d=d, num_classes=int(num_classes))
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = make_shared_fit_ctx(base, X, num_classes)
        algorithm = self.algorithm.lower()
        k = num_classes
        root = jax.random.PRNGKey(self.seed)

        # ---- mesh setup: pad rows (weight 0 -> statistics unchanged) and
        # shard ctx/X/y/boosting-weights over the data axis ----
        ax = None
        n_pad = n
        if mesh is not None:
            ctx, X, ax, n_pad, (y, w) = setup_row_sharding(
                mesh, base, ctx, X, n, (y, w)
            )

        def build_step():
            def gsum(v):
                # global scalar reduction: the SPMD treeReduce
                return preduce(v, ax)

            def round_discrete(ctx, X, y, bw, key):
                w_norm = bw / jnp.maximum(gsum(jnp.sum(bw)), 1e-30)
                # fit + same-row class predictions in one call (tree
                # learners reuse fit-time leaf routing, models/tree.py)
                params, pred = base.fit_and_direction(
                    ctx, y, w_norm, None, key, X, axis_name=ax
                )
                miss = (pred != y).astype(jnp.float32)
                err = gsum(jnp.sum(w_norm * miss))
                beta = err / jnp.maximum((1.0 - err) * (k - 1.0), 1e-30)
                est_weight = jnp.where(
                    beta == 0.0, 1.0, jnp.log(1.0 / jnp.maximum(beta, 1e-300))
                )
                new_bw = w_norm * jnp.power(1.0 / jnp.maximum(beta, 1e-300), miss)
                return params, err, est_weight, new_bw

            def round_real(ctx, X, y, bw, key):
                w_norm = bw / jnp.maximum(gsum(jnp.sum(bw)), 1e-30)
                # fit + same-row probabilities in one call (leaf-id reuse)
                params, proba = base.fit_and_proba(
                    ctx, y, w_norm, None, key, X, axis_name=ax
                )  # [n, k]
                miss = (jnp.argmax(proba, axis=-1) != y.astype(jnp.int32)).astype(
                    jnp.float32
                )
                err = gsum(jnp.sum(w_norm * miss))
                codes = jnp.where(
                    jax.nn.one_hot(y.astype(jnp.int32), k) > 0, 1.0, -1.0 / (k - 1.0)
                )
                ll = jnp.sum(codes * jnp.log(jnp.maximum(proba, EPSILON)), axis=-1)
                new_bw = w_norm * jnp.exp(-((k - 1.0) / k) * ll)
                return params, err, jnp.asarray(1.0, jnp.float32), new_bw

            round_core = round_real if algorithm == "real" else round_discrete

            def chunk(ctx, X, y, bw, keys):
                def body(bw, key):
                    params, err, est_weight, new_bw = round_core(
                        ctx, X, y, bw, key
                    )
                    return new_bw, (
                        params, err, est_weight, gsum(jnp.sum(new_bw))
                    )

                bw, (params_c, errs, est_ws, sum_bws) = jax.lax.scan(
                    body, bw, keys
                )
                return params_c, errs, est_ws, sum_bws, bw

            if mesh is None:
                return jax.jit(chunk)
            return jax.jit(
                shard_map(
                    chunk,
                    mesh=mesh,
                    in_specs=(
                        base.ctx_specs(ctx, ax),
                        P(ax, None),  # X
                        P(ax),  # y
                        P(ax),  # bw
                        P(),  # keys [c, 2]
                    ),
                    out_specs=(P(), P(), P(), P(), P(ax)),
                    check_vma=False,
                )
            )

        chunk_step = cached_program(
            ("boosting_cls_chunk", algorithm, k, base.config_key(), mesh),
            build_step,
        )

        def replay(errs, sum_bws, c, i):
            """Host replay of the per-round aborts over a chunk's outputs:
            returns (#rounds kept from this chunk, stop?).  Rounds past a
            stop never ran in the sequential loop; their outputs are
            discarded."""
            kept = 0
            for j in range(c):
                if j > 0 and float(sum_bws[j - 1]) <= 0:
                    return kept, True  # sequential loop guard: weight mass 0
                err = float(errs[j])
                if algorithm == "discrete" and err >= 1.0 - 1.0 / k:
                    # abort round, drop model (`BoostingClassifier.scala:252`)
                    logger.info(
                        "BoostingClassifier round %d aborted: err=%.4f", i + j, err
                    )
                    return kept, True
                kept = j + 1
                logger.info("BoostingClassifier round %d: err=%.4f", i + j, err)
                if err <= 0:
                    return kept, True
            return kept, False

        def run_chunk(keys, bw):
            params_c, errs, est_ws, sum_bws, bw = chunk_step(ctx, X, y, bw, keys)
            # errs stay on device: the driver converts at commit time, so a
            # speculative dispatch never blocks the host (docs/pipeline.md)
            return params_c, est_ws, sum_bws, bw, errs

        bw = w
        members_chunks: List[Any] = []
        weights_chunks: List[Any] = []
        i = 0
        # n_pad is part of the resume identity: a checkpointed `bw` is padded
        # to the mesh's data-axis size, so a resume under a different mesh
        # must start fresh rather than load a wrong-length weight vector
        ckpt = self._checkpointer(n, d, num_classes, n_pad, telem=telem)
        resumed = ckpt.load_latest()
        warm = False
        if resumed is None:
            # warm-start resume from a served PackedModel prefix (fit_resume
            # in serving/export.py); a real checkpoint always wins
            resumed = self._take_warm_resume()
            warm = resumed is not None
        if resumed is not None:
            last_round, st = resumed
            i = last_round + 1
            bw = jnp.asarray(st["bw"])
            if mesh is not None:
                bw = jax.device_put(
                    bw, NamedSharding(mesh, P(_mesh_row_spec(mesh)))
                )
            members_chunks, weights_chunks = self._resume_chunks(
                st, weights_key="est_weights"
            )
            logger.info("BoostingClassifier resuming from round %d", i)
            detail = ckpt.last_load_detail or {}
            telem.emit(
                "resume_from_checkpoint",
                round=i,
                source="warm_start" if warm else detail.get("source", "latest"),
                fallback=bool(detail.get("fallback", False)),
            )

        telem.phase_mark("setup")
        self._drive_boosting_rounds(
            ckpt, bw, root, members_chunks, weights_chunks, run_chunk, replay,
            i, ramp=(algorithm == "discrete"), telem=telem,
            guard=self._numeric_guard(telem),
        )
        ckpt.delete()
        num_members = int(sum(wc.shape[0] for wc in weights_chunks))
        instr.log_outcome(members=num_members)
        model = BoostingClassificationModel(
            params={
                "members": concat_pytrees(members_chunks)
                if members_chunks
                else None,
                "weights": concat_pytrees(weights_chunks)
                if weights_chunks
                else jnp.zeros((0,), jnp.float32),
            },
            num_features=d,
            num_classes=num_classes,
            num_members=num_members,
            **self.get_params(),
        )
        telem.finish(model=model, members=num_members)
        return model


def _boosting_cls_bw_replay_program(base, algorithm, k):
    """One jitted scan replaying the SAMME boosting-weight recursion over a
    stored member stack — the warm-start half of ``fit_resume``.  Each step
    reproduces the committed round's update exactly (same expressions, same
    reduction order as ``round_discrete``/``round_real`` on a single
    device), and fit-time predictions reuse leaf routing the predict path
    reproduces bit-for-bit (models/tree.py), so the final carry equals the
    ``bw`` a checkpoint would have stored after the last committed round.

    Also returns the LAST round's weighted error: ``err <= 0`` is the one
    stopping rule that keeps its member (perfect fit, replay() in fit), so
    a resumed fit must treat it as terminal convergence rather than grow
    past the point the straight fit stopped at."""

    def build():
        # not `replay`: the host replay helpers in fit share that name, and
        # the traced-branch lint resolves jit targets by name
        def bw_replay(members, bw, X, y):
            if algorithm == "real":

                def body(bw, m):
                    w_norm = bw / jnp.maximum(jnp.sum(bw), 1e-30)
                    proba = base.predict_proba_fn(m, X)
                    miss = (
                        jnp.argmax(proba, axis=-1) != y.astype(jnp.int32)
                    ).astype(jnp.float32)
                    err = jnp.sum(w_norm * miss)
                    codes = jnp.where(
                        jax.nn.one_hot(y.astype(jnp.int32), k) > 0,
                        1.0,
                        -1.0 / (k - 1.0),
                    )
                    ll = jnp.sum(
                        codes * jnp.log(jnp.maximum(proba, EPSILON)), axis=-1
                    )
                    return w_norm * jnp.exp(-((k - 1.0) / k) * ll), err

            else:

                def body(bw, m):
                    w_norm = bw / jnp.maximum(jnp.sum(bw), 1e-30)
                    miss = (base.predict_fn(m, X) != y).astype(jnp.float32)
                    err = jnp.sum(w_norm * miss)
                    beta = err / jnp.maximum((1.0 - err) * (k - 1.0), 1e-30)
                    return (
                        w_norm
                        * jnp.power(1.0 / jnp.maximum(beta, 1e-300), miss),
                        err,
                    )

            out, errs = jax.lax.scan(body, bw, members)
            return out, errs[-1]

        return jax.jit(bw_replay)

    return cached_program(
        ("boosting_cls_warm_replay", algorithm, k, base.config_key()), build
    )


def _boosting_reg_bw_replay_program(base, loss_name):
    """Drucker analogue of :func:`_boosting_cls_bw_replay_program`: replay
    the R2 weight recursion (normalized errors, shaped losses, beta
    reweighting) over the stored members to recover the post-round ``bw``."""

    def build():
        def shape_loss(e):
            if loss_name == "exponential":
                return 1.0 - jnp.exp(-e)
            if loss_name == "squared":
                return e * e
            return e

        def bw_replay(members, bw, X, y):
            def body(bw, m):
                w_norm = bw / jnp.maximum(jnp.sum(bw), 1e-30)
                errors = jnp.abs(y - base.predict_fn(m, X))
                max_error = jnp.max(errors)
                rel = jnp.where(
                    max_error > 0,
                    errors / jnp.maximum(max_error, 1e-30),
                    errors,
                )
                losses = shape_loss(rel)
                est_err = jnp.sum(w_norm * losses)
                beta = est_err / jnp.maximum(1.0 - est_err, 1e-30)
                new_bw = w_norm * jnp.power(
                    jnp.maximum(beta, 1e-300), 1.0 - losses
                )
                return (
                    jnp.where(beta == 0.0, jnp.zeros_like(new_bw), new_bw),
                    None,
                )

            out, _ = jax.lax.scan(body, bw, members)
            return out

        return jax.jit(bw_replay)

    return cached_program(
        ("boosting_reg_warm_replay", loss_name, base.config_key()), build
    )


class BoostingClassificationModel(ClassificationModel, BoostingClassifier):
    def __init__(self, num_members=0, **kwargs):
        super().__init__(**kwargs)
        self.num_members = num_members

    def predict_raw(self, X):
        base = self._base()
        k = self.num_classes
        if self.num_members == 0:
            # reference predictRaw over zero models: zero raw vector
            return jnp.zeros((as_f32(X).shape[0], k), jnp.float32)
        if self.algorithm.lower() == "real":

            def raw_real(members, weights, Xq):
                probas = base.predict_proba_many_fn(members, Xq)
                logp = jnp.log(jnp.maximum(probas, EPSILON))
                decisions = logp - jnp.mean(logp, axis=-1, keepdims=True)
                return (k - 1.0) * jnp.sum(decisions, axis=0)

            name, builder = "raw_real", raw_real
        else:

            def raw_discrete(members, weights, Xq):
                preds = base.predict_many_fn(members, Xq)
                onehot = jax.nn.one_hot(preds.astype(jnp.int32), k)
                votes = jnp.where(onehot > 0, 1.0, -1.0 / (k - 1.0))
                return jnp.einsum("m,mnk->nk", weights, votes)

            name, builder = "raw_discrete", raw_discrete
        return self._predict_program(
            name, builder, (self.params["members"], self.params["weights"]), X
        )

    def predict_proba(self, X):
        return jax.nn.softmax(self.predict_raw(X) / (self.num_classes - 1.0), axis=-1)

    def predict(self, X):
        return jnp.argmax(self.predict_raw(X), axis=-1).astype(jnp.float32)

    def take(self, m: int) -> "BoostingClassificationModel":
        m = min(m, self.num_members)
        return BoostingClassificationModel(
            params={
                "members": slice_pytree(self.params["members"], m),
                "weights": self.params["weights"][:m],
            },
            num_features=self.num_features,
            num_classes=self.num_classes,
            num_members=m,
            **self.get_params(),
        )

    def fit_resume(self, X, y, n_new_rounds, sample_weight=None):
        """Continue this fitted SAMME ensemble for ``n_new_rounds`` more
        rounds on the SAME training data — bit-identical to a single
        ``num_members + n_new_rounds``-round fit (the ``take(k)`` contract
        run forward): per-round ``fold_in`` keys derive from ABSOLUTE round
        indices, and the boosting-weight carry is replayed over the stored
        members by the exact round recursion, so round ``k`` onward sees the
        same inputs either way.  Scope: single-device fits (``mesh=None``)
        on the original training matrix."""
        k, n_new = int(self.num_members), int(n_new_rounds)
        _check_resume_args(self, k, n_new, X)
        X32, y32 = as_f32(X), as_f32(y)
        base = self._base().copy()
        members = self.params["members"]
        bw, last_err = _boosting_cls_bw_replay_program(
            base, self.algorithm.lower(), int(self.num_classes)
        )(members, resolve_weights(y32, sample_weight), X32, y32)
        if float(last_err) <= 0.0:
            # the straight fit terminally converged at round k-1 (err <= 0
            # keeps the member, then stops); a longer fit is this model
            return self
        est = BoostingClassifier(
            **{**self.get_params(), "num_base_learners": k + n_new}
        )
        est._set_warm_resume(
            k - 1,
            {
                "bw": bw,
                "members_layout": self.MEMBERS_LAYOUT,
                "members": members,
                "est_weights": jnp.asarray(
                    self.params["weights"], jnp.float32
                ),
            },
        )
        return est.fit(
            X, y, sample_weight=sample_weight, num_classes=self.num_classes
        )


class BoostingRegressor(_BoostingParams):
    loss = Param(
        "exponential", in_array(["exponential", "linear", "squared"]),
        doc="Drucker R2 per-row loss shaping of the normalized errors",
    )
    voting_strategy = Param(
        "median", in_array(["median", "mean"]),
        doc="'median' = weighted median of member predictions (Drucker), "
        "'mean' = confidence-weighted mean",
    )

    is_classifier = False

    def _base(self) -> BaseLearner:
        return self.base_learner or DecisionTreeRegressor()

    @instrumented_fit
    def fit(
        self, X, y, sample_weight=None, mesh=None
    ) -> "BoostingRegressionModel":
        """Fit; with ``mesh`` rows shard over "data" and each Drucker round
        reduces via collectives: weight mass and ``estErr`` psum, ``maxError``
        pmax (the reference's distributed ``treeAggregate(max)``,
        `BoostingRegressor.scala:232-249`).  Padding rows are excluded from
        ``maxError`` by a validity mask (their weight is already 0)."""
        X, y = as_f32(X), as_f32(y)
        self._validate_fit_inputs(X, y)
        w = resolve_weights(y, sample_weight)
        n, d = X.shape
        instr = Instrumentation("BoostingRegressor.fit")
        instr.log_params(self.get_params())
        instr.log_dataset(n, d)
        telem = FitTelemetry.start(self, n=n, d=d)
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = make_shared_fit_ctx(base, X)
        root = jax.random.PRNGKey(self.seed)
        # snapshot the loss name: the cached closure must not read `self.loss`
        # at (re)trace time — set_params(loss=...) after fit would otherwise
        # run the wrong shaping under the original cache key
        loss_name = self.loss.lower()

        # ---- mesh setup ----
        ax = None
        n_pad = n
        valid = jnp.ones((n,), jnp.float32)
        if mesh is not None:
            ctx, X, ax, n_pad, (y, w, valid) = setup_row_sharding(
                mesh, base, ctx, X, n, (y, w, valid)
            )

        def build_step():
            def gsum(v):
                return preduce(v, ax)

            def gmax(v):
                return pmax_reduce(v, ax)

            def shape_loss(e):
                if loss_name == "exponential":
                    return 1.0 - jnp.exp(-e)
                if loss_name == "squared":
                    return e * e
                return e

            def step(ctx, X, y, valid, bw, key):
                w_norm = bw / jnp.maximum(gsum(jnp.sum(bw)), 1e-30)
                # fit + same-row predictions in one call (leaf-id reuse)
                params, pred = base.fit_and_direction(
                    ctx, y, w_norm, None, key, X, axis_name=ax
                )
                # mask padding rows out of the max: their |y - pred| is
                # meaningless (y padded with 0) and must not set maxError
                errors = valid * jnp.abs(y - pred)
                max_error = gmax(jnp.max(errors))
                rel = jnp.where(
                    max_error > 0, errors / jnp.maximum(max_error, 1e-30), errors
                )
                losses = shape_loss(rel)
                est_err = gsum(jnp.sum(w_norm * losses))
                beta = est_err / jnp.maximum(1.0 - est_err, 1e-30)
                est_weight = jnp.where(
                    beta == 0.0, 1.0, jnp.log(1.0 / jnp.maximum(beta, 1e-300))
                )
                new_bw = w_norm * jnp.power(jnp.maximum(beta, 1e-300), 1.0 - losses)
                new_bw = jnp.where(beta == 0.0, jnp.zeros_like(new_bw), new_bw)
                return params, max_error, est_err, est_weight, new_bw

            def chunk(ctx, X, y, valid, bw, keys):
                def body(bw, key):
                    params, max_error, est_err, est_weight, new_bw = step(
                        ctx, X, y, valid, bw, key
                    )
                    return new_bw, (
                        params, max_error, est_err, est_weight,
                        gsum(jnp.sum(new_bw)),
                    )

                bw, (params_c, max_errs, est_errs, est_ws, sum_bws) = (
                    jax.lax.scan(body, bw, keys)
                )
                return params_c, max_errs, est_errs, est_ws, sum_bws, bw

            if mesh is None:
                return jax.jit(chunk)
            return jax.jit(
                shard_map(
                    chunk,
                    mesh=mesh,
                    in_specs=(
                        base.ctx_specs(ctx, ax),
                        P(ax, None),  # X
                        P(ax),  # y
                        P(ax),  # valid
                        P(ax),  # bw
                        P(),  # keys [c, 2]
                    ),
                    out_specs=(P(), P(), P(), P(), P(), P(ax)),
                    check_vma=False,
                )
            )

        chunk_step = cached_program(
            ("boosting_reg_chunk", loss_name, base.config_key(), mesh),
            build_step,
        )

        def replay(extras, sum_bws, c, i):
            """Host replay of the Drucker stopping rules (see classifier)."""
            max_errs, est_errs = extras
            kept = 0
            for j in range(c):
                if j > 0 and float(sum_bws[j - 1]) <= 0:
                    return kept, True
                if float(max_errs[j]) == 0.0:
                    # degenerate perfect fit: keep model, stop
                    # (`BoostingRegressor.scala:236-239`)
                    logger.info(
                        "BoostingRegressor round %d: maxError=0, stopping", i + j
                    )
                    return j + 1, True
                est_err = float(est_errs[j])
                if est_err >= 0.5:
                    # drop model and stop (`BoostingRegressor.scala:251`)
                    logger.info(
                        "BoostingRegressor round %d dropped: est_err=%.4f",
                        i + j, est_err,
                    )
                    return kept, True
                kept = j + 1
                logger.info(
                    "BoostingRegressor round %d: est_err=%.4f", i + j, est_err
                )
            return kept, False

        def run_chunk(keys, bw):
            params_c, max_errs, est_errs, est_ws, sum_bws, bw = chunk_step(
                ctx, X, y, valid, bw, keys
            )
            # extras stay on device: converted once at commit time, so a
            # speculative dispatch never blocks the host (docs/pipeline.md)
            return params_c, est_ws, sum_bws, bw, (max_errs, est_errs)

        bw = w
        members_chunks: List[Any] = []
        weights_chunks: List[Any] = []
        i = 0
        # n_pad in the fingerprint: see BoostingClassifier.fit
        ckpt = self._checkpointer(n, d, n_pad, telem=telem)
        resumed = ckpt.load_latest()
        warm = False
        if resumed is None:
            # warm-start resume from a served PackedModel prefix (fit_resume
            # in serving/export.py); a real checkpoint always wins
            resumed = self._take_warm_resume()
            warm = resumed is not None
        if resumed is not None:
            last_round, st = resumed
            i = last_round + 1
            bw = jnp.asarray(st["bw"])
            if mesh is not None:
                bw = jax.device_put(
                    bw, NamedSharding(mesh, P(_mesh_row_spec(mesh)))
                )
            members_chunks, weights_chunks = self._resume_chunks(
                st, weights_key="est_weights"
            )
            logger.info("BoostingRegressor resuming from round %d", i)
            detail = ckpt.last_load_detail or {}
            telem.emit(
                "resume_from_checkpoint",
                round=i,
                source="warm_start" if warm else detail.get("source", "latest"),
                fallback=bool(detail.get("fallback", False)),
            )

        telem.phase_mark("setup")
        self._drive_boosting_rounds(
            ckpt, bw, root, members_chunks, weights_chunks, run_chunk, replay,
            i, ramp=True, telem=telem,
            guard=self._numeric_guard(telem),
        )
        ckpt.delete()
        num_members = int(sum(wc.shape[0] for wc in weights_chunks))
        instr.log_outcome(members=num_members)
        model = BoostingRegressionModel(
            params={
                "members": concat_pytrees(members_chunks)
                if members_chunks
                else None,
                "weights": concat_pytrees(weights_chunks)
                if weights_chunks
                else jnp.zeros((0,), jnp.float32),
            },
            num_features=d,
            num_members=num_members,
            **self.get_params(),
        )
        telem.finish(model=model, members=num_members)
        return model


class BoostingRegressionModel(RegressionModel, BoostingRegressor):
    def __init__(self, num_members=0, **kwargs):
        super().__init__(**kwargs)
        self.num_members = num_members

    def member_predictions(self, X):
        base = self._base()
        return self._predict_program(  # [m, n]
            "members",
            lambda members, Xq: base.predict_many_fn(members, Xq),
            (self.params["members"],),
            X,
            out_row_axis=1,
        )

    def predict(self, X):
        if self.num_members == 0:
            return jnp.zeros((as_f32(X).shape[0],), jnp.float32)
        base = self._base()
        # members + aggregation fused into ONE cached program so the whole
        # predict path shape-buckets (the median's per-row vmap used to
        # retrace on every novel n)
        if self.voting_strategy.lower() == "mean":

            def agg_mean(members, weights, Xq):
                preds = base.predict_many_fn(members, Xq)
                return jnp.einsum("m,mn->n", weights, preds) / jnp.maximum(
                    jnp.sum(weights), 1e-30
                )

            name, builder = "predict_mean", agg_mean
        else:

            def agg_median(members, weights, Xq):
                preds = base.predict_many_fn(members, Xq)
                return jax.vmap(weighted_median, in_axes=(1, None))(
                    preds, weights
                )

            name, builder = "predict_median", agg_median
        return self._predict_program(
            name, builder, (self.params["members"], self.params["weights"]), X
        )

    def take(self, m: int) -> "BoostingRegressionModel":
        m = min(m, self.num_members)
        return BoostingRegressionModel(
            params={
                "members": slice_pytree(self.params["members"], m),
                "weights": self.params["weights"][:m],
            },
            num_features=self.num_features,
            num_members=m,
            **self.get_params(),
        )

    def fit_resume(self, X, y, n_new_rounds, sample_weight=None):
        """Continue this fitted Drucker ensemble for ``n_new_rounds`` more
        rounds on the SAME training data — bit-identical to a single longer
        fit; see :meth:`BoostingClassificationModel.fit_resume` for the
        contract and scope."""
        k, n_new = int(self.num_members), int(n_new_rounds)
        _check_resume_args(self, k, n_new, X)
        X32, y32 = as_f32(X), as_f32(y)
        base = self._base().copy()
        members = self.params["members"]
        bw = _boosting_reg_bw_replay_program(base, self.loss.lower())(
            members, resolve_weights(y32, sample_weight), X32, y32
        )
        est = BoostingRegressor(
            **{**self.get_params(), "num_base_learners": k + n_new}
        )
        est._set_warm_resume(
            k - 1,
            {
                "bw": bw,
                "members_layout": self.MEMBERS_LAYOUT,
                "members": members,
                "est_weights": jnp.asarray(
                    self.params["weights"], jnp.float32
                ),
            },
        )
        return est.fit(X, y, sample_weight=sample_weight)
