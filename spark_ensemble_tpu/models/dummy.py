"""Dummy baseline learners (reference: `DummyRegressor.scala`, `DummyClassifier.scala`).

Used standalone as baselines and, critically, as GBM's init model
(`GBMRegressor.scala:287-303`, `GBMClassifier.scala:275-288`).  Strategies:

- DummyRegressor: mean | median | quantile(q) | constant(c)
  (`DummyRegressor.scala:113-129`; quantile via Spark ``approxQuantile`` —
  ours is the exact weighted quantile kernel).
- DummyClassifier: uniform | prior | constant(c)
  (`DummyClassifier.scala:90-123`; raw prediction = log(probability)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.ops.collective import preduce
from spark_ensemble_tpu.models.base import (
    Static,
    static_value,
    BaseLearner,
    ClassificationModel,
    RegressionModel,
    as_f32,
)
from spark_ensemble_tpu.params import Param, gt_eq, in_array, in_range
from spark_ensemble_tpu.utils.quantile import weighted_median, weighted_quantile


class DummyRegressor(BaseLearner):
    strategy = Param(
        "mean", in_array(["mean", "median", "quantile", "constant"]),
        doc="constant prediction rule over the training target",
    )
    quantile = Param(
        0.5, in_range(0.0, 1.0),
        doc="target quantile for strategy='quantile' (exact, weighted)",
    )
    constant = Param(0.0, doc="value for strategy='constant'")
    tol = Param(1e-3, gt_eq(0.0), doc="kept for API parity; quantiles are exact")

    is_classifier = False

    def make_fit_ctx(self, X, num_classes=None):
        return None

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        strategy = self.strategy.lower()
        if strategy == "mean":
            sw_y = preduce(jnp.sum(w * y), axis_name)
            sw = preduce(jnp.sum(w), axis_name)
            value = sw_y / jnp.maximum(sw, 1e-30)
        elif strategy == "median":
            value = weighted_median(y, w, axis_name=axis_name)
        elif strategy == "quantile":
            value = weighted_quantile(y, self.quantile, w, axis_name=axis_name)
        else:
            value = jnp.asarray(self.constant, jnp.float32)
        return {"value": as_f32(value)}

    def predict_fn(self, params, X):
        return jnp.broadcast_to(params["value"], (X.shape[0],))

    def model_from_params(self, params, num_features, num_classes=None):
        return DummyRegressionModel(
            params=params, num_features=num_features, **self.get_params()
        )


class DummyRegressionModel(RegressionModel, DummyRegressor):
    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))


class DummyClassifier(BaseLearner):
    strategy = Param(
        "prior", in_array(["uniform", "prior", "constant"]),
        doc="'prior' predicts the modal class with class-frequency "
        "probabilities; 'uniform' ignores the training distribution",
    )
    constant = Param(0.0, doc="class label for strategy='constant'")

    is_classifier = True

    def make_fit_ctx(self, X, num_classes=None):
        return {"num_classes": Static(num_classes)}

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        k = static_value(ctx["num_classes"])
        strategy = self.strategy.lower()
        if strategy == "uniform":
            proba = jnp.full((k,), 1.0 / k, jnp.float32)
        elif strategy == "prior":
            onehot = jax.nn.one_hot(y.astype(jnp.int32), k)
            counts = preduce(jnp.sum(w[:, None] * onehot, axis=0), axis_name)
            proba = counts / jnp.maximum(jnp.sum(counts), 1e-30)
        else:
            proba = jax.nn.one_hot(jnp.asarray(self.constant, jnp.int32), k)
        # reference: rawPrediction = log(probability) (`DummyClassifier.scala:100-116`)
        raw = jnp.log(jnp.maximum(proba, 1e-30))
        return {"proba": proba, "raw": raw}

    def predict_proba_fn(self, params, X):
        return jnp.broadcast_to(params["proba"], (X.shape[0],) + params["proba"].shape)

    def predict_raw_fn(self, params, X):
        return jnp.broadcast_to(params["raw"], (X.shape[0],) + params["raw"].shape)

    def predict_fn(self, params, X):
        return jnp.argmax(self.predict_proba_fn(params, X), axis=-1).astype(
            jnp.float32
        )

    def model_from_params(self, params, num_features, num_classes=None):
        return DummyClassificationModel(
            params=params,
            num_features=num_features,
            num_classes=num_classes or 2,
            **self.get_params(),
        )


class DummyClassificationModel(ClassificationModel, DummyClassifier):
    def predict_proba(self, X):
        return self.predict_proba_fn(self.params, as_f32(X))

    def predict_raw(self, X):
        return self.predict_raw_fn(self.params, as_f32(X))
