"""Bagging meta-estimators (SubBag: bootstrap rows + random feature subspaces).

Re-designs `BaggingClassifier.scala` / `BaggingRegressor.scala` for XLA: the
reference fits ``numBaseLearners`` members in driver thread-pool Futures,
each member a full Spark job over a sampled RDD (`BaggingClassifier.scala:
180-201`); here ALL members train in a single ``vmap``-ed XLA program over
per-member (bootstrap-weight, feature-mask, key) axes, sharing one binning
context.  Sampling semantics match ``RDD.sample`` (Poisson counts for
replacement=true — the Spark sampler is Poisson, not multinomial — and
Bernoulli masks otherwise) and ``subspace()``'s Bernoulli feature masks with
per-member ``seed + i`` keys (`HasSubBag.scala:69-79`).

Voting (`BaggingClassifier.scala:260-287`): hard = one-hot votes of member
predictions, soft = summed member probabilities; probability = raw /
numModels; prediction = argmax raw (Spark's raw2prediction path).
BaggingRegressionModel predicts the unweighted mean
(`BaggingRegressor.scala:221-228`).

Distributed: ``fit(..., mesh=...)`` places the job on BOTH mesh axes —
rows shard over "data" (the reference's row-partitioned RDDs,
`BaggingRegressor.scala:149-150`; no device holds the full dataset) and
members shard over "member" (the reference's driver thread-pool Futures).
Each device fuse-fits its member block on its row shard with histograms
psum-ed over "data", keeping the single-chip fit_forest fusion win.
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_ensemble_tpu.models.base import (
    BaseLearner,
    ClassificationModel,
    Estimator,
    RegressionModel,
    as_f32,
    cached_program,
    infer_num_classes,
    make_shared_fit_ctx,
    resolve_weights,
)
from spark_ensemble_tpu.models.tree import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)
from spark_ensemble_tpu.params import Param, gt_eq, in_array, in_range
from spark_ensemble_tpu.telemetry.events import FitTelemetry
from spark_ensemble_tpu.utils.instrumentation import instrumented_fit
from spark_ensemble_tpu.utils.random import bootstrap_weights, subspace_mask

logger = logging.getLogger("spark_ensemble_tpu")


class _BaggingParams(Estimator):
    """Reference `BaggingParams.scala:27-37` + `HasSubBag.scala:69-71`."""

    base_learner = Param(
        None, is_estimator=True,
        doc="learner template copied per member; defaults to a depth-5 "
        "histogram decision tree",
    )
    num_base_learners = Param(10, gt_eq(1), doc="ensemble size")
    replacement = Param(
        True,
        doc="bootstrap with replacement (Poisson sample weights) vs "
        "without (Bernoulli); reference SubBag semantics",
    )
    subsample_ratio = Param(
        1.0, in_range(0.0, 1.0, lower_inclusive=False),
        doc="per-member row sample ratio (enters as weights, not subsets)",
    )
    subspace_ratio = Param(
        1.0, in_range(0.0, 1.0, lower_inclusive=False),
        doc="per-member feature-subspace ratio (random subspaces)",
    )
    parallelism = Param(1, gt_eq(1), doc="API parity; members are vmapped")
    seed = Param(0, doc="PRNG seed for member sampling plans")

    def _member_plan(self, n: int, d: int, w: jax.Array):
        """Stacked per-member (fit weights, masks, keys), drawn in ONE
        jitted program: the eager per-member key loop it replaces cost a
        host->device round-trip per member — multi-ms each through the TPU
        tunnel (same fix as ``GBMParams._sampling_plan``).  Draws are
        bit-identical (same fold_in tree, same vmapped plan)."""
        m = int(self.num_base_learners)
        repl, ratio = bool(self.replacement), float(self.subsample_ratio)
        sub_ratio = float(self.subspace_ratio)

        def build():
            def plan_all(root, w):
                keys = jax.vmap(lambda i: jax.random.fold_in(root, i))(
                    jnp.arange(m)
                )

                def plan(key):
                    bag = bootstrap_weights(
                        jax.random.fold_in(key, 0), n, repl, ratio
                    )
                    mask = subspace_mask(
                        jax.random.fold_in(key, 1), d, sub_ratio
                    )
                    return bag * w, mask

                fit_w, masks = jax.vmap(plan)(keys)
                return fit_w, masks, keys

            return jax.jit(plan_all)

        plan = cached_program(
            ("bagging_member_plan", m, n, d, repl, ratio, sub_ratio), build
        )
        return plan(jax.random.PRNGKey(self.seed), w)

    def _fit_members_guarded(self, fit_all, args, telem, label):
        """One fused all-member fit under the robustness runtime: a chaos
        transient-fault hook plus retry/backoff around the dispatch (the
        bagging analogue of the round-chunk retry in the sequential
        families — there is exactly one dispatch to protect)."""
        from spark_ensemble_tpu.robustness.chaos import controller
        from spark_ensemble_tpu.robustness.retry import retry_call

        ctl = controller()
        site = f"{label}:fit_all"

        def attempt():
            ctl.transient(site)
            return fit_all(*args)

        return retry_call(
            attempt, policy=self._retry_policy(),
            op=f"{label}.fit_all", telem=telem,
        )

    def _drop_bad_members(self, members, member_masks, m, guard):
        """Apply the ``on_nonfinite`` policy to the fitted member stack:
        members whose params picked up NaN (chaos ``nan_grad``, or a real
        numeric blow-up in one bootstrap fit) are TRUE-dropped — bagging
        prediction averages members with equal weight, so a poisoned member
        cannot be neutralized by weighting.  ``stop_early`` keeps the prefix
        before the first bad member; ``skip_round``/``halve_step`` (no step
        size to halve in one fused fit) keep every finite member.  Returns
        ``(members, member_masks, kept_count)``."""
        if guard is None or not guard.active:
            return members, member_masks, m
        flags = guard.member_flags(members)
        if flags is None or not flags.any():
            return members, member_masks, m
        first = int(np.flatnonzero(flags)[0])
        if guard.policy == "raise":
            guard.raise_error(first, what="member params")
        if guard.policy == "stop_early":
            keep = np.arange(first)
            action = "stop_early"
        else:
            keep = np.flatnonzero(~flags)
            action = "skip_round"
        if keep.size == 0:
            # a usable bagging model needs at least one finite member
            guard.raise_error(first, what="every member's params")
        guard.record(
            first, action, members_dropped=int(m - keep.size),
            members_kept=int(keep.size),
        )
        idx = jnp.asarray(keep)
        members = jax.tree_util.tree_map(lambda x: x[idx], members)
        return members, member_masks[idx], int(keep.size)

    @staticmethod
    def _shard_rows_and_members(mesh: Mesh, base, ctx, y, fit_w, masks, keys):
        """(data x member) placement — the TPU mapping of the reference's
        TWO parallel axes at once: rows live partitioned across executors
        (`BaggingRegressor.scala:149-150`) while members train concurrently
        from the driver's thread pool (`BaggingClassifier.scala:180-201`).

        Rows (the binning ctx, y, and fit_w's row dim) shard over "data"
        (no device holds the full dataset — the scaling axis); members
        (fit_w's member dim, masks, keys) shard over "member".  Each device
        then fuse-fits its member block on its row shard, psum-ing
        histograms over "data" (``fit_many_from_ctx(axis_name=...)``).

        Member counts pad to the member-axis size with zero-weight phantom
        members (trimmed by the caller); rows pad with zero-weight rows —
        both leave every statistic unchanged.  On a data-only mesh (no
        "member" axis) members replicate and only rows shard."""
        from spark_ensemble_tpu.parallel.mesh import (
            mesh_row_spec,
            mesh_sizes,
            pad_rows,
            shard_ctx_rows,
        )

        data_size, member_size = mesh_sizes(mesh)
        ax = mesh_row_spec(mesh)
        mem = "member" if "member" in mesh.axis_names else None
        n = y.shape[0]
        n_pad = n + (-n) % data_size
        m = fit_w.shape[0]
        m_pad = m + (-m) % member_size
        if m_pad != m:
            pad = [(0, m_pad - m)]
            fit_w = jnp.pad(fit_w, pad + [(0, 0)])
            masks = jnp.pad(masks, pad + [(0, 0)], constant_values=True)
            keys = jnp.pad(keys, pad + [(0, 0)] * (keys.ndim - 1))
        ctx, ctx_specs = shard_ctx_rows(mesh, base, ctx, n_pad)
        fit_w = jnp.pad(fit_w, [(0, 0), (0, n_pad - n)])
        return (
            ctx,
            ctx_specs,
            ax,
            mem,
            jax.device_put(pad_rows(y, n_pad), NamedSharding(mesh, P(ax))),
            jax.device_put(fit_w, NamedSharding(mesh, P(mem, ax))),
            jax.device_put(masks, NamedSharding(mesh, P(mem, None))),
            jax.device_put(keys, NamedSharding(mesh, P(mem, None))),
        )


def _fused_fit_block(base: BaseLearner, axis_name=None):
    """The fused all-member fit body (`fit_many_from_ctx` — trees fold the
    member axis into one histogram matmul per level)."""

    def block(ctx, y, fit_w, masks, keys):
        return base.fit_many_from_ctx(
            ctx,
            jnp.broadcast_to(y[:, None], (y.shape[0], fit_w.shape[0])),
            fit_w.T,
            masks,
            keys,
            axis_name=axis_name,
        )

    return block


def _build_fit_all(base: BaseLearner, mesh=None, ctx_specs=None, ax=None, mem=None):
    """All-member fit program.  Single-device: the fused multi-member path.
    Mesh: the SAME fused body shard_mapped over (data x member) — each
    device fuse-fits its member block on its row shard with psum-ed
    histograms, so the mesh path keeps the fit_forest fusion win."""
    if mesh is None:
        return jax.jit(_fused_fit_block(base))
    from spark_ensemble_tpu.compat import shard_map

    return jax.jit(
        shard_map(
            _fused_fit_block(base, axis_name=ax),
            mesh=mesh,
            in_specs=(
                ctx_specs,
                P(ax),  # y
                P(mem, ax),  # fit_w
                P(mem, None),  # masks
                P(mem, None),  # keys
            ),
            out_specs=P(mem),
            check_vma=False,
        )
    )


class BaggingRegressor(_BaggingParams):
    is_classifier = False

    def _base(self) -> BaseLearner:
        return self.base_learner or DecisionTreeRegressor()

    @instrumented_fit
    def fit(self, X, y, sample_weight=None, mesh=None) -> "BaggingRegressionModel":
        X, y = as_f32(X), as_f32(y)
        self._validate_fit_inputs(X, y)
        w = resolve_weights(y, sample_weight)
        n, d = X.shape
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = make_shared_fit_ctx(base, X)
        fit_w, masks, keys = self._member_plan(n, d, w)
        member_masks = masks
        ctx_specs = ax = mem = None
        if mesh is not None:
            ctx, ctx_specs, ax, mem, y, fit_w, masks, keys = (
                self._shard_rows_and_members(
                    mesh, base, ctx, y, fit_w, masks, keys
                )
            )
        fit_all = cached_program(
            ("bagging_fit", base.config_key(), mesh),
            lambda: _build_fit_all(base, mesh, ctx_specs, ax, mem),
        )
        telem = FitTelemetry.start(self, n=n, d=d)
        telem.phase_mark("setup")
        t_fit = time.perf_counter()
        label = type(self).__name__
        members = self._fit_members_guarded(
            fit_all, (ctx, y, fit_w, masks, keys), telem, label
        )
        m = int(self.num_base_learners)
        if telem.enabled:
            # every member fits in ONE fused program — all m "rounds" share
            # the fenced program time evenly
            telem.round_chunk(0, m, t_fit, fence=members)
        members = jax.tree_util.tree_map(lambda x: x[:m], members)
        from spark_ensemble_tpu.robustness.chaos import controller

        members = controller().poison_member_stack(f"{label}:fit_all", members)
        members, member_masks, m = self._drop_bad_members(
            members, member_masks, m, self._numeric_guard(telem)
        )
        model = BaggingRegressionModel(
            params={"members": members, "masks": member_masks},
            num_features=d,
            num_members=m,
            **self.get_params(),
        )
        telem.finish(model=model, members=m)
        return model


class BaggingRegressionModel(RegressionModel, BaggingRegressor):
    def __init__(self, num_members=None, **kwargs):
        super().__init__(**kwargs)
        # pre-robustness saves carry no num_members: every planned member
        # was fitted, so the param is the count
        self.num_members = (
            int(num_members) if num_members is not None
            else int(self.num_base_learners)
        )

    def member_predictions(self, X):
        base = self._base()
        return self._predict_program(  # [m, n]
            "members",
            lambda members, Xq: base.predict_many_fn(members, Xq),
            (self.params["members"],),
            X,
            out_row_axis=1,
        )

    def predict(self, X):
        return jnp.mean(self.member_predictions(X), axis=0)


class BaggingClassifier(_BaggingParams):
    voting_strategy = Param(
        "hard", in_array(["hard", "soft"]),
        doc="'hard' majority-votes member classes; 'soft' averages "
        "member probabilities",
    )

    is_classifier = True

    def _base(self) -> BaseLearner:
        return self.base_learner or DecisionTreeClassifier()

    @instrumented_fit
    def fit(
        self, X, y, sample_weight=None, mesh=None, num_classes=None
    ) -> "BaggingClassificationModel":
        X, y = as_f32(X), as_f32(y)
        self._validate_fit_inputs(X, y)
        w = resolve_weights(y, sample_weight)
        num_classes = infer_num_classes(y, num_classes)
        n, d = X.shape
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = make_shared_fit_ctx(base, X, num_classes)
        fit_w, masks, keys = self._member_plan(n, d, w)
        member_masks = masks
        ctx_specs = ax = mem = None
        if mesh is not None:
            ctx, ctx_specs, ax, mem, y, fit_w, masks, keys = (
                self._shard_rows_and_members(
                    mesh, base, ctx, y, fit_w, masks, keys
                )
            )
        fit_all = cached_program(
            ("bagging_fit_cls", base.config_key(), num_classes, mesh),
            lambda: _build_fit_all(base, mesh, ctx_specs, ax, mem),
        )
        telem = FitTelemetry.start(self, n=n, d=d, num_classes=int(num_classes))
        telem.phase_mark("setup")
        t_fit = time.perf_counter()
        label = type(self).__name__
        members = self._fit_members_guarded(
            fit_all, (ctx, y, fit_w, masks, keys), telem, label
        )
        m = int(self.num_base_learners)
        if telem.enabled:
            # every member fits in ONE fused program — all m "rounds" share
            # the fenced program time evenly
            telem.round_chunk(0, m, t_fit, fence=members)
        members = jax.tree_util.tree_map(lambda x: x[:m], members)
        from spark_ensemble_tpu.robustness.chaos import controller

        members = controller().poison_member_stack(f"{label}:fit_all", members)
        members, member_masks, m = self._drop_bad_members(
            members, member_masks, m, self._numeric_guard(telem)
        )
        model = BaggingClassificationModel(
            params={"members": members, "masks": member_masks},
            num_features=d,
            num_classes=num_classes,
            num_members=m,
            **self.get_params(),
        )
        telem.finish(model=model, members=m)
        return model


class BaggingClassificationModel(ClassificationModel, BaggingClassifier):
    def __init__(self, num_members=None, **kwargs):
        super().__init__(**kwargs)
        # pre-robustness saves carry no num_members: see regression model
        self.num_members = (
            int(num_members) if num_members is not None
            else int(self.num_base_learners)
        )

    def member_class_predictions(self, X):
        """Per-member class predictions ``f32[m, n]`` (the reference tests'
        member-agreement/diversity assertions use these,
        `BaggingClassifierSuite.scala:80-155`)."""
        base = self._base()
        return self._predict_program(
            "member_preds",
            lambda members, Xq: base.predict_many_fn(members, Xq),
            (self.params["members"],),
            X,
            out_row_axis=1,
        )

    def predict_raw(self, X):
        base = self._base()
        if self.voting_strategy.lower() == "soft":
            name, builder = "raw_soft", lambda members, Xq: jnp.sum(
                base.predict_proba_many_fn(members, Xq), axis=0
            )
        else:
            k = self.num_classes
            name, builder = "raw_hard", lambda members, Xq: jnp.sum(
                jax.nn.one_hot(
                    base.predict_many_fn(members, Xq).astype(jnp.int32), k
                ),
                axis=0,
            )
        return self._predict_program(
            name, builder, (self.params["members"],), X
        )

    def predict_proba(self, X):
        # reference raw2probabilityInPlace scales by 1/numModels
        # (`BaggingClassifier.scala:285-287`); numModels is the FITTED
        # count — the guard may have dropped non-finite members
        return self.predict_raw(X) / self.num_members

    def predict(self, X):
        return jnp.argmax(self.predict_raw(X), axis=-1).astype(jnp.float32)
