"""Bagging meta-estimators (SubBag: bootstrap rows + random feature subspaces).

Re-designs `BaggingClassifier.scala` / `BaggingRegressor.scala` for XLA: the
reference fits ``numBaseLearners`` members in driver thread-pool Futures,
each member a full Spark job over a sampled RDD (`BaggingClassifier.scala:
180-201`); here ALL members train in a single ``vmap``-ed XLA program over
per-member (bootstrap-weight, feature-mask, key) axes, sharing one binning
context.  Sampling semantics match ``RDD.sample`` (Poisson counts for
replacement=true — the Spark sampler is Poisson, not multinomial — and
Bernoulli masks otherwise) and ``subspace()``'s Bernoulli feature masks with
per-member ``seed + i`` keys (`HasSubBag.scala:69-79`).

Voting (`BaggingClassifier.scala:260-287`): hard = one-hot votes of member
predictions, soft = summed member probabilities; probability = raw /
numModels; prediction = argmax raw (Spark's raw2prediction path).
BaggingRegressionModel predicts the unweighted mean
(`BaggingRegressor.scala:221-228`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_ensemble_tpu.models.base import (
    BaseLearner,
    ClassificationModel,
    Estimator,
    RegressionModel,
    as_f32,
    cached_program,
    infer_num_classes,
    resolve_weights,
)
from spark_ensemble_tpu.models.tree import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)
from spark_ensemble_tpu.params import Param, gt_eq, in_array, in_range
from spark_ensemble_tpu.utils.instrumentation import instrumented_fit
from spark_ensemble_tpu.utils.random import bootstrap_weights, subspace_mask


class _BaggingParams(Estimator):
    """Reference `BaggingParams.scala:27-37` + `HasSubBag.scala:69-71`."""

    base_learner = Param(None, is_estimator=True)
    num_base_learners = Param(10, gt_eq(1))
    replacement = Param(True)
    subsample_ratio = Param(1.0, in_range(0.0, 1.0, lower_inclusive=False))
    subspace_ratio = Param(1.0, in_range(0.0, 1.0, lower_inclusive=False))
    parallelism = Param(1, gt_eq(1), doc="API parity; members are vmapped")
    seed = Param(0)

    def _member_plan(self, n: int, d: int, w: jax.Array):
        """Stacked per-member (fit weights, masks, keys)."""
        root = jax.random.PRNGKey(self.seed)
        keys = jnp.stack(
            [jax.random.fold_in(root, i) for i in range(self.num_base_learners)]
        )
        repl, ratio = bool(self.replacement), float(self.subsample_ratio)

        def plan(key):
            bag = bootstrap_weights(jax.random.fold_in(key, 0), n, repl, ratio)
            mask = subspace_mask(jax.random.fold_in(key, 1), d, self.subspace_ratio)
            return bag * w, mask

        fit_w, masks = jax.vmap(plan)(keys)
        return fit_w, masks, keys

    @staticmethod
    def _shard_members(mesh: Mesh, ctx, y, fit_w, masks, keys):
        """Shard the member axis over ALL mesh devices and replicate the
        shared data — the TPU mapping of the reference's driver thread-pool
        member parallelism (`BaggingClassifier.scala:180-201`,
        `parallel/mesh.py` member axis).  The same vmapped fit program is
        then auto-partitioned by XLA along the member axis, so every device
        trains its own block of members and the fitted forest stays sharded
        across devices.  A member count that does not divide the device
        count is padded with zero-weight phantom members (trimmed by the
        caller); phantom fits are all-zero-weight degenerate models that
        cost one extra member slot per device at most."""
        n_dev = mesh.devices.size
        m = fit_w.shape[0]
        m_pad = m + (-m) % n_dev
        if m_pad != m:
            pad = [(0, m_pad - m)]
            fit_w = jnp.pad(fit_w, pad + [(0, 0)])
            masks = jnp.pad(masks, pad + [(0, 0)], constant_values=True)
            keys = jnp.pad(keys, pad + [(0, 0)] * (keys.ndim - 1))
        member = NamedSharding(mesh, P(tuple(mesh.axis_names)))
        rep = NamedSharding(mesh, P())
        ctx = jax.device_put(ctx, jax.tree_util.tree_map(lambda _: rep, ctx))
        y = jax.device_put(y, rep)
        return (
            ctx,
            y,
            jax.device_put(fit_w, member),
            jax.device_put(masks, member),
            jax.device_put(keys, member),
        )


def _build_fit_all(base: BaseLearner, sharded: bool):
    """All-member fit program.  Single-device: the fused multi-member path
    (``fit_many_from_ctx`` — trees fold the member axis into one histogram
    matmul per level).  Mesh-sharded members: the vmapped per-member program,
    which GSPMD partitions along the member axis across devices."""
    if sharded:
        return jax.jit(
            lambda ctx, y, fit_w, masks, keys: jax.vmap(
                lambda fw, m, k: base.fit_from_ctx(ctx, y, fw, m, k)
            )(fit_w, masks, keys)
        )
    return jax.jit(
        lambda ctx, y, fit_w, masks, keys: base.fit_many_from_ctx(
            ctx,
            jnp.broadcast_to(y[:, None], (y.shape[0], fit_w.shape[0])),
            fit_w.T,
            masks,
            keys,
        )
    )


class BaggingRegressor(_BaggingParams):
    is_classifier = False

    def _base(self) -> BaseLearner:
        return self.base_learner or DecisionTreeRegressor()

    @instrumented_fit
    def fit(self, X, y, sample_weight=None, mesh=None) -> "BaggingRegressionModel":
        X, y = as_f32(X), as_f32(y)
        w = resolve_weights(y, sample_weight)
        n, d = X.shape
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = base.make_fit_ctx(X)
        fit_w, masks, keys = self._member_plan(n, d, w)
        member_masks = masks
        if mesh is not None:
            ctx, y, fit_w, masks, keys = self._shard_members(
                mesh, ctx, y, fit_w, masks, keys
            )
        fit_all = cached_program(
            ("bagging_fit", base.config_key(), mesh is not None),
            lambda: _build_fit_all(base, sharded=mesh is not None),
        )
        members = fit_all(ctx, y, fit_w, masks, keys)
        members = jax.tree_util.tree_map(
            lambda x: x[: self.num_base_learners], members
        )
        return BaggingRegressionModel(
            params={"members": members, "masks": member_masks},
            num_features=d,
            **self.get_params(),
        )


class BaggingRegressionModel(RegressionModel, BaggingRegressor):
    def member_predictions(self, X):
        base = self._base()
        fn = self._cached_jit(
            "members", lambda members, Xq: base.predict_many_fn(members, Xq)
        )
        return fn(self.params["members"], as_f32(X))  # [m, n]

    def predict(self, X):
        return jnp.mean(self.member_predictions(X), axis=0)


class BaggingClassifier(_BaggingParams):
    voting_strategy = Param("hard", in_array(["hard", "soft"]))

    is_classifier = True

    def _base(self) -> BaseLearner:
        return self.base_learner or DecisionTreeClassifier()

    @instrumented_fit
    def fit(
        self, X, y, sample_weight=None, mesh=None, num_classes=None
    ) -> "BaggingClassificationModel":
        X, y = as_f32(X), as_f32(y)
        w = resolve_weights(y, sample_weight)
        num_classes = infer_num_classes(y, num_classes)
        n, d = X.shape
        # snapshot the base learner: cached round-step closures must not
        # observe later set_params mutations of the caller's instance
        base = self._base().copy()
        ctx = base.make_fit_ctx(X, num_classes)
        fit_w, masks, keys = self._member_plan(n, d, w)
        member_masks = masks
        if mesh is not None:
            ctx, y, fit_w, masks, keys = self._shard_members(
                mesh, ctx, y, fit_w, masks, keys
            )
        fit_all = cached_program(
            ("bagging_fit_cls", base.config_key(), num_classes, mesh is not None),
            lambda: _build_fit_all(base, sharded=mesh is not None),
        )
        members = fit_all(ctx, y, fit_w, masks, keys)
        members = jax.tree_util.tree_map(
            lambda x: x[: self.num_base_learners], members
        )
        return BaggingClassificationModel(
            params={"members": members, "masks": member_masks},
            num_features=d,
            num_classes=num_classes,
            **self.get_params(),
        )


class BaggingClassificationModel(ClassificationModel, BaggingClassifier):
    def member_class_predictions(self, X):
        """Per-member class predictions ``f32[m, n]`` (the reference tests'
        member-agreement/diversity assertions use these,
        `BaggingClassifierSuite.scala:80-155`)."""
        base = self._base()
        fn = self._cached_jit(
            "member_preds", lambda members, Xq: base.predict_many_fn(members, Xq)
        )
        return fn(self.params["members"], as_f32(X))

    def predict_raw(self, X):
        base = self._base()
        if self.voting_strategy.lower() == "soft":
            fn = self._cached_jit(
                "raw_soft",
                lambda members, Xq: jnp.sum(
                    base.predict_proba_many_fn(members, Xq), axis=0
                ),
            )
        else:
            k = self.num_classes
            fn = self._cached_jit(
                "raw_hard",
                lambda members, Xq: jnp.sum(
                    jax.nn.one_hot(
                        base.predict_many_fn(members, Xq).astype(jnp.int32), k
                    ),
                    axis=0,
                ),
            )
        return fn(self.params["members"], as_f32(X))

    def predict_proba(self, X):
        # reference raw2probabilityInPlace scales by 1/numModels
        # (`BaggingClassifier.scala:285-287`)
        return self.predict_raw(X) / self.num_base_learners

    def predict(self, X):
        return jnp.argmax(self.predict_raw(X), axis=-1).astype(jnp.float32)
