"""Decision-tree base learners over the histogram kernels in ``ops/tree.py``.

The reference's tests use Spark MLlib ``DecisionTree{Regressor,Classifier}``
as the base learner everywhere; these are the TPU-native equivalents.  The
variance (regression) and gini (classification) split criteria are both
instances of the unified sum-of-squares gain in ``ops.tree.fit_tree`` (one
kernel, k target columns).  Defaults mirror Spark MLlib: ``max_depth=5``,
``min_info_gain=0.0``; ``max_bins`` defaults to 64 (Spark: 32) since
histogram bins are cheap on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.models.base import (
    Static,
    static_value,
    BaseLearner,
    ClassificationModel,
    RegressionModel,
    as_f32,
)
from spark_ensemble_tpu.ops.binning import bin_features, compute_bins
from spark_ensemble_tpu.ops.tree import (
    Tree,
    feature_gains,
    fit_forest,
    fit_tree,
    predict_chunked_rows,
    predict_forest,
    predict_tree,
)
from spark_ensemble_tpu.params import Param, gt_eq, in_array, in_range


def _renorm_proba(p):
    """Leaf class distribution -> probability vector: clip tiny negative
    fallback artifacts, renormalize.  ONE definition so predict_proba and
    the routing-reuse fit_and_proba stay exactly in sync."""
    p = jnp.maximum(p, 0.0)
    return p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)


class _TreeLearner(BaseLearner):
    max_depth = Param(
        5, in_range(1, 20),
        doc="tree depth; the dense heap layout always allocates "
        "2^max_depth leaves (static shapes)",
    )
    max_bins = Param(
        64, gt_eq(2),
        doc="histogram bins per feature (quantile binning at fit time)",
    )
    min_info_gain = Param(
        0.0, gt_eq(0.0), doc="minimum split gain; below it a node leafs"
    )
    hist_precision = Param(
        "highest",
        in_array(["highest", "high", "default", "pallas"]),
        doc="MXU precision of the histogram/leaf statistic matmuls: "
        "'highest' = exact f32 (6 bf16 passes, bit-equal to scatter); "
        "'high' = 3-pass bf16x3 (~f32 mantissa); 'default' = single-pass "
        "bf16 (fastest — statistics carry ~3 decimal digits, like a "
        "subsampled histogram); 'pallas' = fused-member level histograms "
        "as a VMEM-resident pallas kernel (ops/pallas_hist.py, 2-pass "
        "hi/lo ~16-bit statistics, no bin-one-hot HBM operand; TPU "
        "backends — elsewhere it runs interpreted, tests only).  Routing "
        "stays exact on every setting.",
    )
    hist = Param(
        "auto",
        in_array(["auto", "scatter", "matmul", "stream", "fused"]),
        doc="Histogram accumulation backend (ops/tree.py): 'auto' picks "
        "the one-hot matmul on accelerators (MXU path), segment_sum "
        "scatter-adds on CPU, and the row-chunked 'stream' tier when the "
        "matmul's [n, d*bins] one-hot outgrows its budget; 'stream' "
        "forces the chunked tier — the HBM-scale path (>~1M rows) whose "
        "per-level traffic is one read of the compact binned features "
        "instead of materialized full-n one-hots; 'fused' runs each tree "
        "level as ONE pallas kernel over bit-packed 4/8-bit bins "
        "(docs/fused_kernel.md): 4-8x less HBM on the dominant read, "
        "in-kernel routing, 3-term bf16 histogram statistics (f32-grade; "
        "predictions tight-allclose to 'matmul'; max_bins <= 256, falls "
        "back to matmul/stream over the VMEM budget or off-TPU at scale).",
    )
    seed = Param(0, doc="unused by the deterministic kernels; API parity")

    def make_fit_ctx(self, X, num_classes=None):
        X = as_f32(X)
        bins = compute_bins(X, self.max_bins)
        Xb = bin_features(X, bins)
        return {"Xb": Xb, "thresholds": bins.thresholds, "num_classes": Static(num_classes)}

    def _targets(self, ctx, y) -> jax.Array:
        raise NotImplementedError

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None,
                     return_leaf=False):
        return fit_tree(
            ctx["Xb"],
            self._targets(ctx, y),
            w,
            ctx["thresholds"],
            feature_mask,
            max_depth=self.max_depth,
            max_bins=self.max_bins,
            min_info_gain=self.min_info_gain,
            axis_name=axis_name,
            hist=self.hist,
            hist_precision=self.hist_precision,
            return_leaf=return_leaf,
        )

    def _targets_many(self, ctx, ys) -> jax.Array:
        """[n, M] member target columns -> [n, M, k] tree targets."""
        raise NotImplementedError

    def fit_many_from_ctx(self, ctx, ys, ws, feature_masks, keys,
                          axis_name=None, return_leaf=False):
        """All members in ONE fused forest fit: the member axis folds into
        the histogram matmul's M dim (``ops.tree.fit_forest``) instead of a
        vmap that re-streams the shared bin-one-hot per member."""
        return fit_forest(
            ctx["Xb"],
            self._targets_many(ctx, ys),
            ws,
            ctx["thresholds"],
            feature_masks,
            max_depth=self.max_depth,
            max_bins=self.max_bins,
            min_info_gain=self.min_info_gain,
            axis_name=axis_name,
            hist=self.hist,
            hist_precision=self.hist_precision,
            return_leaf=return_leaf,
        )

    def _fit_and_leaf_pred(self, ctx, y, w, feature_mask, key, axis_name):
        """Fit + the selected leaf-value vector per row -> (tree,
        pred[n, k]): the shared core of the routing-reuse methods."""
        tree, node = self.fit_from_ctx(
            ctx, y, w, feature_mask, key, axis_name=axis_name,
            return_leaf=True,
        )
        L = tree.leaf_value.shape[0]

        def rows(nd):  # row-chunked past the one-hot budget (HBM scale)
            oh = jax.nn.one_hot(nd[:, 0], L, dtype=jnp.float32)
            return jax.lax.dot_general(
                oh, tree.leaf_value, (((1,), (0,)), ((), ())),
                precision=(
                    jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST
                ),
            )  # [c, k]

        return tree, predict_chunked_rows(rows, node[:, None], 1, L)

    def fit_and_direction(self, ctx, y, w, feature_mask, key, X,
                          axis_name=None):
        """The tree fit already routed every row to its leaf: contract the
        returned leaf ids against the leaf values instead of re-walking
        the tree (bit-identical — binned and raw routing agree,
        `test_binned_and_raw_predict_agree`; exact one-hot selection)."""
        tree, pred = self._fit_and_leaf_pred(
            ctx, y, w, feature_mask, key, axis_name
        )
        return tree, self._direction_from_leaf(pred)

    def fit_many_and_directions(self, ctx, ys, ws, feature_masks, keys, X,
                                axis_name=None):
        """Fused-member fit with leaf-id reuse (see ``fit_and_direction``):
        one [n, M, leaves] one-hot contraction replaces the per-round
        forest predict re-route."""
        trees, node = self.fit_many_from_ctx(
            ctx, ys, ws, feature_masks, keys, axis_name=axis_name,
            return_leaf=True,
        )
        M, L = trees.leaf_value.shape[:2]

        def rows(nd):  # row-chunked past the one-hot budget (HBM scale)
            oh = jax.nn.one_hot(nd, L, dtype=jnp.float32)  # [c, M, L]
            return jnp.einsum(
                "nml,mlk->nmk", oh, trees.leaf_value,
                precision=(
                    jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST
                ),
            )

        preds = predict_chunked_rows(rows, node, M, L)
        return trees, self._direction_from_leaf(preds)

    def _direction_from_leaf(self, pred):
        """Leaf-value selection -> the member's scalar prediction."""
        raise NotImplementedError

    def ctx_gather_rows(self, ctx, idx):
        """Row-compact the binned matrix only; thresholds/num_classes are
        replicated (gradient-based row sampling, models/gbm.py)."""
        return {**ctx, "Xb": ctx["Xb"][idx]}

    def ctx_specs(self, ctx, data_axis):
        from jax.sharding import PartitionSpec as P

        return {
            "Xb": P(data_axis, None),
            "thresholds": P(),
            "num_classes": ctx["num_classes"],
        }

    def feature_gains_fn(self, params: Tree, d: int):
        return feature_gains(params, d)


class DecisionTreeRegressor(_TreeLearner):
    is_classifier = False

    def _direction_from_leaf(self, pred):
        return pred[..., 0]

    def _targets(self, ctx, y):
        return y[:, None]

    def _targets_many(self, ctx, ys):
        return ys[:, :, None]

    def predict_fn(self, params: Tree, X):
        return predict_tree(params, X)[:, 0]

    def predict_many_fn(self, params: Tree, X):
        return predict_forest(params, X)[:, :, 0]

    def model_from_params(self, params, num_features, num_classes=None):
        return DecisionTreeRegressionModel(
            params=params, num_features=num_features, **self.get_params()
        )


class DecisionTreeRegressionModel(RegressionModel, DecisionTreeRegressor):
    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))


class DecisionTreeClassifier(_TreeLearner):
    is_classifier = True

    def _direction_from_leaf(self, pred):
        # parity with predict_fn: argmax over the leaf class distribution
        return jnp.argmax(pred, axis=-1).astype(jnp.float32)

    def fit_and_proba(self, ctx, y, w, feature_mask, key, X,
                      axis_name=None):
        """Leaf-id reuse for SAMME.R: the selected leaf distribution,
        renormalized exactly like ``predict_proba_fn``."""
        tree, leaf_pred = self._fit_and_leaf_pred(
            ctx, y, w, feature_mask, key, axis_name
        )
        return tree, _renorm_proba(leaf_pred)

    def _targets(self, ctx, y):
        return jax.nn.one_hot(y.astype(jnp.int32), static_value(ctx["num_classes"]))

    def _targets_many(self, ctx, ys):
        return jax.nn.one_hot(
            ys.astype(jnp.int32), static_value(ctx["num_classes"])
        )

    def predict_proba_fn(self, params: Tree, X):
        # leaf values are weighted one-hot means: a probability vector up to
        # zero-weight fallbacks; renormalize defensively
        return _renorm_proba(predict_tree(params, X))

    def predict_many_fn(self, params: Tree, X):
        return jnp.argmax(predict_forest(params, X), axis=-1).astype(jnp.float32)

    def predict_proba_many_fn(self, params: Tree, X):
        return _renorm_proba(predict_forest(params, X))

    def predict_raw_fn(self, params: Tree, X):
        return predict_tree(params, X)

    def predict_fn(self, params: Tree, X):
        return jnp.argmax(predict_tree(params, X), axis=-1).astype(jnp.float32)

    def model_from_params(self, params, num_features, num_classes=None):
        return DecisionTreeClassificationModel(
            params=params,
            num_features=num_features,
            num_classes=num_classes or 2,
            **self.get_params(),
        )


class DecisionTreeClassificationModel(ClassificationModel, DecisionTreeClassifier):
    def predict_proba(self, X):
        return self.predict_proba_fn(self.params, as_f32(X))

    def predict_raw(self, X):
        return self.predict_raw_fn(self.params, as_f32(X))

    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))
