"""Multilayer-perceptron base learners (classifier and regressor).

The reference accepts ANY Spark ML ``Predictor`` as an ensemble member
(existential base-learner types, `ensemble/package.scala:32-67`); Spark
MLlib's ``MultilayerPerceptronClassifier`` is its stock nonlinear choice.
This module is the TPU-native equivalent: a fixed-topology MLP whose fit is
a pure, jittable, vmappable member of the BaseLearner protocol — a static
count of full-batch Adam steps inside ``lax.scan`` (no data-dependent
control flow, so members fuse under ``vmap`` and the program compiles
once), weighted loss, features standardized internally.  The forward pass
is back-to-back ``[n,h] @ [h,h']`` matmuls — MXU-shaped by construction,
unlike the tree learners whose MXU mapping had to be designed (ops/tree.py).

SPMD contract (``axis_name``): the fit computes SHARD-LOCAL per-example
loss sums normalized by the GLOBAL (psum-ed) weight mass, then psums the
gradient pytree explicitly — an objective that psums internally would
yield shard-local gradients (the ``psum``-transpose trap documented at
`ops/linesearch.py:130-138`).  The L2 term's gradient is added once AFTER
the reduction so it is not multiplied by the shard count.  Every shard
then applies the identical Adam update, mirroring how the reference's
executors would each hold the same broadcast model between
``treeAggregate`` passes (`GBMClassifier.scala:344-355`).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from spark_ensemble_tpu.models.base import (
    BaseLearner,
    ClassificationModel,
    RegressionModel,
    Static,
    as_f32,
    static_value,
)
from spark_ensemble_tpu.models.linear import _apply_mask, _feature_stats
from spark_ensemble_tpu.ops.collective import preduce
from spark_ensemble_tpu.params import Param, gt, gt_eq, in_array


def _hidden_sizes_ok(v):
    # a scalar (the sklearn-style `hidden_layer_sizes=64` spelling) must
    # fail as an invalid value, not a TypeError from len()
    if not isinstance(v, (list, tuple)):
        return False
    return len(v) >= 1 and all(int(h) == h and h >= 1 for h in v)


class _MLPBase(BaseLearner):
    hidden_layer_sizes = Param(
        (64,),
        _hidden_sizes_ok,
        doc="widths of the hidden layers (static topology: part of the "
        "compiled program's shape, like Spark MLP's `layers` param)",
    )
    activation = Param(
        "relu", in_array(["relu", "tanh"]), doc="hidden-layer nonlinearity"
    )
    max_iter = Param(
        200,
        gt_eq(1),
        doc="full-batch Adam steps; a STATIC count (lax.scan) so member "
        "fits stay fusable — convergence-based stopping would make the "
        "program shape data-dependent",
    )
    learning_rate_init = Param(1e-2, gt(0.0), doc="Adam learning rate")
    reg_param = Param(1e-4, gt_eq(0.0), doc="L2 penalty on weights (not biases)")
    seed = Param(0, doc="weight-init PRNG seed")

    def _sizes(self, d: int, out_dim: int):
        return (d, *[int(h) for h in self.hidden_layer_sizes], out_dim)

    def _act(self, z):
        return jax.nn.relu(z) if self.activation == "relu" else jnp.tanh(z)

    def _init_net(self, key, sizes):
        layers = []
        for fan_in, fan_out in zip(sizes[:-1], sizes[1:]):
            key, sub = jax.random.split(key)
            lim = math.sqrt(6.0 / (fan_in + fan_out))
            layers.append(
                {
                    "W": jax.random.uniform(
                        sub, (fan_in, fan_out), jnp.float32, -lim, lim
                    ),
                    "b": jnp.zeros((fan_out,), jnp.float32),
                }
            )
        return layers

    def _forward(self, layers, Xs):
        h = Xs
        for layer in layers[:-1]:
            h = self._act(h @ layer["W"] + layer["b"])
        return h @ layers[-1]["W"] + layers[-1]["b"]

    def _train_net(self, Xs, w, key, out_dim, per_example_loss, axis_name):
        """Adam on mean weighted loss; returns the trained layer pytree.

        ``per_example_loss(out) -> [n]`` given the net output ``[n, out]``.
        """
        net0 = self._init_net(key, self._sizes(Xs.shape[1], out_dim))
        wsum = jnp.maximum(preduce(jnp.sum(w), axis_name), 1e-30)
        reg = jnp.float32(self.reg_param)

        def local_obj(net):
            # local weighted SUM over this shard's rows / GLOBAL weight
            # mass; no psum inside (see module docstring)
            return jnp.sum(w * per_example_loss(self._forward(net, Xs))) / wsum

        opt = optax.adam(self.learning_rate_init)

        def step(carry, _):
            net, opt_state = carry
            grads = jax.grad(local_obj)(net)
            grads = jax.tree_util.tree_map(
                lambda g: preduce(g, axis_name), grads
            )
            # L2 gradient added once, post-reduction (replicated params)
            grads = [
                {"W": g["W"] + reg * p["W"], "b": g["b"]}
                for g, p in zip(grads, net)
            ]
            updates, opt_state = opt.update(grads, opt_state, net)
            return (optax.apply_updates(net, updates), opt_state), None

        (net, _), _ = jax.lax.scan(
            step, (net0, opt.init(net0)), None, length=int(self.max_iter)
        )
        return net

    def _prep(self, X, feature_mask, w, axis_name):
        """Masked, standardized features + the stats/mask to store."""
        Xm = _apply_mask(X, feature_mask)
        mu, sd = _feature_stats(Xm, w, axis_name)
        Xs = (Xm - mu[None, :]) / sd[None, :]
        mask = (
            feature_mask.astype(jnp.float32)
            if feature_mask is not None
            else jnp.ones((X.shape[1],), jnp.float32)
        )
        return Xs, {"x_mu": mu, "x_sd": sd, "mask": mask}

    def _input(self, params, X):
        Xm = X * params["mask"][None, :]
        return (Xm - params["x_mu"][None, :]) / params["x_sd"][None, :]


class MLPClassifier(_MLPBase):
    is_classifier = True

    def make_fit_ctx(self, X, num_classes: Optional[int] = None):
        return {"X": as_f32(X), "num_classes": Static(num_classes)}

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        X = ctx["X"]
        k = static_value(ctx["num_classes"])
        Xs, stats = self._prep(X, feature_mask, w, axis_name)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), k)

        def ce(logits):
            return -jnp.sum(jax.nn.log_softmax(logits, axis=-1) * onehot, axis=-1)

        layers = self._train_net(Xs, w, key, k, ce, axis_name)
        return {"layers": layers, **stats}

    def predict_raw_fn(self, params, X):
        return self._forward(params["layers"], self._input(params, X))

    def predict_proba_fn(self, params, X):
        return jax.nn.softmax(self.predict_raw_fn(params, X), axis=-1)

    def predict_fn(self, params, X):
        return jnp.argmax(self.predict_raw_fn(params, X), axis=-1).astype(
            jnp.float32
        )

    def model_from_params(self, params, num_features, num_classes=None):
        return MLPClassificationModel(
            params=params,
            num_features=num_features,
            num_classes=num_classes or 2,
            **self.get_params(),
        )


class MLPClassificationModel(ClassificationModel, MLPClassifier):
    def predict_raw(self, X):
        return self.predict_raw_fn(self.params, as_f32(X))

    def predict_proba(self, X):
        return self.predict_proba_fn(self.params, as_f32(X))

    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))


class MLPRegressor(_MLPBase):
    is_classifier = False

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        X = ctx
        Xs, stats = self._prep(X, feature_mask, w, axis_name)
        # standardize the target too (weighted): raw-scale targets (e.g.
        # cpusmall, magnitudes ~1e2) would force a per-dataset learning
        # rate; predictions unscale through the stored moments
        wsum = jnp.maximum(preduce(jnp.sum(w), axis_name), 1e-30)
        y_mu = preduce(jnp.sum(w * y), axis_name) / wsum
        y_var = preduce(jnp.sum(w * (y - y_mu) ** 2), axis_name) / wsum
        y_sd = jnp.maximum(jnp.sqrt(y_var), 1e-7)
        ys = (y - y_mu) / y_sd

        def sq(out):
            return 0.5 * (out[:, 0] - ys) ** 2

        layers = self._train_net(Xs, w, key, 1, sq, axis_name)
        return {"layers": layers, "y_mu": y_mu, "y_sd": y_sd, **stats}

    def predict_fn(self, params, X):
        out = self._forward(params["layers"], self._input(params, X))
        return out[:, 0] * params["y_sd"] + params["y_mu"]

    def model_from_params(self, params, num_features, num_classes=None):
        return MLPRegressionModel(
            params=params, num_features=num_features, **self.get_params()
        )


class MLPRegressionModel(RegressionModel, MLPRegressor):
    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))
