"""Megabatch sweep engine: train a whole hyperparameter sweep as ONE
batched XLA dispatch per round chunk (docs/selection.md#megabatch-sweeps).

The tuning loop (`tuning.py`) fits ``num_maps x num_folds`` candidates that
share the binned feature matrix and differ only in per-candidate
hyperparameter ARRAYS — learning rate, sampling seed, subsample/subspace
draws — plus the fold's zero-weight mask (weight-mask folds keep every
candidate's ``X`` identical, see tuning.py).  That is exactly the shape
``jax.vmap`` wants: this module jits ``vmap(chunk_fn)`` over a new leading
config axis, where ``chunk_fn`` is the SAME unjitted scan-chunked round
function the sequential fit jits (``models/gbm.py``
``make_reg_chunk_fn``/``make_cls_chunk_fn``).  Sweep round math is the
sequential program by construction; results are pinned bit-identical
(tests/test_megabatch.py).

Precedent: GPU tree boosting wins by saturating the accelerator with
batched independent work (arXiv 1806.11248) and pipelined grad/hist
dataflow (arXiv 2011.02022); here the batch is the candidate axis.

Per-dispatch batching is keyed on the ``configs_per_dispatch`` tunable
(autotune/space.py): candidates are packed into slabs of at most that many
lanes, the last slab padded by replicating its first lane (padded lanes are
computed and discarded — vmap lanes are independent).  Program count is
O(distinct chunk shapes), never O(candidates).

With a validation split, per-round validation losses come back ``[S, c]``
and the host applies the reference patience rule per candidate; candidates
that stop early get their remaining rounds hard-zeroed via the existing
``scale`` damper (the numeric guard's mechanism — successive halving for
free), and their trailing members are trimmed by the same ``keep = i - v``
absolute-round-index contract the sequential fit uses.

Under a ``mesh`` the CONFIG axis is sharded over the mesh's "member" axis
(rows stay whole per lane, so per-lane reductions are single-device and
values match the unsharded lanes); the data/member row sharding of
``fit(..., mesh=...)`` stays with the sequential path.
"""

from __future__ import annotations

import logging
import time
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_ensemble_tpu.autotune import resolve as _tuned
from spark_ensemble_tpu.models.base import (
    as_f32,
    cached_program,
    infer_num_classes,
    make_shared_fit_ctx,
    resolve_weights,
    resolved_scan_chunk,
)
from spark_ensemble_tpu.models.gbm import (
    GBMClassificationModel,
    GBMClassifier,
    GBMRegressionModel,
    GBMRegressor,
    _make_reg_loss,
    make_cls_chunk_fn,
    make_reg_chunk_fn,
    slice_pytree,
)
from spark_ensemble_tpu.telemetry.events import FitTelemetry
from spark_ensemble_tpu.telemetry.quality import drift_reference_from_ctx
from spark_ensemble_tpu.utils.quantile import weighted_quantile

logger = logging.getLogger(__name__)

#: live literal behind the ``configs_per_dispatch`` tunable
#: (autotune/space.py mirrors this default — keep them in sync)
_CONFIGS_PER_DISPATCH = 32

#: params that may differ WITHIN one batched sweep group: they enter the
#: compiled program as traced arrays (learning_rate) or as data the host
#: feeds it (seed/subsample/subspace draws), or stay host-side entirely
#: (round counts, patience bookkeeping)
SWEEP_BATCHED_PARAMS = (
    "learning_rate",
    "seed",
    "subsample_ratio",
    "subspace_ratio",
    "num_base_learners",
    "num_rounds",
    "validation_tol",
)

# vmap in_axes over the chunk-fn signatures (models/gbm.py):
#   reg: (ctx, X, y, w, valid_w, pred, pred_val, delta, X_val, y_val,
#         bag_ws, keys, masks, scales, lr)
#   cls: (ctx, X, y_enc, w, pred, pred_val, alpha_ws, X_val, y_enc_val,
#         bag_ws, keys, masks, scales, lr)
# shared data (ctx/X/targets/validation split) broadcasts; everything a
# candidate owns — weights, prediction carries, sampling draws, lr — maps
# over the leading config axis.
_REG_IN_AXES = (None, None, None, 0, None, 0, 0, 0, None, None,
                0, 0, 0, 0, 0)
_CLS_IN_AXES = (None, None, None, 0, 0, 0, 0, None, None,
                0, 0, 0, 0, 0)


def sweep_group_key(estimator) -> tuple:
    """Structural fingerprint of a candidate: its ``config_key`` with every
    batchable param pinned to a sentinel value.  Candidates with equal
    group keys trace to the SAME vmapped program and may share one
    megabatch; a tuning grid that also sweeps structural params (loss,
    depth, base learner, ...) is partitioned into one batch per group."""
    return estimator.copy(
        learning_rate=1.0,
        seed=0,
        subsample_ratio=1.0,
        subspace_ratio=1.0,
        num_base_learners=1,
        num_rounds=1,
        validation_tol=0.01,
    ).config_key()


def sweep_unsupported_reason(estimator, mesh=None) -> Optional[str]:
    """Why this estimator cannot ride the megabatch path (None = it can).
    ``tuning.py`` falls back to the sequential loop on a reason under
    ``megabatch="auto"`` and raises it under ``megabatch="on"``."""
    if not isinstance(estimator, (GBMRegressor, GBMClassifier)):
        return (
            f"{type(estimator).__name__} has no megabatch sweep support "
            "(GBMRegressor/GBMClassifier only)"
        )
    if estimator.checkpoint_dir:
        return "checkpoint_dir is set (sweep candidates are not checkpointable)"
    if estimator.profile_dir:
        return "profile_dir is set (per-candidate profiling needs sequential fits)"
    if estimator.on_nonfinite not in ("raise", "off"):
        return (
            f"on_nonfinite={estimator.on_nonfinite!r} needs the sequential "
            "recovery driver (sweeps support 'raise'/'off' only)"
        )
    if str(estimator.sampling).lower() != "none":
        return (
            f"sampling={estimator.sampling!r} compacts rows per round "
            "(models/gbm.py GOSS/MVS) and has no megabatch round core yet"
        )
    if str(estimator.leaf_model).lower() == "linear":
        return (
            "leaf_model='linear' fits ridge leaves outside the fused "
            "forest kernel and has no megabatch round core yet"
        )
    return None


def _pad_rounds(a, max_m: int):
    """Pad a per-candidate round-indexed array to the sweep's max round
    count by repeating the last row; padded rounds run at scale 0 and are
    trimmed, so the values never reach a kept member."""
    if a.shape[0] == max_m:
        return a
    reps = jnp.broadcast_to(a[-1:], (max_m - a.shape[0],) + a.shape[1:])
    return jnp.concatenate([a, reps], axis=0)


def _concat_rounds(chunks: List[Any]):
    """Concatenate [S, c, ...] chunk pytrees along the ROUND axis (axis 1;
    axis 0 is the config axis)."""
    if len(chunks) == 1:
        return chunks[0]
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=1), *chunks
    )


def _config_sharder(mesh, slab: int):
    """device_put callback sharding the leading config axis over the mesh's
    "member" axis (None when the mesh cannot hold it).  Rows stay whole per
    lane — each candidate's reductions run on one device, so lane values
    match the unsharded program."""
    if mesh is None or "member" not in getattr(mesh, "axis_names", ()):
        return None
    member_size = int(np.prod([
        mesh.shape[a] for a in mesh.axis_names if a == "member"
    ]))
    if member_size <= 1 or slab % member_size != 0:
        return None

    def put(tree):
        def one(x):
            x = jnp.asarray(x)
            spec = P(*(("member",) + (None,) * (x.ndim - 1)))
            return jax.device_put(x, NamedSharding(mesh, spec))

        return jax.tree_util.tree_map(one, tree)

    return put


def _drive_sweep_slab(
    dispatch,
    lanes_m: List[int],
    max_m: int,
    chunk: int,
    with_validation: bool,
    best0: List[float],
    patience: List[int],
    val_tols: List[float],
    patience_step,
    guard=None,
    telem: Optional[FitTelemetry] = None,
):
    """Lockstep round loop for one slab of candidates: one batched dispatch
    per round chunk, host patience per lane, ``scale = 0`` masking for
    lanes that stopped (successive halving — losers stop consuming the
    dispatch's useful lanes while keys/masks stay aligned to absolute round
    indices).  Returns (members_chunks, weights_chunks, i, v, best,
    val_hists)."""
    S = len(lanes_m)
    members_chunks: List[Any] = []
    weights_chunks: List[Any] = []
    i = [0] * S
    v = [0] * S
    best = list(best0)
    stopped = [False] * S
    val_hists: List[List[float]] = [[] for _ in range(S)]
    r0 = 0
    while r0 < max_m and any(
        not stopped[s] and lanes_m[s] > r0 for s in range(S)
    ):
        c = min(chunk, max_m - r0)
        scales = np.ones((S, c), np.float32)
        for s in range(S):
            for j in range(c):
                if stopped[s] or r0 + j >= lanes_m[s]:
                    scales[s, j] = 0.0
        active = int(scales.sum())
        t0 = time.perf_counter()
        params_c, weights_c, errs = dispatch(r0, c, jnp.asarray(scales))
        if guard is not None and guard.active:
            # one fused finiteness reduction over the whole [S, c] chunk —
            # same detection cadence as the sequential driver; the only
            # supported policy here is fail-fast (see
            # sweep_unsupported_reason)
            strict = (weights_c, errs) if with_validation else (weights_c,)
            if guard.first_nonfinite(params_c, *strict) is not None:
                guard.raise_error(r0, what="sweep chunk outputs")
        members_chunks.append(params_c)
        weights_chunks.append(weights_c)
        if with_validation:
            errs_np = np.asarray(errs)
            for s in range(S):
                if stopped[s] or lanes_m[s] <= r0:
                    continue
                lane_stop = False
                for j in range(min(c, lanes_m[s] - r0)):
                    err = float(errs_np[s, j])
                    val_hists[s].append(err)
                    best[s], v[s] = patience_step(
                        best[s], err, v[s], val_tols[s]
                    )
                    if v[s] >= patience[s]:
                        i[s] = r0 + j + 1
                        stopped[s] = True
                        lane_stop = True
                        break
                if not lane_stop:
                    i[s] = min(lanes_m[s], r0 + c)
        else:
            for s in range(S):
                i[s] = min(lanes_m[s], r0 + c)
        if telem is not None and telem.enabled:
            # fence on the chunk outputs before reading the clock, then
            # attribute the dispatch's wall to its live lanes — the
            # per-candidate round ledger for sweeps
            telem.blocking_read((params_c, weights_c, errs))
            wall = time.perf_counter() - t0
            telem.emit(
                "sweep_chunk",
                start_round=r0,
                rounds=c,
                candidates=S,
                active_lane_rounds=active,
                wall_s=wall,
                per_candidate_round_s=wall / max(1, active),
            )
        r0 += c
    return members_chunks, weights_chunks, i, v, best, val_hists


def fit_sweep(
    estimators: Sequence[Any],
    X,
    y,
    sample_weights: Optional[Sequence[Any]] = None,
    num_classes: Optional[int] = None,
    validation_indicator=None,
    mesh=None,
    telemetry_path: Optional[str] = None,
) -> List[Any]:
    """Fit every candidate estimator on the SAME feature matrix as one
    batched program per round chunk; returns fitted models in candidate
    order, each bit-identical to ``estimators[b].fit(X, y,
    sample_weight=sample_weights[b], ...)`` on a single device.

    Candidates must share every structural param (``sweep_group_key``);
    they may differ in ``SWEEP_BATCHED_PARAMS``.  ``sample_weights`` is one
    weight vector per candidate (tuning's zero-weight fold masks), or None
    for unit weights everywhere."""
    ests = list(estimators)
    if not ests:
        return []
    est0 = ests[0]
    reason = sweep_unsupported_reason(est0, mesh)
    if reason is not None:
        raise ValueError(f"fit_sweep: {reason}")
    gk = sweep_group_key(est0)
    for est in ests[1:]:
        if sweep_group_key(est) != gk:
            raise ValueError(
                "fit_sweep candidates must share every structural param; "
                "only " + ", ".join(SWEEP_BATCHED_PARAMS) + " may differ "
                "within one batch (group structurally-distinct candidates "
                "with sweep_group_key)"
            )
    B = len(ests)
    X = as_f32(X)
    y = as_f32(y)
    est0._validate_fit_inputs(X, y)
    if sample_weights is None:
        sample_weights = [None] * B
    if len(sample_weights) != B:
        raise ValueError(
            f"sample_weights must have one entry per candidate "
            f"({B}); got {len(sample_weights)}"
        )
    w_full = [resolve_weights(y, sw) for sw in sample_weights]
    if validation_indicator is not None:
        vi = np.asarray(validation_indicator, bool)
        X_val, y_val = X[vi], y[vi]
        Xt, yt = X[~vi], y[~vi]
        w_list = [wb[~vi] for wb in w_full]
    else:
        X_val = y_val = None
        Xt, yt = X, y
        w_list = w_full
    n, d = Xt.shape
    with_validation = X_val is not None

    telem = FitTelemetry.start(
        est0, family=f"GBMSweep[{type(est0).__name__}]", n=n, d=d,
        telemetry_path=telemetry_path, candidates=B,
    )
    try:
        models = _fit_sweep_inner(
            ests, gk, Xt, yt, w_list, X_val, y_val, with_validation,
            num_classes, mesh, telem, n, d,
        )
    except BaseException as e:  # noqa: BLE001 — terminal telemetry record
        telem.abort(e, candidates=B)
        raise
    telem.finish(candidates=B)
    return models


def _fit_sweep_inner(
    ests, gk, Xt, yt, w_list, X_val, y_val, with_validation, num_classes,
    mesh, telem, n, d,
):
    est0 = ests[0]
    B = len(ests)
    is_cls = bool(est0.is_classifier)
    base = est0._base().copy()
    ctx = make_shared_fit_ctx(base, Xt)
    drift_ref = drift_reference_from_ctx(ctx)

    # structural snapshots (identical across the group — enforced by gk)
    updates = est0.updates.lower()
    optimized = bool(est0.optimized_weights)
    goss = (
        (float(est0.top_rate), float(est0.other_rate))
        if est0.sample_method.lower() == "goss"
        else None
    )
    tol = float(est0.tol)
    max_iter = int(est0.max_iter)
    loss_name = est0.loss.lower()
    chunk = resolved_scan_chunk(est0, n)
    cpd = max(1, int(_tuned(
        "configs_per_dispatch", _CONFIGS_PER_DISPATCH, n=n
    )))
    slab = min(B, cpd)

    # ---- per-candidate host setup (reuses the fit-path cached programs,
    # so every array below is bit-identical to what fit() would stage) ----
    lanes_m = [int(e.num_base_learners) for e in ests]
    max_m = max(lanes_m)
    plans = [e._sampling_plan(n, d) for e in ests]
    keys_pad = [_pad_rounds(k, max_m) for k, _ in plans]
    masks_pad = [_pad_rounds(m, max_m) for _, m in plans]
    bag_many = [e._make_bag_many_fn(n, n) for e in ests]
    lr_all = [float(e.learning_rate) for e in ests]
    patience = [int(e.num_rounds) for e in ests]
    val_tols = [float(e.validation_tol) for e in ests]

    if is_cls:
        k = infer_num_classes(
            jnp.concatenate([yt, y_val]) if y_val is not None else yt,
            num_classes,
        )
        loss = est0._make_loss(k)
        dim = loss.dim
        y_enc = loss.encode_label(yt)
        inits = [
            e._init_raw_scores(Xt, yt, wb, k, dim)
            for e, wb in zip(ests, w_list)
        ]
        init_models = [im for im, _ in inits]
        init_raws = [ir for _, ir in inits]
        preds0 = [
            jnp.broadcast_to(ir[None, :], (n, dim)).astype(jnp.float32)
            for ir in init_raws
        ]
        chunk_fn = make_cls_chunk_fn(
            base, loss, dim, updates, optimized, goss, tol, max_iter,
            with_validation,
        )
        in_axes = _CLS_IN_AXES
        tag = "gbm_cls_sweep"
        huber = False
        y_enc_val = loss.encode_label(y_val) if with_validation else None
        eval_loss = cached_program(
            ("gbm_cls_eval", loss_name, k),
            lambda: jax.jit(
                lambda pred_v, y_enc_v: jnp.mean(loss.loss(y_enc_v, pred_v))
            ),
        )
    else:
        alpha_q = float(est0.alpha)
        huber = loss_name == "huber"
        inits = [e._fit_init(Xt, yt, wb) for e, wb in zip(ests, w_list)]
        init_models = list(inits)
        preds0 = [im.predict(Xt) for im in init_models]
        if huber:
            full_y = (
                jnp.concatenate([yt, y_val]) if y_val is not None else yt
            )
            delta0 = weighted_quantile(full_y, alpha_q)
        else:
            delta0 = jnp.asarray(0.0, jnp.float32)
        chunk_fn = make_reg_chunk_fn(
            base, loss_name, alpha_q, updates, optimized, goss, tol,
            max_iter, huber, with_validation,
        )
        in_axes = _REG_IN_AXES
        tag = "gbm_reg_sweep"
        eval_loss = cached_program(
            ("gbm_reg_eval", loss_name, alpha_q),
            lambda: jax.jit(
                lambda pred_v, delta, y_v: jnp.mean(
                    _make_reg_loss(loss_name, alpha_q, delta).loss(
                        _make_reg_loss(loss_name, alpha_q, delta)
                        .encode_label(y_v),
                        pred_v[:, None],
                    )
                )
            ),
        )

    valid_w = jnp.ones((n,), jnp.float32)
    val_dummy = jnp.zeros((0,), jnp.float32)
    guard = est0._numeric_guard(telem)
    shard_put = _config_sharder(mesh, slab)

    def sweep_program(c: int):
        # one compiled program per (slab, chunk-length) — NEVER per
        # candidate; the tier-2 megabatch contract pins this
        # (analysis/contracts.py)
        return cached_program(
            (tag, gk, slab, c, huber, with_validation, mesh),
            lambda: jax.jit(jax.vmap(chunk_fn, in_axes=in_axes)),
        )

    telem.phase_mark("setup")
    models: List[Any] = [None] * B
    for lo in range(0, B, slab):
        lanes = list(range(lo, min(lo + slab, B)))
        # pad the last slab by replicating its first lane: padded lanes
        # recompute lane 0's rounds and are discarded below, keeping one
        # program shape across slabs
        pad_lanes = lanes + [lanes[0]] * (slab - len(lanes))
        S = len(pad_lanes)

        w_stack = jnp.stack([w_list[b] for b in pad_lanes])
        lr_arr = jnp.asarray([lr_all[b] for b in pad_lanes], jnp.float32)
        keys_stack = jnp.stack([keys_pad[b] for b in pad_lanes])
        masks_stack = jnp.stack([masks_pad[b] for b in pad_lanes])
        pred = jnp.stack([preds0[b] for b in pad_lanes])
        slab_m = [lanes_m[b] for b in pad_lanes]
        slab_max_m = max(slab_m)
        if is_cls:
            carry_extra = jnp.ones((S, dim), jnp.float32)  # alpha_ws
        else:
            carry_extra = jnp.stack([delta0] * S)  # delta
        if with_validation:
            if is_cls:
                pred_val = jnp.stack([
                    jnp.broadcast_to(
                        init_raws[b][None, :], (X_val.shape[0], dim)
                    ).astype(jnp.float32)
                    for b in pad_lanes
                ])
                best0 = [
                    float(eval_loss(pred_val[s], y_enc_val))
                    for s in range(S)
                ]
            else:
                pred_val = jnp.stack([
                    init_models[b].predict(X_val) for b in pad_lanes
                ])
                best0 = [
                    float(eval_loss(pred_val[s], carry_extra[s], y_val))
                    for s in range(S)
                ]
        else:
            width = (0, dim) if is_cls else (0,)
            pred_val = jnp.zeros((S,) + width, jnp.float32)
            best0 = [0.0] * S
        if shard_put is not None:
            (w_stack, lr_arr, keys_stack, masks_stack, pred, pred_val,
             carry_extra) = shard_put((
                w_stack, lr_arr, keys_stack, masks_stack, pred, pred_val,
                carry_extra,
            ))

        carry = {"pred": pred, "pred_val": pred_val, "extra": carry_extra}

        def dispatch(r0, c, scales, carry=carry, S=S,
                     keys_stack=keys_stack, masks_stack=masks_stack,
                     w_stack=w_stack, lr_arr=lr_arr, pad_lanes=pad_lanes):
            bag_ws = jnp.stack([
                bag_many[b](keys_pad[b][r0:r0 + c]) for b in pad_lanes
            ])
            keys_c = keys_stack[:, r0:r0 + c]
            masks_c = masks_stack[:, r0:r0 + c]
            if shard_put is not None:
                bag_ws, scales = shard_put((bag_ws, scales))
            program = sweep_program(c)
            if is_cls:
                (params_c, weights_c, errs, new_pred, new_pred_val,
                 new_extra) = program(
                    ctx, Xt, y_enc, w_stack, carry["pred"],
                    carry["pred_val"], carry["extra"],
                    X_val if with_validation else val_dummy,
                    y_enc_val if with_validation else val_dummy,
                    bag_ws, keys_c, masks_c, scales, lr_arr,
                )
            else:
                (params_c, weights_c, errs, new_pred, new_pred_val,
                 new_extra) = program(
                    ctx, Xt, yt, w_stack, valid_w, carry["pred"],
                    carry["pred_val"], carry["extra"],
                    X_val if with_validation else val_dummy,
                    y_val if with_validation else val_dummy,
                    bag_ws, keys_c, masks_c, scales, lr_arr,
                )
            carry["pred"] = new_pred
            carry["extra"] = new_extra
            if with_validation:
                carry["pred_val"] = new_pred_val
            return params_c, weights_c, errs if with_validation else None

        members_chunks, weights_chunks, i, v, best, val_hists = (
            _drive_sweep_slab(
                dispatch, slab_m, slab_max_m, chunk, with_validation,
                best0, [patience[b] for b in pad_lanes],
                [val_tols[b] for b in pad_lanes],
                est0._patience_step, guard=guard, telem=telem,
            )
        )

        all_members = (
            _concat_rounds(members_chunks) if members_chunks else None
        )
        all_weights = (
            _concat_rounds(weights_chunks) if weights_chunks else None
        )
        for s, b in enumerate(pad_lanes):
            if s >= len(lanes):
                break  # padded replica lanes
            keep = i[s] - v[s]
            est_b = ests[b]
            _, masks_b = plans[b]
            val_hist = (
                jnp.asarray(val_hists[s], jnp.float32)
                if with_validation
                else None
            )
            lane_members = (
                slice_pytree(
                    jax.tree_util.tree_map(lambda x: x[s], all_members),
                    keep,
                )
                if keep > 0 and all_members is not None
                else None
            )
            lane_weights = (
                all_weights[s][:keep]
                if keep > 0 and all_weights is not None
                else (
                    jnp.zeros((0, dim)) if is_cls else jnp.zeros((0,))
                )
            )
            if is_cls:
                model = GBMClassificationModel(
                    params={
                        "members": lane_members,
                        "weights": lane_weights,
                        "masks": masks_b[:keep],
                        "init_raw": init_raws[b],
                        "val_hist": val_hist,
                    },
                    num_features=d,
                    num_classes=k,
                    num_members=keep,
                    dim=dim,
                    **est_b.get_params(),
                )
            else:
                model = GBMRegressionModel(
                    params={
                        "members": lane_members,
                        "weights": lane_weights,
                        "masks": masks_b[:keep],
                        "init": init_models[b].params,
                        "val_hist": val_hist,
                    },
                    num_features=d,
                    init_model=init_models[b],
                    num_members=keep,
                    **est_b.get_params(),
                )
            if drift_ref is not None:
                model.drift_ref_ = drift_ref
            if not hasattr(model, "fit_history_"):
                # fitted-model contract parity with fit(): per-candidate
                # round rows do not exist inside a batched dispatch, so
                # sweep models carry an empty (not missing) history
                model.fit_history_ = {
                    "round": np.zeros(0, np.int64),
                    "learner_index": np.zeros(0, np.int64),
                    "duration_s": np.zeros(0, np.float64),
                    "loss": np.zeros(0, np.float64),
                    "step_size": np.zeros(0, np.float64),
                }
            models[b] = model
    return models
