"""Gaussian naive Bayes base learner.

Fills the role of Spark MLlib ``NaiveBayes`` in the reference's stacking
bench config ("DT + LR + NB bases").  Weighted per-class feature means and
variances plus a log-prior; class log-likelihoods sum per-feature Gaussian
terms.  Feature-mask entries simply zero a feature's log-likelihood
contribution, the masked-projection equivalent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.ops.collective import preduce as _preduce
from spark_ensemble_tpu.models.base import (
    Static,
    static_value,
    BaseLearner,
    ClassificationModel,
    as_f32,
)
from spark_ensemble_tpu.params import Param, gt_eq


class GaussianNaiveBayes(BaseLearner):
    var_smoothing = Param(
        1e-6, gt_eq(0.0),
        doc="fraction of the largest feature variance added to every "
        "per-class variance for numerical stability",
    )

    is_classifier = True

    def make_fit_ctx(self, X, num_classes=None):
        return {"X": as_f32(X), "num_classes": Static(num_classes)}

    def fit_from_ctx(self, ctx, y, w, feature_mask, key, axis_name=None):
        preduce = lambda v: _preduce(v, axis_name)

        X = ctx["X"]
        k = static_value(ctx["num_classes"])
        d = X.shape[1]
        onehot = jax.nn.one_hot(y.astype(jnp.int32), k)  # [n, k]
        wc = onehot * w[:, None]  # [n, k]
        class_w = preduce(jnp.sum(wc, axis=0))  # [k]
        mean = preduce(wc.T @ X) / jnp.maximum(class_w[:, None], 1e-30)  # [k, d]
        sq = preduce(wc.T @ (X * X))
        var = sq / jnp.maximum(class_w[:, None], 1e-30) - mean * mean
        # global feature variance for the smoothing floor, over PRESENT
        # rows only (w > 0): zero-weight rows are out-of-bag samples or
        # mesh padding and must not shift the floor — the "padding rows
        # carry weight 0" contract every learner honors
        present = (w > 0).astype(jnp.float32)
        n_glob = jnp.maximum(preduce(jnp.sum(present)), 1.0)
        x_mu = preduce(jnp.sum(X * present[:, None], axis=0)) / n_glob
        x_var = (
            preduce(
                jnp.sum(
                    ((X - x_mu[None, :]) ** 2) * present[:, None], axis=0
                )
            )
            / n_glob
        )
        var = jnp.maximum(var, 0.0) + self.var_smoothing * jnp.maximum(
            x_var, 1e-12
        )
        prior = class_w / jnp.maximum(jnp.sum(class_w), 1e-30)
        mask = (
            feature_mask.astype(jnp.float32)
            if feature_mask is not None
            else jnp.ones((d,), jnp.float32)
        )
        return {
            "mean": mean,
            "var": var,
            "log_prior": jnp.log(jnp.maximum(prior, 1e-30)),
            "mask": mask,
        }

    def predict_raw_fn(self, params, X):
        # [n, k, d] per-feature log-likelihood terms, masked then summed
        diff = X[:, None, :] - params["mean"][None, :, :]
        ll = -0.5 * (
            jnp.log(2.0 * jnp.pi * params["var"])[None, :, :]
            + diff * diff / params["var"][None, :, :]
        )
        ll = ll * params["mask"][None, None, :]
        return params["log_prior"][None, :] + jnp.sum(ll, axis=-1)

    def predict_proba_fn(self, params, X):
        return jax.nn.softmax(self.predict_raw_fn(params, X), axis=-1)

    def predict_fn(self, params, X):
        return jnp.argmax(self.predict_raw_fn(params, X), axis=-1).astype(jnp.float32)

    def model_from_params(self, params, num_features, num_classes=None):
        return GaussianNaiveBayesModel(
            params=params,
            num_features=num_features,
            num_classes=num_classes or 2,
            **self.get_params(),
        )


class GaussianNaiveBayesModel(ClassificationModel, GaussianNaiveBayes):
    def predict_proba(self, X):
        return self.predict_proba_fn(self.params, as_f32(X))

    def predict_raw(self, X):
        return self.predict_raw_fn(self.params, as_f32(X))

    def predict(self, X):
        return self.predict_fn(self.params, as_f32(X))
