"""Pipelines and feature transformers.

The reference's estimators extend Spark ``Predictor`` precisely so they
compose with ``Pipeline`` stages and feature transformers
(SURVEY.md §1 L5; reference `docs/example.md`).  This module supplies the
array-native equivalent: a ``Pipeline`` of fitted transformer stages ending
in (optionally) a predictor, where every transformer is a jitted array
kernel rather than a DataFrame column UDF.

Transformers follow the Estimator/Model split: ``StandardScaler().fit(X)``
returns a ``StandardScalerModel`` whose ``transform`` is pure and jittable,
so a whole pipeline's feature path fuses into the downstream model's XLA
program.
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.models.base import Estimator, Model, as_f32
from spark_ensemble_tpu.params import Param, Params


class Transformer(Params):
    """A stateless or fitted feature transform ``X -> X'``."""

    def transform(self, X) -> jax.Array:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Feature transformers
# ---------------------------------------------------------------------------


class StandardScaler(Estimator):
    """Column standardization (Spark ``ml.feature.StandardScaler``)."""

    with_mean = Param(True, doc="center features at the training mean")
    with_std = Param(True, doc="scale features to unit training variance")

    def fit(self, X, y=None, sample_weight=None) -> "StandardScalerModel":
        X = as_f32(X)
        mean = jnp.mean(X, axis=0)
        std = jnp.std(X, axis=0)
        return StandardScalerModel(
            params={"mean": mean, "scale": jnp.maximum(std, 1e-12)},
            num_features=X.shape[1],
            **self.get_params(),
        )


class StandardScalerModel(Model, StandardScaler):
    def transform(self, X):
        X = as_f32(X)
        if self.with_mean:
            X = X - self.params["mean"]
        if self.with_std:
            X = X / self.params["scale"]
        return X

    def predict(self, X):  # transformers are not predictors
        raise TypeError("StandardScalerModel is a transformer; use transform()")


class MinMaxScaler(Estimator):
    """Rescale columns to [min, max] (Spark ``ml.feature.MinMaxScaler``)."""

    feature_min = Param(0.0, doc="lower bound of the scaled range")
    feature_max = Param(1.0, doc="upper bound of the scaled range")

    def fit(self, X, y=None, sample_weight=None) -> "MinMaxScalerModel":
        X = as_f32(X)
        lo = jnp.min(X, axis=0)
        hi = jnp.max(X, axis=0)
        return MinMaxScalerModel(
            params={"lo": lo, "range": hi - lo},
            num_features=X.shape[1],
            **self.get_params(),
        )


class MinMaxScalerModel(Model, MinMaxScaler):
    def transform(self, X):
        X = as_f32(X)
        rng = self.params["range"]
        # constant columns rescale to the midpoint, matching Spark's
        # E_max == E_min rule
        unit = jnp.where(
            rng > 0, (X - self.params["lo"]) / jnp.maximum(rng, 1e-30), 0.5
        )
        return unit * (self.feature_max - self.feature_min) + self.feature_min

    def predict(self, X):
        raise TypeError("MinMaxScalerModel is a transformer; use transform()")


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------


class Pipeline(Estimator):
    """Fit stages left to right; transformer outputs feed later stages
    (Spark ``ml.Pipeline``).  Stages may be transformer estimators (fitted to
    models exposing ``transform``), already-fitted transformers, or a final
    predictor estimator."""

    stages = Param(
        None, is_estimator=True,
        doc="ordered transformers + final estimator, Spark Pipeline style",
    )

    @property
    def is_classifier(self):
        """A pipeline classifies iff some estimator stage does — keeps the
        num_classes plumbing (tuning folds missing the top class) working
        for tuned Pipelines too."""
        return any(
            getattr(s, "is_classifier", False) for s in (self.stages or [])
        )

    def fit(
        self, X, y=None, sample_weight=None, num_classes=None, mesh=None
    ) -> "PipelineModel":
        """Fit; ``mesh`` is forwarded to every mesh-aware estimator stage
        (the ensembles), so a scaler + distributed GBM pipeline trains the
        GBM on the mesh."""
        from spark_ensemble_tpu.models.base import mesh_fit_kwargs

        fitted: List[Any] = []
        Xc = as_f32(X)
        num_features = Xc.shape[1]
        for stage in list(self.stages or []):
            if isinstance(stage, (Transformer, Model)):
                # already-fitted stages pass through untouched (Spark
                # semantics: a fitted Model in a Pipeline is a transformer
                # stage, never re-fit)
                fitted.append(stage)
                if hasattr(stage, "transform"):
                    Xc = stage.transform(Xc)
            elif isinstance(stage, Estimator):
                kw = mesh_fit_kwargs(stage, mesh)
                if getattr(stage, "is_classifier", False):
                    model = stage.fit(
                        Xc, y, sample_weight=sample_weight,
                        num_classes=num_classes, **kw,
                    )
                else:
                    model = stage.fit(Xc, y, sample_weight=sample_weight, **kw)
                fitted.append(model)
                if hasattr(model, "transform"):
                    Xc = model.transform(Xc)
            else:
                raise TypeError(f"invalid pipeline stage {stage!r}")
        # class count comes from the LAST stage that knows it (the final
        # predictor); earlier transformer stages may carry num_classes=None
        num_classes = next(
            (
                m.num_classes
                for m in reversed(fitted)
                if getattr(m, "num_classes", None) is not None
            ),
            None,
        )
        return PipelineModel(
            stage_models=fitted,
            num_features=num_features,
            num_classes=num_classes,
            **self.get_params(),
        )


class PipelineModel(Model, Pipeline):
    def __init__(self, stage_models=None, num_classes=None, **kwargs):
        super().__init__(**kwargs)
        self.stage_models = stage_models or []
        self.num_classes = num_classes

    def _features(self, X):
        Xc = as_f32(X)
        # mirror fit(): a non-final stage without `transform` (e.g. a fitted
        # predictor mid-pipeline) passes features through unchanged, so
        # predict() matches fit-time feature flow instead of raising
        for stage in self.stage_models[:-1]:
            if hasattr(stage, "transform"):
                Xc = stage.transform(Xc)
        return Xc

    @property
    def _final(self):
        return self.stage_models[-1]

    def transform(self, X):
        """Apply every transformer stage; a final predictor stage (no
        ``transform``) is skipped, so the result is the feature matrix the
        final predictor consumes."""
        Xc = as_f32(X)
        for stage in self.stage_models:
            if hasattr(stage, "transform"):
                Xc = stage.transform(Xc)
        return Xc

    def predict(self, X):
        return self._final.predict(self._features(X))

    def predict_raw(self, X):
        return self._final.predict_raw(self._features(X))

    def predict_proba(self, X):
        return self._final.predict_proba(self._features(X))
