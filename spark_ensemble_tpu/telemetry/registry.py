"""Metric primitives: counters, gauges, streaming histograms, round timers.

The reference exposes training progress only through Spark ML
``Instrumentation`` log lines; on TPU the interesting quantities (per-round
device time, compile counts, memory high-water marks) are numeric and worth
aggregating, not just printing.  ``MetricsRegistry`` is the process-local
home for them: cheap enough to update per round, thread-safe because
``StackingClassifier(parallelism>1)`` fits members from a thread pool.

The one jax-specific subtlety lives in ``RoundTimer``: dispatch is async, so
``perf_counter()`` after a jitted call measures dispatch, not execution.
``RoundTimer.stop(*fence)`` blocks on every jax array reachable from the
fence objects (the same ``block_on_arrays`` walk ``instrumented_fit`` uses
before closing a profiler trace) and only then reads the clock.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from spark_ensemble_tpu.utils.instrumentation import block_on_arrays

__all__ = [
    "Counter",
    "Gauge",
    "StreamingHistogram",
    "RoundTimer",
    "MetricsRegistry",
]


class Counter:
    """Monotonically increasing count (e.g. jit compiles per process)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += int(n)

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-write-wins scalar (e.g. current device bytes_in_use)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "gauge", "value": self.value}


class StreamingHistogram:
    """Fixed log2-bucketed streaming histogram: O(1) record, no sample
    retention, quantiles answered from bucket edges.  The span covers
    microseconds-to-hours of seconds-denominated durations and byte counts
    up to ~1 TiB; values outside clamp into the edge buckets."""

    _MIN_EXP = -20  # 2**-20 ~ 1e-6
    _MAX_EXP = 40  # 2**40  ~ 1e12

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        nbuckets = self._MAX_EXP - self._MIN_EXP + 1
        self._buckets = [0] * nbuckets
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def _bucket_index(self, value: float) -> int:
        if value <= 0:
            return 0
        e = int(math.floor(math.log2(value)))
        return min(max(e - self._MIN_EXP, 0), len(self._buckets) - 1)

    def record(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._buckets[self._bucket_index(value)] += 1
            self._count += 1
            self._sum += value
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Upper-edge estimate of the ``q`` quantile (exact for the min/max
        of a one-bucket population; otherwise within a 2x bucket width)."""
        with self._lock:
            if self._count == 0:
                return None
            target = q * self._count
            seen = 0
            for idx, c in enumerate(self._buckets):
                seen += c
                if seen >= target:
                    return min(
                        float(2.0 ** (idx + self._MIN_EXP + 1)), self._max
                    )
            return self._max

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            if self._count == 0:
                return {"type": "histogram", "count": 0}
            mean = self._sum / self._count
            mn, mx, cnt, sm = self._min, self._max, self._count, self._sum
        return {
            "type": "histogram",
            "count": cnt,
            "sum": sm,
            "min": mn,
            "max": mx,
            "mean": mean,
            "p50": self.quantile(0.5),
            "p90": self.quantile(0.9),
            "p99": self.quantile(0.99),
        }

    def snapshot(self) -> Dict[str, Any]:
        return self.summary()


class RoundTimer:
    """Monotonic round timer whose ``stop`` fences on device work.

    ``start()`` reads ``perf_counter``; ``stop(*fence)`` first blocks on
    every jax array reachable from the fence objects — without the fence,
    async dispatch makes the elapsed time the cost of ENQUEUEING the round,
    not running it (the same reason ``instrumented_fit`` blocks before
    closing a profiler trace).  Durations stream into a histogram, so the
    registry answers "p99 round time" without retaining per-round samples.
    """

    def __init__(self, name: str, histogram: StreamingHistogram):
        self.name = name
        self.histogram = histogram
        self._t0: Optional[float] = None

    def start(self) -> float:
        self._t0 = time.perf_counter()
        return self._t0

    def stop(self, *fence: Any) -> float:
        if self._t0 is None:
            raise RuntimeError(f"RoundTimer {self.name!r} stopped before start")
        if fence:
            block_on_arrays(list(fence))
        elapsed = time.perf_counter() - self._t0
        self._t0 = None
        self.histogram.record(elapsed)
        return elapsed

    def time(self, fn, *args, fence_result: bool = True, **kwargs):
        """Run ``fn`` under the timer; fences on its result by default."""
        self.start()
        result = fn(*args, **kwargs)
        self.stop(result if fence_result else ())
        return result


class MetricsRegistry:
    """Named get-or-create home for metrics; one instance per concern
    (the telemetry events module keeps a process-global one)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}
        self._sources: Dict[str, Any] = {}

    def _get_or_create(self, name: str, cls, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter, lambda: Counter(name))

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge, lambda: Gauge(name))

    def histogram(self, name: str) -> StreamingHistogram:
        return self._get_or_create(
            name, StreamingHistogram, lambda: StreamingHistogram(name)
        )

    def timer(self, name: str) -> RoundTimer:
        """A fresh timer over the (shared) histogram registered under
        ``name`` — timers hold in-flight start state, so unlike the other
        metric kinds they are NOT shared between callers."""
        return RoundTimer(name, self.histogram(name))

    def register_source(self, name: str, fn) -> None:
        """Register a LIVE snapshot source: ``fn()`` returns a JSON-ready
        value rendered into :meth:`snapshot` under ``name`` — how
        long-lived stateful objects (a ``FleetRouter``'s SLO counters)
        surface through the one-stop process snapshot without mirroring
        every update into counters.  Re-registering a name replaces the
        source; the owner unregisters on shutdown."""
        with self._lock:
            self._sources[name] = fn

    def unregister_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Point-in-time values of every metric plus every registered
        live source, JSON-ready.  Source callables run OUTSIDE the
        registry lock (they may take their owner's lock); a source that
        raises reports its error instead of poisoning the snapshot."""
        with self._lock:
            items: List[Tuple[str, Any]] = sorted(self._metrics.items())
            sources: List[Tuple[str, Any]] = sorted(self._sources.items())
        out = {name: m.snapshot() for name, m in items}
        for name, fn in sources:
            try:
                out[name] = {"type": "source", "value": fn()}
            except Exception as e:  # noqa: BLE001 - snapshot must not die
                out[name] = {
                    "type": "source",
                    "error": f"{type(e).__name__}: {e}",
                }
        return out
