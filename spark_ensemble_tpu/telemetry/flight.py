"""Crash flight recorder: a per-process black box for dead hosts.

The telemetry JSONL sink is flush-on-finish by design (one append per
fit keeps the hot path allocation-only), which means the host that gets
preempted loses its stream exactly when it matters.  This module keeps
an always-on, allocation-cheap ring of the last K span/event rows every
emit chokepoint produced (``FitTelemetry._emit`` and ``emit_event``
record into it), and dumps the ring — plus device memory stats and the
``global_metrics()`` snapshot, which carries coordinator and breaker
state through their registered live sources — to a post-mortem JSON
file when the process is about to die (``HostLostError`` /
``ChaosHostPreemption`` / guard abort; docs/tracing.md#pod-scope).

Overhead discipline: ``record`` stores one *reference* to the dict the
sink already built — no copy, no allocation beyond the preallocated
ring — and is only reached when a telemetry sink is active (the
disabled ``FitTelemetry`` singleton never calls ``_emit``), so the
no-sink path stays allocation-free (bench-pinned ``trace_overhead_pct``).

Dump location: ``SE_TPU_FLIGHT_DIR`` env, else the directory of the
active ``SE_TPU_TELEMETRY`` stream, else no dump (the recorder still
rings in memory).  The dump is written tmp-file + fsync + atomic rename
so a crash mid-dump never leaves a half-written black box.

Pure stdlib at module scope — jax is only touched lazily inside
:meth:`FlightRecorder.dump`, and failures there degrade to a dump
without memory stats (a black box on a jax-free host still works).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("spark_ensemble_tpu")

__all__ = [
    "FlightRecorder",
    "recorder",
    "dump_flight",
    "flight_dump_path",
    "FLIGHT_DIR_ENV",
    "DEFAULT_CAPACITY",
]

FLIGHT_DIR_ENV = "SE_TPU_FLIGHT_DIR"
DEFAULT_CAPACITY = 256


def _jsonable(obj: Any):
    """Last-resort JSON coercion for ring rows (numpy scalars etc.)."""
    try:
        return float(obj)
    except Exception:
        return str(obj)


class FlightRecorder:
    """Fixed-capacity ring of the last K telemetry rows.

    ``record`` is the hot path: one lock, one index store of a reference
    to the caller's dict (never copied — the row is immutable once
    emitted), one counter bump.  The ring list is preallocated at
    construction so steady-state recording allocates nothing.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive (got {capacity})")
        self.capacity = int(capacity)
        self._ring: List[Optional[Dict[str, Any]]] = [None] * self.capacity
        self._next = 0
        self._lock = threading.Lock()

    def record(self, row: Dict[str, Any]) -> None:
        with self._lock:
            self._ring[self._next % self.capacity] = row
            self._next += 1

    @property
    def recorded(self) -> int:
        """Total rows ever recorded (>= len(rows()))."""
        with self._lock:
            return self._next

    def rows(self) -> List[Dict[str, Any]]:
        """The retained rows, oldest first."""
        with self._lock:
            n = self._next
            if n <= self.capacity:
                return [r for r in self._ring[:n] if r is not None]
            start = n % self.capacity
            out = self._ring[start:] + self._ring[:start]
        return [r for r in out if r is not None]

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._next = 0

    def dump(self, path: str, reason: str = "",
             error: Optional[BaseException] = None,
             extra: Optional[Dict[str, Any]] = None) -> str:
        """Write the post-mortem JSON: the retained rows plus device
        memory stats and the process metrics snapshot (coordinator /
        breaker state rides the registered sources).  fsync'd and
        atomically renamed into place — the caller is usually about to
        re-raise a preemption, and the file must survive a SIGKILL
        landing right after."""
        payload: Dict[str, Any] = {
            "kind": "flight_recorder",
            "reason": reason,
            "pid": os.getpid(),
            "ts": time.time(),
            "capacity": self.capacity,
            "recorded": self.recorded,
            "rows": self.rows(),
        }
        if error is not None:
            payload["error_type"] = type(error).__name__
            payload["error"] = str(error)[:500]
        try:  # lazy: the black box must work on a jax-free host
            from spark_ensemble_tpu.telemetry.events import (
                device_memory_stats,
                global_metrics,
            )

            payload["memory"] = device_memory_stats()
            payload["metrics"] = global_metrics().snapshot()
        except Exception:  # pragma: no cover - depends on install state
            pass
        if extra:
            payload.update(extra)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, default=_jsonable)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        try:  # fsync the directory so the rename itself is durable
            dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:  # pragma: no cover - exotic filesystems
            pass
        return path


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    """The process-global ring every emit chokepoint records into."""
    return _RECORDER


def flight_dump_path(telemetry_path: Optional[str] = None) -> Optional[str]:
    """Where this process's black box lands: ``SE_TPU_FLIGHT_DIR``, else
    next to the telemetry stream (explicit ``telemetry_path`` or the
    ``SE_TPU_TELEMETRY`` env), else None (no dump)."""
    d = os.environ.get(FLIGHT_DIR_ENV) or None
    if not d and telemetry_path:
        d = os.path.dirname(os.path.abspath(telemetry_path))
    if not d:
        tel = os.environ.get("SE_TPU_TELEMETRY") or None
        if tel:
            d = os.path.dirname(os.path.abspath(tel))
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        logger.exception("flight recorder: cannot create %s", d)
        return None
    return os.path.join(d, f"flight_p{os.getpid()}.json")


def dump_flight(reason: str = "", error: Optional[BaseException] = None,
                telemetry_path: Optional[str] = None,
                extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Best-effort black-box dump of the process ring; returns the path,
    or None when no dump directory resolves.  Never raises — this runs
    on teardown paths that must still re-raise the original error."""
    path = flight_dump_path(telemetry_path)
    if path is None:
        return None
    try:
        return _RECORDER.dump(path, reason=reason, error=error, extra=extra)
    except Exception:  # noqa: BLE001 - teardown path must not die
        logger.exception("flight recorder: dump to %s failed", path)
        return None
