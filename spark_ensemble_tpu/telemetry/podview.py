"""Pod-scope trace stitching: N per-host JSONL streams -> one timeline.

PR 11 made training pod-scale but left observability host-scale: every
process writes its own telemetry stream (``SE_TPU_TELEMETRY`` names a
per-host file) with its own wall clock, its own pid-local span ids, and
its own per-fit trace ids.  This module merges those streams into a
single pod-level trace that ``tools/trace_viewer.py`` can render with
one ``host{i}`` track group per host and the preemption -> rewind flow
arrows crossing hosts (docs/tracing.md#pod-scope):

- **Fit alignment**: the k-th *distributed* fit on every host (the fits
  that emit ``dist_config``) is the same pod-wide fit — hosts execute
  the elastic attempt sequence in lockstep — so the k-th group's spans
  are rewritten onto one ``pod.{k}`` trace under one synthesized
  ``pod_fit_{k}`` root.  Manifest digests are cross-checked when both
  streams recorded them (a mismatch is reported, not fatal: the trace
  is still viewable evidence of the disagreement).
- **Clock offsets**: hosts' wall clocks disagree (NTP skew, container
  start offsets).  Rather than trusting any clock, offsets are
  estimated at the fit's natural sync barriers — the manifest-agreement
  ``all_gather`` and each level/leaf sweep's blocking reduce fetch —
  where every host provably unblocks at (nearly) the same true instant.
  The per-host offset is the median over matched barriers of
  ``t_host - t_reference``; subtracting it lands all spans on the
  reference host's timeline.
- **Id hygiene**: span/parent ids are prefixed ``h{i}.`` (pid-local ids
  can collide across hosts), threads are rewritten into ``host{i}``
  track groups, and flow ids are left untouched — cross-host flows
  (``parallel/elastic.py`` derives them from ``crc32(victim, site)``)
  are identical on every host by construction, which is exactly what
  lets the viewer draw the preemption arrow from the victim's stream
  into the survivor's rewind.

The same per-host dist spans carry measured ``steps_s``/``fetch_s``
walls, which :func:`skew_report` folds into straggler attribution:
per-round max/median ratios, the per-round offender, and the
persistent offender across rounds (rendered by
``tools/telemetry_report.py`` and floored by ``tools/perf_sentinel.py``
as ``pod_skew_ratio``).

Pure stdlib, no package imports: ``tools/trace_viewer.py`` loads this
file by path to keep its runs-anywhere, no-jax contract.
"""

from __future__ import annotations

import json
import os
import statistics
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "load_stream",
    "expand_inputs",
    "host_index",
    "estimate_offsets",
    "stitch",
    "stitch_files",
    "skew_report",
    "render_skew",
]

#: span names that end at a cross-host sync barrier (the blocking
#: replicated-reduce fetch in DistributedSweep.sweep_forest)
DIST_SPAN_PREFIX = "dist_level_"
DIST_LEAF_SPAN = "dist_leaf"


def _is_dist_span(ev: Dict[str, Any]) -> bool:
    if ev.get("event") != "span":
        return False
    name = ev.get("name", "")
    return name.startswith(DIST_SPAN_PREFIX) or name == DIST_LEAF_SPAN


def load_stream(path: str) -> List[Dict[str, Any]]:
    """One telemetry JSONL stream, lenient about a half-written tail
    line (the stream is append-only; a killed host stops mid-line)."""
    out: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def expand_inputs(paths: Sequence[str]) -> List[str]:
    """Resolve a mix of files and directories into a deterministic list
    of JSONL streams: directories are walked recursively (sorted), only
    ``*.jsonl`` files are taken, and duplicates are dropped preserving
    first-seen order — the shape the streaming CI job uploads
    (``**/telemetry_p*.jsonl`` under one artifact root)."""
    out: List[str] = []
    seen = set()

    def add(p: str) -> None:
        rp = os.path.abspath(p)
        if rp not in seen:
            seen.add(rp)
            out.append(p)

    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs.sort()
                for name in sorted(files):
                    if name.endswith(".jsonl"):
                        add(os.path.join(root, name))
        else:
            add(p)
    return out


def host_index(events: List[Dict[str, Any]], default: int) -> int:
    """The host (process) index a stream was written by — the
    ``dist_config`` row records ``jax.process_index()``; streams without
    one (single-host fits) fall back to their input position."""
    for ev in events:
        if ev.get("event") == "dist_config" and "process" in ev:
            return int(ev["process"])
    return default


def _dist_fit_order(events: List[Dict[str, Any]]) -> List[str]:
    """fit_ids of this stream's distributed fits, in first-``dist_config``
    order — position k is pod-wide fit group k."""
    order: List[str] = []
    seen = set()
    for ev in events:
        if ev.get("event") == "dist_config":
            fid = ev.get("fit_id", "?")
            if fid not in seen:
                seen.add(fid)
                order.append(fid)
    return order


def _barrier_points(
    events: List[Dict[str, Any]], fit_id: str
) -> Dict[Tuple, float]:
    """Wall-clock times at which this host crossed each sync barrier of
    one fit, keyed so the same barrier matches across hosts: the i-th
    manifest agreement, and the i-th occurrence of each dist sweep span
    (barrier = the moment its blocking reduce fetch returned —
    ``ts + steps_s + fetch_s`` when the span carries the measured
    split, else the span end)."""
    pts: Dict[Tuple, float] = {}
    agree_i = 0
    name_counts: Dict[str, int] = {}
    for ev in events:
        if ev.get("fit_id") != fit_id:
            continue
        if ev.get("event") == "dist_manifest_agreed":
            pts[("agree", agree_i)] = float(ev.get("ts", 0.0))
            agree_i += 1
        elif _is_dist_span(ev):
            name = ev.get("name", "")
            k = name_counts.get(name, 0)
            name_counts[name] = k + 1
            ts = float(ev.get("ts", 0.0))
            if "steps_s" in ev and "fetch_s" in ev:
                barrier = ts + float(ev["steps_s"]) + float(ev["fetch_s"])
            else:
                barrier = ts + float(ev.get("dur_s", 0.0))
            pts[("span", name, k)] = barrier
    return pts


def estimate_offsets(
    streams: Sequence[List[Dict[str, Any]]],
) -> List[float]:
    """Per-stream clock offsets relative to stream 0, estimated at the
    matched sync barriers of each pod-wide fit group.  The median over
    matched barriers rejects the occasional late unblock (a host that
    also ran the finish program before its next barrier); a stream
    sharing no barriers with the reference keeps offset 0.0."""
    if not streams:
        return []
    per_stream: List[Dict[Tuple, float]] = []
    for events in streams:
        pts: Dict[Tuple, float] = {}
        for g, fid in enumerate(_dist_fit_order(events)):
            for key, ts in _barrier_points(events, fid).items():
                pts[(g,) + key] = ts
        per_stream.append(pts)
    ref = per_stream[0]
    offsets = [0.0]
    for pts in per_stream[1:]:
        deltas = [pts[k] - ref[k] for k in pts.keys() & ref.keys()]
        offsets.append(statistics.median(deltas) if deltas else 0.0)
    return offsets


def stitch(
    streams: Sequence[List[Dict[str, Any]]],
    offsets: Optional[Sequence[float]] = None,
    hosts: Optional[Sequence[int]] = None,
) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Merge per-host streams into one pod-level event list (sorted by
    aligned ``ts``) plus a stitch-info summary.  See the module
    docstring for the rewrite rules."""
    if hosts is None:
        hosts = [host_index(ev, i) for i, ev in enumerate(streams)]
    if offsets is None:
        offsets = estimate_offsets(streams)
    group_maps = [
        {fid: g for g, fid in enumerate(_dist_fit_order(ev))}
        for ev in streams
    ]
    digests: Dict[int, Dict[int, str]] = {}
    merged: List[Dict[str, Any]] = []
    bounds: Dict[int, List[float]] = {}
    for events, h, off, gmap in zip(streams, hosts, offsets, group_maps):
        for ev in events:
            row = dict(ev)
            if "ts" in row:
                row["ts"] = float(row["ts"]) - off
            row["host"] = h
            g = gmap.get(row.get("fit_id", ""))
            if row.get("event") == "dist_manifest_agreed" and g is not None:
                digests.setdefault(g, {})[h] = row.get("digest", "")
            if row.get("event") == "span":
                if row.get("span_id"):
                    row["span_id"] = f"h{h}.{row['span_id']}"
                if row.get("parent_id"):
                    row["parent_id"] = f"h{h}.{row['parent_id']}"
                th = row.get("thread")
                if not th or th == "main":
                    row["thread"] = f"host{h}"
                elif th == f"host{h}" or th.startswith(f"host{h}/"):
                    pass
                else:
                    row["thread"] = f"host{h}/{th}"
                if g is not None:
                    row["trace_id"] = f"pod.{g}"
                    if not row.get("parent_id"):
                        row["parent_id"] = f"pod.{g}.root"
                    ts = float(row.get("ts", 0.0))
                    b = bounds.setdefault(g, [ts, ts])
                    b[0] = min(b[0], ts)
                    b[1] = max(b[1], ts + float(row.get("dur_s", 0.0)))
            merged.append(row)
    for g, (lo, hi) in sorted(bounds.items()):
        merged.append({
            "event": "span",
            "name": f"pod_fit_{g}",
            "trace_id": f"pod.{g}",
            "span_id": f"pod.{g}.root",
            "parent_id": "",
            "ts": lo,
            "dur_s": max(hi - lo, 0.0),
            "pid": 0,
            "thread": "pod",
            "fit_id": f"pod:{g}",
            "hosts": list(hosts),
        })
    merged.sort(key=lambda e: float(e.get("ts", 0.0)))
    mismatches = [
        {"group": g, "digests": dict(per)}
        for g, per in sorted(digests.items())
        if len(set(per.values())) > 1
    ]
    info = {
        "streams": len(streams),
        "hosts": list(hosts),
        "offsets": [float(o) for o in offsets],
        "groups": len(bounds),
        "digest_mismatches": mismatches,
    }
    return merged, info


def stitch_files(
    paths: Sequence[str],
) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """:func:`stitch` over :func:`expand_inputs`-resolved paths; the
    info dict gains the resolved ``inputs`` list."""
    resolved = expand_inputs(paths)
    streams = [load_stream(p) for p in resolved]
    merged, info = stitch(streams)
    info["inputs"] = resolved
    return merged, info


# ---------------------------------------------------------------------------
# straggler & skew detection
# ---------------------------------------------------------------------------


def _median(values: List[float]) -> float:
    return statistics.median(values) if values else 0.0


def skew_report(
    streams: Sequence[List[Dict[str, Any]]],
    hosts: Optional[Sequence[int]] = None,
) -> Dict[str, Any]:
    """Fold per-host sweep/reduce/shard-wait walls into straggler
    attribution.  Per round (the ``round`` attr on dist sweep spans):
    the max/median ratio across hosts and the offending host (ties
    break to the smallest index, so attribution is deterministic).
    ``pod_skew_ratio`` is the same ratio over whole-fit per-host sweep
    walls — 1.0 for a single host, the sentinel's healthy floor.
    ``host_stalled`` chaos events are tallied separately by victim so
    an injected stall is attributable even when only one process emits
    (single-process simulated pods)."""
    if hosts is None:
        hosts = [host_index(ev, i) for i, ev in enumerate(streams)]
    per_host: Dict[int, Dict[str, float]] = {}
    rounds: Dict[int, Dict[int, float]] = {}
    stalls: Dict[int, Dict[str, float]] = {}
    for events, h in zip(streams, hosts):
        agg = per_host.setdefault(h, {
            "steps_s": 0.0, "fetch_s": 0.0, "sweep_s": 0.0,
            "reduce_s": 0.0, "shard_wait_s": 0.0,
        })
        for ev in events:
            if _is_dist_span(ev):
                steps = float(ev.get("steps_s", ev.get("dur_s", 0.0)))
                agg["steps_s"] += steps
                agg["fetch_s"] += float(ev.get("fetch_s", 0.0))
                rnd = int(ev.get("round", -1))
                rounds.setdefault(rnd, {})
                rounds[rnd][h] = rounds[rnd].get(h, 0.0) + steps
            elif ev.get("event") == "dist_sweep":
                agg["sweep_s"] += float(ev.get("sweep_us", 0.0)) / 1e6
                agg["reduce_s"] += float(ev.get("reduce_us", 0.0)) / 1e6
            elif ev.get("event") == "shard_wait_us":
                agg["shard_wait_s"] += float(ev.get("wait_us", 0.0)) / 1e6
            elif ev.get("event") == "host_stalled":
                victim = int(ev.get("victim", h))
                slot = stalls.setdefault(
                    victim, {"count": 0, "seconds": 0.0}
                )
                slot["count"] += 1
                slot["seconds"] += float(ev.get("seconds", 0.0))
    round_rows: List[Dict[str, Any]] = []
    offender_counts: Dict[int, int] = {}
    for rnd in sorted(rounds):
        values = rounds[rnd]
        med = _median(list(values.values()))
        top = max(values.items(), key=lambda kv: (kv[1], -kv[0]))
        ratio = (top[1] / med) if med > 0 else 1.0
        round_rows.append({
            "round": rnd,
            "ratio": ratio,
            "offender": top[0],
            "values": {str(h): v for h, v in sorted(values.items())},
        })
        # balanced rounds (ratio ~1) carry no attribution signal — a
        # tie-broken "offender" there would dilute a real straggler's
        # persistence count
        if ratio > 1.1:
            offender_counts[top[0]] = offender_counts.get(top[0], 0) + 1
    # hosts with no distributed activity at all (a stream of single-host
    # fits) carry no skew signal — drop them so the report only renders
    # when there is a pod to report on
    per_host = {
        h: agg for h, agg in per_host.items()
        if any(v > 0 for v in agg.values())
        or any(h in vals for vals in rounds.values())
    }
    totals = {h: agg["steps_s"] for h, agg in per_host.items()}
    pod_ratio = 1.0
    if len(totals) > 1:
        med = _median(list(totals.values()))
        pod_ratio = (max(totals.values()) / med) if med > 0 else 1.0
    persistent = None
    if offender_counts:
        persistent = max(
            offender_counts.items(), key=lambda kv: (kv[1], -kv[0])
        )[0]
    return {
        "hosts": sorted(per_host),
        "per_host": {str(h): agg for h, agg in sorted(per_host.items())},
        "rounds": round_rows,
        "pod_skew_ratio": float(pod_ratio),
        "persistent_offender": persistent,
        "stalls": {str(v): s for v, s in sorted(stalls.items())},
    }


def render_skew(report: Dict[str, Any]) -> str:
    """The skew report as the text block ``tools/telemetry_report.py``
    appends after the per-fit sections."""
    lines = ["== pod skew =="]
    ratio = report.get("pod_skew_ratio", 1.0)
    head = f"pod_skew_ratio: {ratio:.2f}"
    persistent = report.get("persistent_offender")
    if persistent is not None:
        head += f"  persistent offender: host {persistent}"
    lines.append(head)
    for h in report.get("hosts", []):
        agg = report["per_host"][str(h)]
        lines.append(
            f"host {h}: sweep {agg['steps_s'] * 1e3:.1f}ms  "
            f"fetch {agg['fetch_s'] * 1e3:.1f}ms  "
            f"reduce {agg['reduce_s'] * 1e3:.1f}ms  "
            f"shard_wait {agg['shard_wait_s'] * 1e3:.1f}ms"
        )
    for row in report.get("rounds", []):
        vals = "  ".join(
            f"h{h}={v * 1e3:.1f}ms" for h, v in row["values"].items()
        )
        lines.append(
            f"round {row['round']}: ratio {row['ratio']:.2f}  "
            f"offender host {row['offender']}  ({vals})"
        )
    for victim, s in report.get("stalls", {}).items():
        lines.append(
            f"stalls: host {victim} x{int(s['count'])} "
            f"({s['seconds']:.2f}s injected)"
        )
    return "\n".join(lines)
