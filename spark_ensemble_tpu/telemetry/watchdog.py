"""Online perf watchdog: the ``tools/perf_sentinel.py`` thresholds
applied to **live** metrics instead of post-hoc bench records
(docs/operator.md).

A :class:`Watchdog` evaluates a small rule table on a rolling cadence
against ``global_metrics().snapshot()`` — serving p99 and hedge rate
from the live ``fleet/*`` sources, steady-state compile count, the
host-blocked share and cost-model error gauges the fit ledger
publishes — and drives a two-state alert machine per rule: a rule must
breach for ``breach_for`` consecutive ticks to raise an ``slo_alert``
telemetry event, and then hold healthy for ``clear_for`` consecutive
ticks before the matching ``cleared`` event fires (hysteresis, so one
hedged request or one straggling round does not flap the verdict).

The verdict is what ``/healthz`` serves (503 while any alert is
active) and what the planned continual-learning rollback loop will
consume.  Probes only read already-collected registry state: no device
values are fetched, no programs traced, no blocking reads — pinned by
the tier-2 ``operator`` graftlint contract.

Thresholds come from the repo's own sentinel when available: rule
defaults are derived from ``tools/perf_sentinel.py`` ``METRICS``
(direction + noise floors) joined with ``PERF_BASELINE.json``, exactly
the way the offline gate computes its allowance; metrics the baseline
does not pin fall back to the documented defaults below.
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Rule", "Watchdog", "default_rules", "sentinel_thresholds",
           "probe_fleet_max", "probe_gauge", "probe_quality_max"]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: metric -> (direction, threshold) used when neither the sentinel
#: module nor the committed baseline pins the metric.  Values are
#: deliberately loose — the watchdog is a tripwire for "clearly wrong",
#: the offline sentinel stays the precision gate (docs/operator.md).
FALLBACK_THRESHOLDS: Dict[str, tuple] = {
    "serving_p99_ms":        ("lower", 250.0),
    "hedge_rate":            ("lower", 0.5),
    "compiles_since_warmup": ("lower", 0.0),
    "host_blocked_share":    ("lower", 0.75),
    "cost_model_error_pct":  ("lower", 200.0),
    # model-quality plane (telemetry/quality.py, docs/quality.md): the
    # conventional PSI major-shift mark, and the shadow scorer's rolling
    # prediction-divergence ceiling (same number by design — both read
    # "a quarter of the signal moved")
    "quality_psi_max":       ("lower", 0.25),
    "shadow_divergence":     ("lower", 0.25),
}


def sentinel_thresholds(
    repo_root: str = _REPO,
) -> Dict[str, tuple]:
    """(direction, threshold) per watchdog metric, derived from the
    offline sentinel's ``METRICS`` floors + ``PERF_BASELINE.json`` the
    same way ``tools/perf_sentinel.py compare`` computes its allowance:
    for a "lower" metric with baseline ``b`` the live threshold is
    ``max(b * (1 + rel_floor), b + abs_floor)``.  Metrics absent from
    the baseline (or when the tools/ checkout is not present — installed
    wheels) keep :data:`FALLBACK_THRESHOLDS`."""
    out = dict(FALLBACK_THRESHOLDS)
    sentinel_path = os.path.join(repo_root, "tools", "perf_sentinel.py")
    baseline_path = os.path.join(repo_root, "PERF_BASELINE.json")
    if not os.path.exists(sentinel_path):
        return out
    try:
        spec = importlib.util.spec_from_file_location(
            "_se_tpu_perf_sentinel", sentinel_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        metrics = dict(getattr(mod, "METRICS", {}))
    except Exception:  # noqa: BLE001 - sentinel drift never kills serving
        return out
    baseline: Dict[str, Any] = {}
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as fh:
                baseline = json.load(fh)
        except (OSError, ValueError):
            baseline = {}
    for name, (direction, rel, floor) in metrics.items():
        if name not in out:
            continue  # offline-only metric (fit_seconds, throughput, ...)
        base = baseline.get(name)
        if not isinstance(base, (int, float)):
            continue  # baseline does not pin it: keep the fallback
        base = float(base)
        if direction == "lower":
            out[name] = ("lower", max(base * (1.0 + rel), base + floor))
        else:
            out[name] = ("higher", min(base * (1.0 - rel), base - floor))
    return out


# ---------------------------------------------------------------------------
# probes: read a registry snapshot, return the live value (or None)
# ---------------------------------------------------------------------------


def _source_values(
    snapshot: Dict[str, Any], prefix: str, key: str
) -> List[float]:
    vals: List[float] = []
    for name, snap in snapshot.items():
        if not name.startswith(prefix) or snap.get("type") != "source":
            continue
        value = snap.get("value")
        if isinstance(value, dict) and isinstance(
            value.get(key), (int, float)
        ):
            vals.append(float(value[key]))
    return vals


def _fleet_values(snapshot: Dict[str, Any], key: str) -> List[float]:
    return _source_values(snapshot, "fleet/", key)


def probe_fleet_max(key: str) -> Callable[[Dict[str, Any]], Optional[float]]:
    def probe(snapshot: Dict[str, Any]) -> Optional[float]:
        vals = _fleet_values(snapshot, key)
        return max(vals) if vals else None
    return probe


def probe_quality_max(
    key: str,
) -> Callable[[Dict[str, Any]], Optional[float]]:
    """Max of ``key`` across the live ``quality/*`` sources (drift
    monitors publish ``psi_max``, shadow scorers ``divergence``) — one
    drifting stream degrades the process.  ``None`` (frozen rule) while
    no quality source is live or none has completed a window yet."""
    def probe(snapshot: Dict[str, Any]) -> Optional[float]:
        vals = _source_values(snapshot, "quality/", key)
        return max(vals) if vals else None
    return probe


def probe_gauge(name: str, absolute: bool = False):
    def probe(snapshot: Dict[str, Any]) -> Optional[float]:
        snap = snapshot.get(name)
        if not snap or snap.get("type") != "gauge":
            return None
        value = snap.get("value")
        if not isinstance(value, (int, float)):
            return None
        return abs(float(value)) if absolute else float(value)
    return probe


@dataclass
class Rule:
    """One watched SLO: a probe over the registry snapshot, a threshold
    with a direction, and the raise/clear hysteresis widths (ticks)."""

    name: str
    probe: Callable[[Dict[str, Any]], Optional[float]]
    threshold: float
    direction: str = "lower"       # "lower": value must stay <= threshold
    breach_for: int = 2            # consecutive breaching ticks to raise
    clear_for: int = 3             # consecutive healthy ticks to clear
    # mutable alert state (owned by the watchdog tick loop)
    active: bool = field(default=False, repr=False)
    breach_ticks: int = field(default=0, repr=False)
    ok_ticks: int = field(default=0, repr=False)
    last_value: Optional[float] = field(default=None, repr=False)

    def breaching(self, value: float) -> bool:
        if self.direction == "lower":
            return value > self.threshold
        return value < self.threshold


def default_rules(
    thresholds: Optional[Dict[str, tuple]] = None,
    breach_for: int = 2,
    clear_for: int = 3,
) -> List[Rule]:
    """The standard rule table (docs/operator.md): serving p99 + hedge
    rate + steady-state compiles from the live ``fleet/*`` sources
    (max across routers — one sick stream degrades the process), the
    fit ledger's host-blocked share, the absolute cost-model error, and
    the model-quality plane's per-feature PSI + shadow divergence."""
    th = thresholds or sentinel_thresholds()
    probes: Dict[str, Callable] = {
        "serving_p99_ms": probe_fleet_max("p99_ms"),
        "hedge_rate": probe_fleet_max("hedge_rate"),
        "compiles_since_warmup": probe_fleet_max("compiles_since_warmup"),
        "host_blocked_share": probe_gauge("fit/host_blocked_share"),
        "cost_model_error_pct": probe_gauge(
            "fit/cost_model_error_pct", absolute=True),
        # sustained feature drift or candidate divergence is a health
        # incident: same hysteresis as the systems rules (docs/quality.md)
        "quality_psi_max": probe_quality_max("psi_max"),
        "shadow_divergence": probe_quality_max("divergence"),
    }
    rules = []
    for name, probe in probes.items():
        direction, threshold = th.get(
            name, FALLBACK_THRESHOLDS.get(name, ("lower", 0.0)))
        rules.append(Rule(
            name=name, probe=probe, threshold=float(threshold),
            direction=direction, breach_for=breach_for,
            clear_for=clear_for,
        ))
    return rules


class Watchdog:
    """Rolling evaluator + alert state machine over the live registry.

    ``start()`` runs :meth:`evaluate_once` every ``interval_s`` on a
    daemon thread; tests drive the machine deterministically by calling
    :meth:`evaluate_once` themselves (no thread, no clock coupling).
    ``slo_alert`` events go through :func:`emit_event` (so they land in
    the same JSONL stream as ``fleet_slo`` rows and show up as instant
    markers in the exported Perfetto trace), and the registry carries
    ``watchdog/alerts_active`` / ``watchdog/alerts_total`` for scrapes.
    """

    def __init__(self, rules: Optional[List[Rule]] = None,
                 interval_s: float = 2.0,
                 telemetry_path: Optional[str] = None,
                 registry=None):
        from spark_ensemble_tpu.telemetry.events import (
            global_metrics, serving_stream_id,
        )

        self.rules = list(rules) if rules is not None else default_rules()
        self.interval_s = float(interval_s)
        self._telemetry_path = telemetry_path
        self._registry = registry if registry is not None else global_metrics()
        self._stream = serving_stream_id("watchdog")
        self._lock = threading.Lock()
        self._ticks = 0
        self._gauge_active = self._registry.gauge("watchdog/alerts_active")
        self._gauge_active.set(0)
        self._counter_total = self._registry.counter("watchdog/alerts_total")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- alert plumbing ----------------------------------------------------

    def _emit(self, rule: Rule, state: str) -> None:
        from spark_ensemble_tpu.telemetry.events import emit_event

        emit_event(
            "slo_alert",
            path=self._telemetry_path,
            stream=self._stream,
            state=state,
            metric=rule.name,
            value=rule.last_value,
            threshold=rule.threshold,
            direction=rule.direction,
            ticks=self._ticks,
        )

    def evaluate_once(
        self, snapshot: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """One tick: probe every rule, advance its hysteresis counters,
        raise/clear alerts.  Returns the per-rule readings (the shape
        ``/statusz`` embeds).  Safe to call concurrently with the
        background thread (one tick at a time under the lock)."""
        if snapshot is None:
            snapshot = self._registry.snapshot()
        with self._lock:
            self._ticks += 1
            readings: Dict[str, Any] = {}
            for rule in self.rules:
                value = None
                try:
                    value = rule.probe(snapshot)
                except Exception:  # noqa: BLE001 - a probe bug != an outage
                    value = None
                rule.last_value = value
                if value is None:
                    # nothing live to judge (no fleet running, no fit
                    # finished): freeze the state machine, don't clear
                    readings[rule.name] = {
                        "value": None, "threshold": rule.threshold,
                        "active": rule.active,
                    }
                    continue
                if rule.breaching(value):
                    rule.breach_ticks += 1
                    rule.ok_ticks = 0
                    if (not rule.active
                            and rule.breach_ticks >= rule.breach_for):
                        rule.active = True
                        self._counter_total.inc()
                        self._emit(rule, "raised")
                else:
                    rule.ok_ticks += 1
                    rule.breach_ticks = 0
                    if rule.active and rule.ok_ticks >= rule.clear_for:
                        rule.active = False
                        self._emit(rule, "cleared")
                readings[rule.name] = {
                    "value": value, "threshold": rule.threshold,
                    "active": rule.active,
                }
            self._gauge_active.set(
                sum(1 for r in self.rules if r.active))
            return readings

    def verdict(self) -> Dict[str, Any]:
        """The ``/healthz`` payload: ``ok`` unless any alert is active."""
        with self._lock:
            alerts = [
                {
                    "metric": r.name, "value": r.last_value,
                    "threshold": r.threshold, "direction": r.direction,
                }
                for r in self.rules if r.active
            ]
            return {
                "status": "degraded" if alerts else "ok",
                "alerts": alerts,
                "ticks": self._ticks,
                "interval_s": self.interval_s,
                "rules": {
                    r.name: {"threshold": r.threshold,
                             "direction": r.direction,
                             "value": r.last_value,
                             "active": r.active}
                    for r in self.rules
                },
            }

    # -- background loop ---------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 - the watchdog never crashes
                pass  # the process it watches

    def start(self) -> "Watchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="se-tpu-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
