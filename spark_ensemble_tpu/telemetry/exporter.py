"""Live operator endpoints: ``/metrics`` (OpenMetrics), ``/statusz``,
``/programz``, ``/healthz`` — a stdlib ``http.server`` thread over the
process's own state (docs/operator.md).

Scrape discipline: every endpoint renders **already-collected** state —
``global_metrics().snapshot()`` (counters/gauges/histograms plus the
live ``fleet/*`` / ``elastic/*`` statusz sources), the program
inventory's stored rows, and the watchdog's current verdict.  A scrape
never traces, lowers, or compiles a program (the tier-2
``operator.scrape`` contract pins zero program dispatches) and is safe
mid-fit and mid-serve: sources run outside the registry lock and take
only their owner's locks.

:class:`OperatorPlane` is the one-call bundle (inventory + HBM sampler +
watchdog + HTTP server) used by ``bench.py`` and the CI serving-chaos
job; ``python -m spark_ensemble_tpu.telemetry.exporter --snapshot DIR``
is the one-shot file mode (CI artifacts), and ``--validate FILE`` runs
the stdlib OpenMetrics syntax checker on an exposition file.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

__all__ = [
    "render_openmetrics",
    "validate_openmetrics",
    "OperatorServer",
    "OperatorPlane",
    "start_operator_plane",
    "write_snapshot",
]

#: every exported sample lives under this prefix, so one grep isolates
#: the package's metrics in a shared scrape
METRIC_PREFIX = "se_tpu"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str) -> str:
    n = _NAME_RE.sub("_", name.strip("/"))
    if n and n[0].isdigit():
        n = "_" + n
    return f"{METRIC_PREFIX}_{n}"


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\"", "\\\"")
        .replace("\n", "\\n")
    )


def _fmt(value: float) -> str:
    f = float(value)
    if f != f:
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _flatten_numeric(value: Any, path: str = "") -> List[Tuple[str, float]]:
    """Numeric/bool leaves of a source payload as (dotted path, value) —
    strings and nulls drop out (they are /statusz material, not samples)."""
    out: List[Tuple[str, float]] = []
    if isinstance(value, bool):
        out.append((path, 1.0 if value else 0.0))
    elif isinstance(value, (int, float)):
        out.append((path, float(value)))
    elif isinstance(value, dict):
        for k in sorted(value, key=str):
            sub = f"{path}.{k}" if path else str(k)
            out.extend(_flatten_numeric(value[k], sub))
    elif isinstance(value, (list, tuple)):
        out.append((f"{path}.len" if path else "len", float(len(value))))
    return out


def render_openmetrics(
    snapshot: Optional[Dict[str, Dict[str, Any]]] = None,
) -> str:
    """Render a ``MetricsRegistry.snapshot()`` as OpenMetrics 1.0 text.

    Counters become ``counter`` families (``_total`` samples), gauges
    ``gauge``, streaming histograms ``summary`` (p50/p90/p99 quantiles +
    ``_count``/``_sum`` — the registry keeps log2 buckets, not
    Prometheus-native ones, so quantiles are the honest export).  Live
    sources (``fleet/<stream>``, ``elastic/<label>``) flatten their
    numeric leaves into one gauge family per source group with
    ``source`` and ``field`` labels."""
    if snapshot is None:
        from spark_ensemble_tpu.telemetry.events import global_metrics

        snapshot = global_metrics().snapshot()
    plain: List[str] = []
    by_group: Dict[str, List[str]] = {}
    for name in sorted(snapshot):
        snap = snapshot[name]
        kind = snap.get("type")
        if kind == "counter":
            m = _metric_name(name)
            plain.append(f"# TYPE {m} counter")
            plain.append(f"{m}_total {_fmt(snap.get('value') or 0)}")
        elif kind == "gauge":
            value = snap.get("value")
            if value is None:
                continue
            m = _metric_name(name)
            plain.append(f"# TYPE {m} gauge")
            plain.append(f"{m} {_fmt(value)}")
        elif kind == "histogram":
            if not snap.get("count"):
                continue
            m = _metric_name(name)
            plain.append(f"# TYPE {m} summary")
            for q in ("0.5", "0.9", "0.99"):
                qv = snap.get({"0.5": "p50", "0.9": "p90", "0.99": "p99"}[q])
                if qv is not None:
                    plain.append(f'{m}{{quantile="{q}"}} {_fmt(qv)}')
            plain.append(f"{m}_count {_fmt(snap['count'])}")
            plain.append(f"{m}_sum {_fmt(snap.get('sum', 0.0))}")
        elif kind == "source":
            if "value" not in snap:
                continue  # erroring source: reported on /statusz instead
            group = name.split("/", 1)[0] if "/" in name else "source"
            stream = name.split("/", 1)[1] if "/" in name else name
            lines = by_group.setdefault(group, [])
            src = _escape_label(stream)
            for field, value in _flatten_numeric(snap["value"]):
                lines.append(
                    f'{_metric_name(group)}{{source="{src}",'
                    f'field="{_escape_label(field)}"}} {_fmt(value)}'
                )
    out: List[str] = list(plain)
    for group in sorted(by_group):
        out.append(f"# TYPE {_metric_name(group)} gauge")
        out.extend(by_group[group])
    out.append("# EOF")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# stdlib OpenMetrics syntax checker (the CI scrape validator)
# ---------------------------------------------------------------------------

_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|summary|histogram|info|stateset|unknown)$"
)
_META_RE = re.compile(r"^# (HELP|UNIT) ([a-zA-Z_:][a-zA-Z0-9_:]*) ?(.*)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf)|NaN|[+-]Inf)"
    r"(?: -?[0-9]+(?:\.[0-9]+)?)?$"
)

#: sample-name suffixes each family type may emit
_TYPE_SUFFIXES = {
    "counter": ("_total", "_created"),
    "gauge": ("",),
    "summary": ("", "_count", "_sum", "_created"),
    "histogram": ("_bucket", "_count", "_sum", "_created"),
    "info": ("_info",),
    "stateset": ("",),
    "unknown": ("",),
}


def validate_openmetrics(text: str) -> List[str]:
    """Line-level OpenMetrics 1.0 syntax check — pure stdlib, no client
    library.  Returns a list of violations (empty == valid): parseable
    metadata/sample lines only, every sample under a declared family
    with a type-legal suffix, no family re-declaration or interleaving,
    exactly one terminal ``# EOF``."""
    errors: List[str] = []
    lines = text.splitlines()
    if not lines or lines[-1] != "# EOF":
        errors.append("exposition must end with a terminal '# EOF' line")
    types: Dict[str, str] = {}
    closed: set = set()
    current: Optional[str] = None

    def _family_of(sample: str) -> Optional[str]:
        best = None
        for fam, kind in types.items():
            for suffix in _TYPE_SUFFIXES[kind]:
                if sample == fam + suffix and (
                    best is None or len(fam) > len(best)
                ):
                    best = fam
        return best

    for i, line in enumerate(lines, 1):
        if line == "# EOF":
            if i != len(lines):
                errors.append(f"line {i}: content after '# EOF'")
                break
            continue
        if not line or line[0] == "#":
            m = _TYPE_RE.match(line)
            if m:
                fam, kind = m.group(1), m.group(2)
                if fam in types:
                    errors.append(f"line {i}: duplicate TYPE for '{fam}'")
                if current is not None:
                    closed.add(current)
                types[fam] = kind
                current = fam
                continue
            if _META_RE.match(line):
                continue
            errors.append(f"line {i}: unparseable comment/metadata: {line!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"line {i}: unparseable sample line: {line!r}")
            continue
        fam = _family_of(m.group(1))
        if fam is None:
            errors.append(
                f"line {i}: sample '{m.group(1)}' has no declared TYPE "
                "family (or an illegal suffix for its type)"
            )
            continue
        if fam != current:
            if fam in closed:
                errors.append(
                    f"line {i}: family '{fam}' interleaved with other "
                    "families (samples must be contiguous)"
                )
            if current is not None:
                closed.add(current)
            current = fam
    return errors


# ---------------------------------------------------------------------------
# HTTP server
# ---------------------------------------------------------------------------

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)


class _Handler(BaseHTTPRequestHandler):
    server_version = "se-tpu-operator"

    def log_message(self, fmt, *args):  # noqa: D102 - silence per-scrape
        pass  # log lines (scrapes are periodic; stderr noise helps nobody)

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, payload: Any, code: int = 200) -> None:
        body = json.dumps(payload, sort_keys=True, default=str).encode()
        self._send(code, body, "application/json; charset=utf-8")

    def do_GET(self):  # noqa: N802 - http.server API
        srv: "OperatorServer" = self.server  # type: ignore[assignment]
        url = urlparse(self.path)
        try:
            if url.path == "/metrics":
                srv.scrapes.inc()
                text = render_openmetrics(srv.registry.snapshot())
                self._send(200, text.encode(), OPENMETRICS_CONTENT_TYPE)
            elif url.path == "/statusz":
                self._send_json(srv.statusz())
            elif url.path == "/programz":
                q = parse_qs(url.query)
                top = None
                if q.get("n"):
                    try:
                        top = int(q["n"][0])
                    except ValueError:
                        top = None
                rows = srv.inventory.rows(top=top)
                self._send_json({
                    "programs": rows,
                    "summary": srv.inventory.summary(),
                })
            elif url.path == "/healthz":
                verdict = srv.health_verdict()
                code = 200 if verdict.get("status") == "ok" else 503
                self._send_json(verdict, code=code)
            elif url.path == "/qualityz":
                self._send_json(srv.qualityz())
            else:
                self._send_json({"error": f"no such endpoint {url.path}",
                                 "endpoints": ["/metrics", "/statusz",
                                               "/programz", "/healthz",
                                               "/qualityz"]},
                                code=404)
        except BrokenPipeError:  # scraper went away mid-reply
            pass


class OperatorServer(ThreadingHTTPServer):
    """The endpoint server: binds, serves on a daemon thread, renders the
    process's registry / inventory / watchdog verdict.  ``port=0`` binds
    an ephemeral port (tests, bench); the bound port is ``self.port``."""

    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry=None, inventory=None, watchdog=None):
        super().__init__((host, int(port)), _Handler)
        if registry is None:
            from spark_ensemble_tpu.telemetry.events import global_metrics

            registry = global_metrics()
        if inventory is None:
            from spark_ensemble_tpu.telemetry import programz

            inventory = programz.global_inventory()
        self.registry = registry
        self.inventory = inventory
        self.watchdog = watchdog
        self.t0 = time.time()
        self.scrapes = self.registry.counter("operator/scrapes")
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.server_address[0]}:{self.port}"

    def start(self) -> "OperatorServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self.serve_forever, name="se-tpu-operator-http",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self.shutdown()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self.server_close()

    def statusz(self) -> Dict[str, Any]:
        import sys

        backend = "uninitialized"
        jax = sys.modules.get("jax")
        if jax is not None:
            # never import (let alone initialize) jax for a scrape; only
            # report the backend the process already brought up
            try:
                backend = jax.default_backend()
            except Exception:  # noqa: BLE001
                backend = "error"
        out: Dict[str, Any] = {
            "pid": os.getpid(),
            "uptime_s": time.time() - self.t0,
            "backend": backend,
            "scrapes": self.scrapes.value,
            "programs": self.inventory.summary(),
            "watchdog": self.health_verdict(),
            "metrics": self.registry.snapshot(),
        }
        return out

    def health_verdict(self) -> Dict[str, Any]:
        if self.watchdog is None:
            return {"status": "ok", "watchdog": "not attached"}
        return self.watchdog.verdict()

    def qualityz(self) -> Dict[str, Any]:
        """The model-quality page (docs/quality.md): every live
        ``quality/*`` source (drift monitors, shadow scorers) plus the
        plane's gauges/counters/histograms, and the watchdog's two
        quality rules when one is attached."""
        snapshot = self.registry.snapshot()
        sources: Dict[str, Any] = {}
        series: Dict[str, Any] = {}
        for name, snap in sorted(snapshot.items()):
            if not name.startswith("quality/"):
                continue
            if snap.get("type") == "source":
                sources[name[len("quality/"):]] = snap.get("value")
            else:
                series[name] = snap.get(
                    "value", snap.get("stats", snap)
                )
        rules = {}
        if self.watchdog is not None:
            rules = {
                name: r
                for name, r in self.watchdog.verdict()["rules"].items()
                if name in ("quality_psi_max", "shadow_divergence")
            }
        return {
            "streams": sources,
            "series": series,
            "watchdog": rules,
        }


class OperatorPlane:
    """The whole live operator plane in one handle: program inventory
    enabled, HBM sampler running, watchdog evaluating, endpoints served.
    ``stop()`` tears everything down (inventory capture included)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 watchdog=None, with_watchdog: bool = True,
                 sampler_interval_s: float = 1.0,
                 watchdog_interval_s: float = 2.0,
                 telemetry_path: Optional[str] = None):
        from spark_ensemble_tpu.telemetry import programz

        self.inventory = programz.enable()
        self.sampler = programz.HbmSampler(interval_s=sampler_interval_s)
        if watchdog is None and with_watchdog:
            from spark_ensemble_tpu.telemetry.watchdog import Watchdog

            watchdog = Watchdog(interval_s=watchdog_interval_s,
                                telemetry_path=telemetry_path)
        self.watchdog = watchdog
        self.server = OperatorServer(
            host=host, port=port, inventory=self.inventory,
            watchdog=watchdog,
        )

    @property
    def url(self) -> str:
        return self.server.url

    def start(self) -> "OperatorPlane":
        self.sampler.start()
        if self.watchdog is not None:
            self.watchdog.start()
        self.server.start()
        return self

    def stop(self) -> None:
        from spark_ensemble_tpu.telemetry import programz

        self.server.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        self.sampler.stop()
        programz.disable()


def start_operator_plane(port: int = 0, **kwargs) -> OperatorPlane:
    """Convenience: build and start an :class:`OperatorPlane` (the call
    ``bench.py`` and the CI chaos driver make)."""
    return OperatorPlane(port=port, **kwargs).start()


# ---------------------------------------------------------------------------
# one-shot snapshot mode (CI artifacts) + CLI
# ---------------------------------------------------------------------------


def write_snapshot(out_dir: str, registry=None, inventory=None,
                   watchdog=None) -> Dict[str, str]:
    """Write ``metrics.txt`` / ``statusz.json`` / ``programz.json`` into
    ``out_dir`` from the current process state; returns the paths.  The
    metrics exposition is validated before it is written — a CI artifact
    that fails the stdlib checker fails the job that produced it."""
    os.makedirs(out_dir, exist_ok=True)
    srv = OperatorServer.__new__(OperatorServer)  # render without binding
    if registry is None:
        from spark_ensemble_tpu.telemetry.events import global_metrics

        registry = global_metrics()
    if inventory is None:
        from spark_ensemble_tpu.telemetry import programz

        inventory = programz.global_inventory()
    srv.registry = registry
    srv.inventory = inventory
    srv.watchdog = watchdog
    srv.t0 = time.time()
    srv.scrapes = registry.counter("operator/scrapes")
    text = render_openmetrics(registry.snapshot())
    problems = validate_openmetrics(text)
    if problems:
        raise ValueError(
            "generated exposition fails the OpenMetrics checker: "
            + "; ".join(problems[:5])
        )
    paths = {
        "metrics": os.path.join(out_dir, "metrics.txt"),
        "statusz": os.path.join(out_dir, "statusz.json"),
        "programz": os.path.join(out_dir, "programz.json"),
        "qualityz": os.path.join(out_dir, "qualityz.json"),
    }
    with open(paths["metrics"], "w") as f:
        f.write(text)
    with open(paths["statusz"], "w") as f:
        json.dump(srv.statusz(), f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    with open(paths["programz"], "w") as f:
        json.dump({"programs": inventory.rows(),
                   "summary": inventory.summary()},
                  f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    with open(paths["qualityz"], "w") as f:
        json.dump(srv.qualityz(), f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    return paths


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--snapshot", metavar="DIR", default=None,
        help="write metrics.txt/statusz.json/programz.json for this "
        "process's current state and exit (the CI artifact mode)",
    )
    parser.add_argument(
        "--validate", metavar="FILE", default=None,
        help="run the stdlib OpenMetrics syntax checker on an exposition "
        "file; non-zero exit on violations",
    )
    args = parser.parse_args(argv)
    if args.validate:
        with open(args.validate) as f:
            problems = validate_openmetrics(f.read())
        for p in problems:
            print(p)
        print(json.dumps({"file": args.validate, "ok": not problems,
                          "violations": len(problems)}))
        return 1 if problems else 0
    if args.snapshot:
        paths = write_snapshot(args.snapshot)
        print(json.dumps({"snapshot": paths}))
        return 0
    parser.error("one of --snapshot / --validate is required")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
