"""Training observability: metrics, structured fit events, compile stats.

See ``docs/telemetry.md`` for how to enable the JSONL sink
(``SE_TPU_TELEMETRY`` / the ``telemetry_path`` param), the event schema,
and ``tools/telemetry_report.py`` for rendering streams into the same
per-phase cost table ``utils/profiling.py`` produces from profiler traces.
Pod scope (``docs/tracing.md#pod-scope``): ``podview`` stitches per-host
streams into one pod trace and folds straggler skew; ``flight`` keeps the
per-process crash ring dumped on preemption.
Live operator plane (``docs/operator.md``): ``programz`` keeps the
per-compiled-program XLA cost inventory, ``exporter`` serves it (with the
whole registry) over ``/metrics``/``/statusz``/``/programz``/``/healthz``,
and ``watchdog`` applies the perf-sentinel thresholds online.
Model-quality plane (``docs/quality.md``): ``quality`` scores on-device
feature-drift sketches against the fit-time bin reference, decomposes
requests over ensemble prefixes (staged attribution), and shadow-scores
registry candidates — served at ``/qualityz`` and watched by the same
watchdog.
"""

from spark_ensemble_tpu.telemetry.flight import (
    FlightRecorder,
    dump_flight,
    flight_dump_path,
)
from spark_ensemble_tpu.telemetry.podview import (
    estimate_offsets,
    skew_report,
    stitch,
    stitch_files,
)

from spark_ensemble_tpu.telemetry.registry import (
    Counter,
    Gauge,
    MetricsRegistry,
    RoundTimer,
    StreamingHistogram,
)
from spark_ensemble_tpu.telemetry.events import (
    FitTelemetry,
    TelemetryRecorder,
    device_memory_stats,
    emit_event,
    global_metrics,
    record_fits,
    serving_stream_id,
    telemetry_sink_active,
)
from spark_ensemble_tpu.telemetry.exporter import (
    OperatorPlane,
    OperatorServer,
    render_openmetrics,
    start_operator_plane,
    validate_openmetrics,
    write_snapshot,
)
from spark_ensemble_tpu.telemetry.programz import (
    HbmSampler,
    ProgramInventory,
    ProgramRecord,
    global_inventory,
    xla_cost_fields,
)
from spark_ensemble_tpu.telemetry.quality import (
    DriftMonitor,
    ShadowScorer,
    drift_reference_from_ctx,
    kl_divergence,
    psi,
    staged_attribution,
)
from spark_ensemble_tpu.telemetry.watchdog import (
    Rule,
    Watchdog,
    default_rules,
    probe_quality_max,
    sentinel_thresholds,
)
from spark_ensemble_tpu.telemetry.trace import (
    NULL_SPAN,
    NULL_TRACER,
    Span,
    TraceContext,
    Tracer,
    new_flow_id,
    new_span_id,
    new_trace_id,
    trace_annotations_enabled,
)

__all__ = [
    "Counter",
    "Gauge",
    "MetricsRegistry",
    "RoundTimer",
    "StreamingHistogram",
    "FitTelemetry",
    "TelemetryRecorder",
    "device_memory_stats",
    "emit_event",
    "global_metrics",
    "record_fits",
    "serving_stream_id",
    "telemetry_sink_active",
    "Span",
    "TraceContext",
    "Tracer",
    "NULL_SPAN",
    "NULL_TRACER",
    "new_trace_id",
    "new_span_id",
    "new_flow_id",
    "trace_annotations_enabled",
    "FlightRecorder",
    "dump_flight",
    "flight_dump_path",
    "estimate_offsets",
    "skew_report",
    "stitch",
    "stitch_files",
    "ProgramInventory",
    "ProgramRecord",
    "HbmSampler",
    "global_inventory",
    "xla_cost_fields",
    "OperatorPlane",
    "OperatorServer",
    "render_openmetrics",
    "start_operator_plane",
    "validate_openmetrics",
    "write_snapshot",
    "Rule",
    "Watchdog",
    "default_rules",
    "probe_quality_max",
    "sentinel_thresholds",
    "DriftMonitor",
    "ShadowScorer",
    "drift_reference_from_ctx",
    "kl_divergence",
    "psi",
    "staged_attribution",
]
