"""Per-program XLA cost inventory — the ``/programz`` operator surface.

The round ledger (telemetry/events.py) prices every round with the
hand-written analytic model ``ops/tree.py:round_cost_est``; this module
adds the third leg of the cost triangle: what XLA itself says the
compiled program costs.  A process-wide :class:`ProgramInventory` hooks
the ``cached_program`` / ``_predict_program`` chokepoints in
``models/base.py`` (via :func:`~spark_ensemble_tpu.models.base.
set_program_sink`) and records, per distinct ``(tag, abstract argument
signature)`` program: call count, build wall, first-call wall (the
synchronous trace+compile part of dispatch), and — once analyzed — the
XLA ``cost_analysis()`` / ``memory_analysis()`` numbers (flops, bytes
accessed, argument/output/temp HBM).

Analysis is deliberately decoupled from capture:

- **capture** is a dict update per call (safe on fit and serve paths);
- **analysis** re-lowers the program from stored ``ShapeDtypeStruct``
  avals (no device buffers are retained) and asks XLA for its cost
  model.  ``deep=False`` (the default used by the background sampler)
  stops at ``Lowered.cost_analysis()`` — **zero backend compiles**, so
  the zero-compile serving contracts cannot be perturbed; ``deep=True``
  additionally compiles for ``memory_analysis()`` (explicit calls only).

``/programz`` scrapes (telemetry/exporter.py) render *stored* rows and
never trace, lower, or compile — the tier-2 ``operator.scrape`` contract
pins that.  The :class:`HbmSampler` is the background HBM-watermark
thread feeding ``hbm/<dev>/*`` gauges in ``global_metrics()`` and
draining pending (shallow) analysis off the hot path.

See docs/operator.md for the row schema and the documented CPU tolerance
between XLA flops and the analytic ``round_cost_est``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "ProgramRecord",
    "ProgramInventory",
    "HbmSampler",
    "global_inventory",
    "enable",
    "disable",
    "enabled",
]

#: LRU bound on retained program records: a record is a few hundred bytes
#: of host metadata (plus, until analyzed, the jitted fn reference that
#: already lives in the program cache), so the bound exists for hygiene,
#: not memory pressure.
_MAX_RECORDS = 256


def _to_avals(tree):
    """Replace every array-like leaf with a ``ShapeDtypeStruct`` so the
    record pins NO device buffers; non-array leaves (static config args)
    pass through for re-lowering."""
    import jax

    def leaf(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        return x

    return jax.tree_util.tree_map(leaf, tree)


def _scalar(value) -> Optional[float]:
    try:
        f = float(value)
    except (TypeError, ValueError):
        return None
    return f


class ProgramRecord:
    """One distinct compiled program: identity, call accounting, and the
    XLA analysis once :meth:`ProgramInventory.analyze_pending` ran."""

    __slots__ = (
        "tag", "signature", "first_ts", "last_ts", "calls", "build_s",
        "first_call_s", "total_call_s", "analysis", "analysis_error",
        "_fn", "_args", "_kwargs",
    )

    def __init__(self, tag: str, signature: tuple, fn, args, kwargs,
                 call_s: float, build_s: Optional[float]):
        now = time.time()
        self.tag = tag
        self.signature = signature
        self.first_ts = now
        self.last_ts = now
        self.calls = 1
        self.build_s = build_s
        self.first_call_s = call_s
        self.total_call_s = call_s
        self.analysis: Optional[Dict[str, float]] = None
        self.analysis_error: Optional[str] = None
        self._fn = fn
        self._args = _to_avals(args)
        self._kwargs = _to_avals(kwargs) if kwargs else {}

    @property
    def status(self) -> str:
        if self.analysis is not None:
            return "analyzed"
        if self.analysis_error is not None:
            return "unavailable"
        return "pending"

    def row(self) -> Dict[str, Any]:
        """JSON-ready ``/programz`` row (docs/operator.md#programz)."""
        out: Dict[str, Any] = {
            "tag": self.tag,
            "signature": [list(s) for s in self.signature],
            "calls": self.calls,
            "first_ts": self.first_ts,
            "last_ts": self.last_ts,
            "first_call_s": self.first_call_s,
            "total_call_s": self.total_call_s,
            "status": self.status,
        }
        if self.build_s is not None:
            out["build_s"] = self.build_s
        if self.analysis:
            out.update(self.analysis)
        if self.analysis_error:
            out["analysis_error"] = self.analysis_error
        return out

    def _analyze(self, deep: bool) -> None:
        """Lower from the stored avals and pull XLA's cost model; with
        ``deep`` also compile for ``memory_analysis()`` (one extra backend
        compile per program — never on the sampler path)."""
        fn, args, kwargs = self._fn, self._args, self._kwargs
        if fn is None:
            self.analysis_error = "program reference already released"
            return
        out: Dict[str, float] = {}
        try:
            lowered = fn.lower(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 - analysis is best-effort
            self.analysis_error = f"lower failed: {type(e).__name__}: {e}"
            return
        cost = None
        try:
            cost = lowered.cost_analysis()
        except Exception:  # noqa: BLE001 - backend without cost analysis
            cost = None
        compiled = None
        if deep or cost is None:
            try:
                compiled = lowered.compile()
            except Exception as e:  # noqa: BLE001
                if cost is None:
                    self.analysis_error = (
                        f"compile failed: {type(e).__name__}: {e}"
                    )
                    return
        if cost is None and compiled is not None:
            try:
                cost = compiled.cost_analysis()
            except Exception:  # noqa: BLE001
                cost = None
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if cost:
            flops = _scalar(cost.get("flops"))
            if flops is not None and flops >= 0:
                out["flops"] = flops
            nbytes = _scalar(cost.get("bytes accessed"))
            if nbytes is not None and nbytes >= 0:
                out["bytes_accessed"] = nbytes
        if compiled is not None:
            mem = None
            try:
                mem = compiled.memory_analysis()
            except Exception:  # noqa: BLE001 - cpu backends return None
                mem = None
            if mem is not None:
                hbm = 0.0
                for attr, key in (
                    ("argument_size_in_bytes", "argument_bytes"),
                    ("output_size_in_bytes", "output_bytes"),
                    ("temp_size_in_bytes", "temp_bytes"),
                    ("generated_code_size_in_bytes", "generated_code_bytes"),
                ):
                    v = _scalar(getattr(mem, attr, None))
                    if v is not None:
                        out[key] = v
                        if key != "generated_code_bytes":
                            hbm += v
                if hbm > 0:
                    out["peak_hbm_bytes"] = hbm
        if out:
            self.analysis = out
            # the record is now self-contained: release the program and
            # aval references so the inventory never extends a model's
            # lifetime past its analysis
            self._fn = None
            self._args = None
            self._kwargs = None
        else:
            self.analysis_error = (
                "backend reported no cost analysis for this program"
            )


class ProgramInventory:
    """Process-wide program inventory; installed as the models/base
    program sink by :func:`enable` and scraped by ``/programz``."""

    def __init__(self, max_records: int = _MAX_RECORDS):
        self._lock = threading.Lock()
        self._records: "OrderedDict[Tuple[str, tuple], ProgramRecord]" = (
            OrderedDict()
        )
        self._max = int(max_records)
        self._tls = threading.local()
        self._calls = 0

    # -- capture (the models/base sink) -----------------------------------

    def record_call(self, tag: str, sig: tuple, fn, args, kwargs,
                    call_s: float, build_s: Optional[float]) -> None:
        key = (tag, sig)
        with self._lock:
            self._calls += 1
            rec = self._records.get(key)
            if rec is not None:
                rec.calls += 1
                rec.last_ts = time.time()
                rec.total_call_s += call_s
                self._records.move_to_end(key)
                self._tls.last = rec
                return
        # miss: building the aval tree allocates, so do it off-lock
        rec = ProgramRecord(tag, sig, fn, args, kwargs, call_s, build_s)
        with self._lock:
            existing = self._records.get(key)
            if existing is not None:
                existing.calls += 1
                existing.total_call_s += call_s
                rec = existing
            else:
                self._records[key] = rec
                while len(self._records) > self._max:
                    self._records.popitem(last=False)
            self._tls.last = rec
        self._publish_gauges()

    def last_program_record(self) -> Optional[ProgramRecord]:
        """The most recent program call recorded on THIS thread — how the
        round ledger joins a chunk's ``round_end`` rows to the chunk
        program it just dispatched."""
        return getattr(self._tls, "last", None)

    # -- analysis ---------------------------------------------------------

    def analyze_pending(self, limit: Optional[int] = None,
                        deep: bool = False) -> int:
        """Run XLA analysis on up to ``limit`` pending records; returns
        the number analyzed (or marked unavailable).  ``deep=False``
        performs zero backend compiles (see module docstring)."""
        with self._lock:
            pending = [
                r for r in self._records.values() if r.status == "pending"
            ]
        if limit is not None:
            pending = pending[: max(int(limit), 0)]
        done = 0
        for rec in pending:
            rec._analyze(deep)
            done += 1
        if done:
            self._publish_gauges()
        return done

    # -- consumption ------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def records(self) -> List[ProgramRecord]:
        with self._lock:
            return list(self._records.values())

    def rows(self, top: Optional[int] = None) -> List[Dict[str, Any]]:
        """``/programz`` rows, heaviest first (XLA flops, then calls);
        pure rendering of stored state — never traces or compiles."""
        rows = [r.row() for r in self.records()]
        rows.sort(
            key=lambda r: (-float(r.get("flops", 0.0)), -r["calls"], r["tag"])
        )
        if top is not None:
            rows = rows[: max(int(top), 0)]
        return rows

    def summary(self) -> Dict[str, Any]:
        recs = self.records()
        with self._lock:
            calls = self._calls
        return {
            "programs": len(recs),
            "calls": calls,
            "analyzed": sum(1 for r in recs if r.status == "analyzed"),
            "pending": sum(1 for r in recs if r.status == "pending"),
            "unavailable": sum(1 for r in recs if r.status == "unavailable"),
        }

    def emit_rows(self, top: Optional[int] = None,
                  path: Optional[str] = None) -> int:
        """Emit one ``program`` telemetry event per ``/programz`` row into
        the active JSONL sink — how an inventory snapshot lands next to
        ``fleet_slo`` rows so ``tools/telemetry_report.py`` can render its
        per-program table offline.  Returns the number emitted."""
        from spark_ensemble_tpu.telemetry.events import emit_event

        rows = self.rows(top=top)
        for row in rows:
            emit_event("program", path=path, **row)
        return len(rows)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._calls = 0
            self._tls = threading.local()

    def _publish_gauges(self) -> None:
        from spark_ensemble_tpu.telemetry.events import global_metrics

        s = self.summary()
        reg = global_metrics()
        reg.gauge("programz/programs").set(s["programs"])
        reg.gauge("programz/analyzed").set(s["analyzed"])
        reg.gauge("programz/pending").set(s["pending"])


_GLOBAL_INVENTORY = ProgramInventory()


def global_inventory() -> ProgramInventory:
    """The process-global inventory (what /programz serves)."""
    return _GLOBAL_INVENTORY


def enable() -> ProgramInventory:
    """Install the global inventory as the models/base program sink.
    Programs fetched BEFORE enabling stay invisible — enable the operator
    plane before fitting/serving (telemetry/exporter.py does this)."""
    from spark_ensemble_tpu.models.base import set_program_sink

    set_program_sink(_GLOBAL_INVENTORY.record_call)
    return _GLOBAL_INVENTORY


def disable() -> None:
    from spark_ensemble_tpu.models.base import set_program_sink

    set_program_sink(None)


def enabled() -> bool:
    from spark_ensemble_tpu.models.base import _PROGRAM_SINK

    return _PROGRAM_SINK[0] is not None


# ---------------------------------------------------------------------------
# round-ledger join (telemetry/events.py round_chunk)
# ---------------------------------------------------------------------------


def xla_cost_fields(round_cost: Optional[Dict[str, Any]],
                    per_round_s: float,
                    rounds_per_dispatch: int) -> Dict[str, Any]:
    """The XLA leg of the three-way ``round_end`` cost line, joined from
    this thread's last recorded program call (the chunk program the round
    driver just fenced).  Empty until the record is analyzed — the
    sampler analyzes in the background, so later chunks of the same fit
    pick the fields up.  Never raises; never lowers or compiles."""
    rec = _GLOBAL_INVENTORY.last_program_record()
    if rec is None or not rec.analysis:
        return {}
    rounds = max(int(rounds_per_dispatch), 1)
    fields: Dict[str, Any] = {"program_tag": rec.tag}
    flops = rec.analysis.get("flops")
    nbytes = rec.analysis.get("bytes_accessed")
    if flops:
        per_round_flops = flops / rounds
        fields["xla_flops"] = per_round_flops
        peak = (round_cost or {}).get("peak_flops")
        if peak and per_round_s > 0:
            fields["mfu_xla"] = per_round_flops / (per_round_s * float(peak))
        if peak:
            modeled = per_round_flops / float(peak)
            bw = (round_cost or {}).get("hbm_bw_est")
            if bw and nbytes:
                modeled = max(modeled, (nbytes / rounds) / float(bw))
            fields["xla_modeled_s"] = modeled
        flops_est = (round_cost or {}).get("flops_est")
        if flops_est:
            fields["xla_vs_analytic_flops_ratio"] = (
                per_round_flops / float(flops_est)
            )
    if nbytes:
        fields["xla_bytes_accessed"] = nbytes / rounds
    peak_hbm = rec.analysis.get("peak_hbm_bytes")
    if peak_hbm:
        fields["xla_peak_hbm_bytes"] = peak_hbm
    return fields


# ---------------------------------------------------------------------------
# background HBM-watermark sampler
# ---------------------------------------------------------------------------


class HbmSampler:
    """Daemon thread sampling per-device allocator stats into
    ``global_metrics()`` gauges (``hbm/<dev>/bytes_in_use`` and the
    process-lifetime ``hbm/<dev>/watermark_bytes``) and draining pending
    program analysis (shallow — zero backend compiles) off the hot path.
    CPU backends without allocator stats still get the analysis drain;
    the gauges simply stay absent, matching ``device_memory_stats()``."""

    def __init__(self, interval_s: float = 1.0, analyze: bool = True,
                 analyze_per_tick: int = 1,
                 inventory: Optional[ProgramInventory] = None):
        self.interval_s = float(interval_s)
        self._analyze = bool(analyze)
        self._per_tick = int(analyze_per_tick)
        self._inventory = inventory or _GLOBAL_INVENTORY
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watermarks: Dict[str, float] = {}
        self.ticks = 0

    def sample_once(self) -> Dict[str, Dict[str, int]]:
        from spark_ensemble_tpu.telemetry.events import (
            device_memory_stats,
            global_metrics,
        )

        reg = global_metrics()
        stats = device_memory_stats()
        for dev, s in stats.items():
            in_use = float(s.get("bytes_in_use", 0))
            reg.gauge(f"hbm/{dev}/bytes_in_use").set(in_use)
            mark = max(
                self._watermarks.get(dev, 0.0),
                in_use,
                float(s.get("peak_bytes_in_use", 0)),
            )
            self._watermarks[dev] = mark
            reg.gauge(f"hbm/{dev}/watermark_bytes").set(mark)
        if self._analyze:
            self._inventory.analyze_pending(limit=self._per_tick, deep=False)
        self.ticks += 1
        return stats

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - sampling must never kill
                pass  # the thread; next tick retries

    def start(self) -> "HbmSampler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="se-tpu-hbm-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
