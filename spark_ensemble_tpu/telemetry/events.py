"""Structured per-fit event stream: round timings, phases, compiles, memory.

Every ``fit`` can emit a stream of structured events — ``fit_start``,
``round_start``/``round_end`` pairs (loss, step size, learner index,
duration), an optional ``phase_probe`` (fine-grained per-phase device
costs), and a closing ``fit_end`` (per-phase wall breakdown, jit compile
count/seconds, device memory stats).  Three sinks, checked in order:

1. ``telemetry_path`` estimator param — JSONL appended at fit end,
2. ``SE_TPU_TELEMETRY`` environment variable — same, path from the env,
3. an active ``record_fits()`` context — events kept in memory.

When none is active the per-fit handle is a shared no-op singleton: no
events are allocated and fits stay on the exact same cached XLA programs
(the telemetry params are not part of any program cache key), which is what
keeps the measured enable-overhead under the budget ``bench.py`` enforces.

Timing honesty under async dispatch: round durations come from fencing the
scan-chunked round program (``block_on_arrays``, the same walk
``instrumented_fit`` uses) and dividing the chunk wall time by the rounds
it fused — XLA runs ``scan_chunk`` rounds as ONE dispatch, so per-round
host timestamps inside the chunk do not exist.  The ``fit_end`` phase map
always sums to the measured fit wall time by construction: measured spans
plus a ``host_other`` remainder for un-spanned host work.

Compile observability rides ``jax.monitoring``: a process-global listener
counts ``backend_compile_duration`` events (cache hits emit none), and each
fit reports the delta across its window.  Attribution is process-wide —
concurrent fits (stacking ``parallelism>1``) each see compiles from the
shared window, which is the truthful answer on one process-wide cache.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from spark_ensemble_tpu.telemetry import flight as _flight
from spark_ensemble_tpu.telemetry.registry import MetricsRegistry
from spark_ensemble_tpu.telemetry.trace import (
    NULL_CONTEXT,
    NULL_SPAN,
    Span,
    TraceContext,
    Tracer,
    new_span_id,
    new_trace_id,
)
from spark_ensemble_tpu.utils.instrumentation import block_on_arrays

logger = logging.getLogger("spark_ensemble_tpu")

__all__ = [
    "FitTelemetry",
    "TelemetryRecorder",
    "record_fits",
    "device_memory_stats",
    "global_metrics",
    "emit_event",
    "serving_stream_id",
]

TELEMETRY_ENV = "SE_TPU_TELEMETRY"
PHASES_ENV = "SE_TPU_TELEMETRY_PHASES"

# standalone (non-fit) event types emitted by the serving subsystem
# (docs/serving.md): export compaction, per-bucket AOT warmup, per-request
# service records.  docs/telemetry.md documents their fields.
SERVING_EVENT_TYPES = (
    "model_packed",
    "engine_warmup",
    "request_served",
    "model_evicted",
    # fleet tier (docs/fleet.md): per-request routing records, breaker
    # state transitions, hedge firings, staged shedding, periodic SLO rows
    "fleet_request",
    "replica_state",
    "hedge_fired",
    "request_shed",
    "fleet_slo",
    # causal tracing plane (docs/tracing.md): span records emitted by the
    # serving fleet / engine ride the same standalone-event chokepoint
    "span",
    # operator plane (docs/operator.md): the online watchdog's SLO breach
    # raise/clear records (telemetry/watchdog.py) and /programz inventory
    # rows snapshotted into the stream (ProgramInventory.emit_rows)
    "slo_alert",
    "program",
    # model-quality plane (docs/quality.md): per-window drift scores from
    # the on-device sketches, sampled shadow-candidate evals, and quality
    # alert raise/clear transitions (telemetry/quality.py)
    "drift_window",
    "shadow_eval",
    "quality_alert",
)

# ---------------------------------------------------------------------------
# process-global state: metrics registry, compile listener, recorder slot
# ---------------------------------------------------------------------------

_GLOBAL = MetricsRegistry()


def global_metrics() -> MetricsRegistry:
    """The process-global registry (compile counters live here)."""
    return _GLOBAL


_COMPILE_LOCK = threading.Lock()
_COMPILE_COUNT = 0
_COMPILE_SECS = 0.0
_CACHE_REQUESTS = 0
_CACHE_HITS = 0
_LISTENER_STATE = {"registered": False}

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# NOTE: _BACKEND_COMPILE_EVENT wraps compile_or_get_cached in current jax,
# so it fires even when the persistent compilation cache serves the
# executable from disk.  Real compile work is requests - hits below.
_CACHE_REQUEST_EVENT = "/jax/compilation_cache/compile_requests_use_cache"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    global _COMPILE_COUNT, _COMPILE_SECS
    if event == _BACKEND_COMPILE_EVENT:
        with _COMPILE_LOCK:
            _COMPILE_COUNT += 1
            _COMPILE_SECS += float(duration)
        _GLOBAL.counter("jit/compile_count").inc()
        _GLOBAL.histogram("jit/compile_seconds").record(float(duration))


def _on_event(event: str, **_kw) -> None:
    global _CACHE_REQUESTS, _CACHE_HITS
    if event == _CACHE_REQUEST_EVENT:
        with _COMPILE_LOCK:
            _CACHE_REQUESTS += 1
        _GLOBAL.counter("jit/persistent_cache_requests").inc()
    elif event == _CACHE_HIT_EVENT:
        with _COMPILE_LOCK:
            _CACHE_HITS += 1
        _GLOBAL.counter("jit/persistent_cache_hits").inc()


def _ensure_compile_listener() -> None:
    # lazy: jax.monitoring listeners are append-only (no deregistration),
    # so nothing registers until the first telemetry-enabled fit
    with _COMPILE_LOCK:
        if _LISTENER_STATE["registered"]:
            return
        _LISTENER_STATE["registered"] = True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_event_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:  # pragma: no cover - jax without monitoring
        pass


def compile_snapshot() -> tuple:
    """(count, seconds) of backend compiles observed so far this process."""
    with _COMPILE_LOCK:
        return _COMPILE_COUNT, _COMPILE_SECS


def persistent_cache_snapshot() -> tuple:
    """(requests, hits) of persistent-compilation-cache lookups so far;
    ``requests - hits`` is the number of REAL backend compiles when the
    cache is active (the compile-duration event above cannot tell a disk
    hit from a compile)."""
    with _COMPILE_LOCK:
        return _CACHE_REQUESTS, _CACHE_HITS


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """Per-device allocator stats from ``device.memory_stats()``; backends
    without an allocator report (CPU) simply drop out of the map."""
    import jax

    out: Dict[str, Dict[str, int]] = {}
    for i, dev in enumerate(jax.local_devices()):
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        keep = {}
        for key in ("bytes_in_use", "peak_bytes_in_use", "largest_alloc_size",
                    "bytes_limit", "num_allocs"):
            if key in stats:
                keep[key] = int(stats[key])
        out[f"{dev.platform}:{i}"] = keep or {
            k: int(v) for k, v in stats.items() if isinstance(v, (int, float))
        }
    return out


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------


class TelemetryRecorder:
    """Thread-safe in-memory event sink (stacking fits members from a
    thread pool, and each member fit emits into the same recorder)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []

    def record(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self._events.append(event)

    def extend(self, events: List[Dict[str, Any]]) -> None:
        with self._lock:
            self._events.extend(events)

    @property
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def fits(self) -> Dict[str, List[Dict[str, Any]]]:
        """Events grouped by fit id, in emission order."""
        out: Dict[str, List[Dict[str, Any]]] = {}
        for ev in self.events:
            out.setdefault(ev.get("fit_id", "?"), []).append(ev)
        return out


_RECORDER_LOCK = threading.Lock()
_RECORDER: Optional[TelemetryRecorder] = None


@contextlib.contextmanager
def record_fits() -> Iterator[TelemetryRecorder]:
    """Capture every fit's event stream in memory for the duration of the
    context — the programmatic alternative to the JSONL sinks::

        with telemetry.record_fits() as rec:
            model = GBMClassifier(...).fit(X, y)
        rounds = [e for e in rec.events if e["event"] == "round_end"]

    A module-level slot rather than a contextvar on purpose: stacking
    fits members from worker threads, and those threads must see the
    recorder the caller installed."""
    global _RECORDER
    rec = TelemetryRecorder()
    with _RECORDER_LOCK:
        prev, _RECORDER = _RECORDER, rec
    try:
        yield rec
    finally:
        with _RECORDER_LOCK:
            _RECORDER = prev


def _active_recorder() -> Optional[TelemetryRecorder]:
    with _RECORDER_LOCK:
        return _RECORDER


_JSONL_LOCK = threading.Lock()


def _append_jsonl(path: str, events: List[Dict[str, Any]],
                  fsync: bool = False) -> None:
    lines = [json.dumps(ev, sort_keys=True, default=float) for ev in events]
    with _JSONL_LOCK:
        with open(path, "a") as f:
            for line in lines:
                f.write(line + "\n")
            if fsync:
                # crash paths (host_preempt, abort) must not lose the
                # terminal rows to page-cache buffering: the victim is
                # about to re-raise and may be SIGKILLed mid-teardown
                f.flush()
                os.fsync(f.fileno())


# ---------------------------------------------------------------------------
# standalone events (serving subsystem)
# ---------------------------------------------------------------------------

_STREAM_SEQ = itertools.count()


def serving_stream_id(label: str = "serving") -> str:
    """A fresh stream id in the same ``family:pid:seq`` shape as fit ids, so
    ``tools/telemetry_report.py`` groups a serving session's events the way
    it groups a fit's."""
    return f"{label}:{os.getpid()}:{next(_STREAM_SEQ)}"


def telemetry_sink_active(path: Optional[str] = None) -> bool:
    """Whether :func:`emit_event` with this ``path`` would reach any sink
    — the cheap pre-check hot paths use to skip building span objects
    entirely when nobody is listening (docs/tracing.md)."""
    return bool(
        path or os.environ.get(TELEMETRY_ENV) or _active_recorder() is not None
    )


def emit_event(event: str, path: Optional[str] = None, **fields) -> None:
    """Emit one standalone structured event (``model_packed``,
    ``engine_warmup``, ``request_served``, ...) through the same sinks as
    fit telemetry: explicit ``path`` > ``SE_TPU_TELEMETRY`` env > the active
    ``record_fits()`` recorder.  JSONL writes are immediate — serving
    processes are long-running, so there is no fit-end flush to ride.
    A no-op (nothing allocated past the sink check) when no sink is active.
    """
    path = path or os.environ.get(TELEMETRY_ENV) or None
    recorder = _active_recorder()
    if not path and recorder is None:
        return
    ev: Dict[str, Any] = {"event": event, "ts": time.time()}
    ev.update(fields)
    ev.setdefault("fit_id", "serving")
    _flight.recorder().record(ev)
    if recorder is not None:
        recorder.record(ev)
    if path:
        _append_jsonl(path, [ev])


# ---------------------------------------------------------------------------
# per-fit handle
# ---------------------------------------------------------------------------

_FIT_SEQ = itertools.count()


class FitTelemetry:
    """Per-fit event emitter; ``FitTelemetry.start(...)`` returns a shared
    no-op singleton when no sink is active, so the disabled path costs one
    attribute check per call site and allocates nothing."""

    enabled = True

    def __init__(self, family: str, path: Optional[str],
                 recorder: Optional[TelemetryRecorder]):
        self.family = family
        self.fit_id = f"{family}:{os.getpid()}:{next(_FIT_SEQ)}"
        self._path = path
        self._recorder = recorder
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._phases: Dict[str, float] = {}
        self._rounds = 0
        self._host_blocked_s = 0.0
        self._finished = False
        self._t0 = time.perf_counter()
        self._last_mark = self._t0
        # causal tracing plane (telemetry/trace.py): every fit is one
        # trace; the root "fit" span's id is allocated up front so child
        # spans (round chunks, shard waits, checkpoint saves) can parent
        # to it before the root itself is emitted at finish()/abort()
        self.trace_id = new_trace_id()
        self._root_span_id = new_span_id()
        self._ts0 = time.time()
        self._tracer = Tracer(self._emit, trace_id=self.trace_id)
        _ensure_compile_listener()
        self._compile0 = compile_snapshot()
        # incremental JSONL flush cursor (flush-on-crash support: the
        # host_preempt path flushes mid-fit; finish()/abort() flush the
        # remainder) and the measured-vs-estimated ledger baselines
        self._flushed = 0
        self._ledger_compile = self._compile0
        self._ledger_mem: Dict[str, int] = {}

    # -- construction -----------------------------------------------------

    @classmethod
    def start(cls, estimator=None, family: str = "", n: Optional[int] = None,
              d: Optional[int] = None, telemetry_path: Optional[str] = None,
              **meta) -> "FitTelemetry":
        """Resolve the sink (param > env > in-memory recorder) and open the
        stream; returns the disabled singleton when nothing is listening."""
        path = telemetry_path or getattr(estimator, "telemetry_path", None)
        path = path or os.environ.get(TELEMETRY_ENV) or None
        recorder = _active_recorder()
        if not path and recorder is None:
            return _DISABLED
        if not family and estimator is not None:
            family = type(estimator).__name__
        telem = cls(family, path, recorder)
        start_ev = {"event": "fit_start", "family": family}
        if n is not None:
            start_ev["n"] = int(n)
        if d is not None:
            start_ev["d"] = int(d)
        start_ev.update(meta)
        telem._emit(start_ev)
        _stack().append(telem)
        return telem

    # -- emission ---------------------------------------------------------

    def emit(self, event: str, **fields) -> None:
        """Append an ad-hoc structured event (``retry``, ``guard_nonfinite``,
        ``resume_from_checkpoint``, ...) to the stream — the hook the
        robustness runtime reports through (docs/robustness.md)."""
        ev: Dict[str, Any] = {"event": event}
        ev.update(fields)
        self._emit(ev)

    def _emit(self, event: Dict[str, Any]) -> None:
        event = dict(event)
        event.setdefault("fit_id", self.fit_id)
        event.setdefault("ts", time.time())
        with self._lock:
            self._events.append(event)
        _flight.recorder().record(event)
        if self._recorder is not None:
            self._recorder.record(event)

    def phase_mark(self, name: str) -> None:
        """Charge the host time since the previous mark (or fit start) to
        phase ``name`` — the span bookkeeping that makes the ``fit_end``
        phase map sum to wall time by construction."""
        now = time.perf_counter()
        with self._lock:
            self._phases[name] = self._phases.get(name, 0.0) + (
                now - self._last_mark
            )
            self._last_mark = now

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        """Measure a block into phase ``name`` without disturbing the
        running mark (for out-of-line work like checkpoint waits)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._phases[name] = self._phases.get(name, 0.0) + dt

    def host_blocked(self, seconds: float) -> None:
        """Charge ``seconds`` of driver time spent blocked on a device
        read between dispatches (the serialization the lookahead pipeline
        exists to hide — docs/pipeline.md); accumulated per fit and
        reported as ``host_blocked_us`` on ``fit_end``."""
        with self._lock:
            self._host_blocked_s += float(seconds)

    def blocking_read(self, fence: Any) -> None:
        """Fence on ``fence`` (any pytree of device arrays) and charge the
        wait to the host-blocked accumulator — the one call the round
        drivers make before touching a chunk's outputs."""
        t0 = time.perf_counter()
        block_on_arrays(fence)
        self.host_blocked(time.perf_counter() - t0)

    def flush(self, fsync: bool = False) -> int:
        """Append events emitted since the last flush to the JSONL sink
        (no-op without one); returns the row count written.  Crash paths
        pass ``fsync=True`` so the stream survives the process dying
        right after — the victim's half of a preemption would otherwise
        sit in the page cache when SIGKILL lands (docs/tracing.md)."""
        if not self._path:
            return 0
        with self._lock:
            pending = self._events[self._flushed:]
            self._flushed = len(self._events)
        if pending:
            _append_jsonl(self._path, pending, fsync=fsync)
        return len(pending)

    # -- causal tracing (telemetry/trace.py; docs/tracing.md) -------------

    def trace_context(self) -> TraceContext:
        """Propagation handle for a child span begun on another thread
        (checkpoint writer, prefetch reconstruction): parents to the
        fit's root span."""
        return TraceContext(self.trace_id, self._root_span_id)

    def begin_span(self, name: str, parent=None, thread=None,
                   annotate: bool = True, **attrs) -> Span:
        """Start a span on this fit's trace (defaults to a child of the
        root "fit" span).  The caller must guarantee ``end()`` on every
        path — ``with`` or try/finally (graftlint ``unclosed-span``)."""
        if parent is None:
            parent = self.trace_context()
        return self._tracer.begin_span(
            name, parent=parent, thread=thread, annotate=annotate, **attrs
        )

    def emit_span(self, name: str, ts: float, dur_s: float, parent=None,
                  thread=None, **fields) -> str:
        """Emit an already-measured span (work done on a thread that must
        stay telemetry-free, e.g. the shard-prefetch worker); returns the
        span id for further parenting."""
        if parent is None:
            parent = self.trace_context()
        return self._tracer.emit_span(
            name, ts, dur_s, parent=parent, thread=thread, **fields
        )

    def _emit_root_span(self, wall: float, **attrs) -> None:
        rec: Dict[str, Any] = {
            "event": "span",
            "name": "fit",
            "trace_id": self.trace_id,
            "span_id": self._root_span_id,
            "parent_id": "",
            "ts": self._ts0,
            "dur_s": wall,
            "pid": os.getpid(),
            "family": self.family,
        }
        rec.update(attrs)
        self._emit(rec)

    def round_chunk(self, start_round: int, count: int, t0: float,
                    fence: Any = (), losses: Any = None, step_sizes: Any = None,
                    learner_index: Optional[int] = None,
                    phase: str = "rounds",
                    divisor: Optional[int] = None,
                    round_cost: Optional[Dict[str, Any]] = None) -> float:
        """Record ``count`` rounds dispatched as one fused program: fence on
        the chunk outputs, then emit a ``round_start``/``round_end`` pair per
        round at chunk_duration/count each (see module docstring: per-round
        host timestamps inside a scan chunk do not exist).  ``divisor``
        overrides the per-round denominator when the chunk COMPUTED more
        rounds than it kept (boosting aborts discard the tail).

        ``round_cost`` (ops/tree.py ``round_cost_est``) attaches the static
        per-round cost model to every round_end — ``hist_tier``,
        ``pack_bits``, ``hbm_bytes_est`` — and, combined with the measured
        per-round duration, a per-round ``mfu_est`` (flops_est /
        (duration * peak_flops)), so MFU is observable per fit instead of
        only in one-off captures.

        Measured-vs-estimated ledger (docs/tracing.md#pod-scope): each
        chunk also records what the devices actually did against what
        the cost model predicted — the compile-count delta and
        per-device ``bytes_in_use`` delta since the previous chunk land
        on the chunk's first ``round_end`` (``chunk_compiles`` /
        ``chunk_compile_s`` / ``memory_delta``), and when the cost model
        supplies ``hbm_bw_est`` the roofline time ``modeled_s =
        max(flops/peak, hbm_bytes/bw)`` is compared against the measured
        per-round duration as ``cost_model_error_pct``."""
        if fence is not None and fence != ():
            block_on_arrays(fence)
        now = time.perf_counter()
        duration = now - t0
        per_round = duration / max(divisor if divisor else count, 1)
        loss_arr = None if losses is None else np.asarray(losses).reshape(-1)
        step_arr = None
        if step_sizes is not None:
            step_arr = np.asarray(step_sizes, dtype=np.float64)
            step_arr = step_arr.reshape(step_arr.shape[0], -1).mean(axis=1)
        mem = device_memory_stats()
        c1, s1 = compile_snapshot()
        chunk_compiles = c1 - self._ledger_compile[0]
        chunk_compile_s = s1 - self._ledger_compile[1]
        self._ledger_compile = (c1, s1)
        mem_delta: Dict[str, int] = {}
        for dev, stats in mem.items():
            cur = int(stats.get("bytes_in_use", 0))
            prev = self._ledger_mem.get(dev)
            if prev is not None and cur != prev:
                mem_delta[dev] = cur - prev
            self._ledger_mem[dev] = cur
        cost_fields: Dict[str, Any] = {}
        if round_cost:
            for key in ("hist_tier", "pack_bits", "hbm_bytes_est",
                        "sampled_rows", "sample_bucket", "hbm_saved_est"):
                if key in round_cost:
                    cost_fields[key] = round_cost[key]
            flops = round_cost.get("flops_est")
            peak = round_cost.get("peak_flops")
            if flops and peak and per_round > 0:
                cost_fields["mfu_est"] = float(flops) / (per_round * float(peak))
                modeled = float(flops) / float(peak)
                bw = round_cost.get("hbm_bw_est")
                if bw:
                    modeled = max(
                        modeled,
                        float(round_cost.get("hbm_bytes_est", 0.0)) / float(bw),
                    )
                cost_fields["modeled_s"] = modeled
                cost_fields["cost_model_error_pct"] = (
                    100.0 * abs(per_round - modeled) / per_round
                )
                # live copy for the online watchdog (docs/operator.md):
                # the sentinel's cost-model tripwire, readable mid-fit
                _GLOBAL.gauge("fit/cost_model_error_pct").set(
                    cost_fields["cost_model_error_pct"]
                )
        # three-way cost line (docs/operator.md): when the program
        # inventory is live, join the chunk program's XLA analysis —
        # measured wall (duration_s) vs analytic roofline (modeled_s)
        # vs XLA (xla_modeled_s), with MFU recomputed from XLA flops
        from spark_ensemble_tpu.telemetry import programz as _programz

        if _programz.enabled():
            cost_fields.update(
                _programz.xla_cost_fields(
                    round_cost, per_round,
                    divisor if divisor else count,
                )
            )
        for j in range(count):
            rnd = start_round + j
            li = rnd if learner_index is None else learner_index
            self._emit({"event": "round_start", "round": rnd,
                        "learner_index": li})
            end_ev: Dict[str, Any] = {
                "event": "round_end",
                "round": rnd,
                "learner_index": li,
                "duration_s": per_round,
                "phases": {"device_round": per_round},
            }
            end_ev.update(cost_fields)
            if j == 0:
                # the ledger deltas are chunk-granular (one dispatch);
                # charging them to every synthesized round would
                # overcount, so they ride the chunk's first round only
                end_ev["chunk_compiles"] = chunk_compiles
                end_ev["chunk_compile_s"] = chunk_compile_s
                if mem_delta:
                    end_ev["memory_delta"] = mem_delta
            if loss_arr is not None and j < loss_arr.shape[0]:
                end_ev["loss"] = float(loss_arr[j])
            if step_arr is not None and j < step_arr.shape[0]:
                end_ev["step_size"] = float(step_arr[j])
            if mem:
                end_ev["memory"] = mem
            self._emit(end_ev)
        with self._lock:
            self._rounds += count
            self._phases[phase] = self._phases.get(phase, 0.0) + duration
            self._last_mark = now
        return duration

    def member_fit(self, learner_index: int, duration_s: float,
                   loss: Optional[float] = None,
                   family: Optional[str] = None) -> None:
        """One sequentially-fitted member (stacking base learners): a
        round_start/round_end pair whose round index IS the learner index."""
        self._emit({"event": "round_start", "round": learner_index,
                    "learner_index": learner_index})
        ev: Dict[str, Any] = {
            "event": "round_end",
            "round": learner_index,
            "learner_index": learner_index,
            "duration_s": float(duration_s),
            "phases": {"member_fit": float(duration_s)},
        }
        if loss is not None:
            ev["loss"] = float(loss)
        if family:
            ev["member_family"] = family
        mem = device_memory_stats()
        if mem:
            ev["memory"] = mem
        self._emit(ev)
        with self._lock:
            self._rounds += 1
            self._phases["rounds"] = (
                self._phases.get("rounds", 0.0) + float(duration_s)
            )
            self._last_mark = time.perf_counter()

    def phase_probe(self, phases: Dict[str, float],
                    note: Optional[str] = None) -> None:
        """Fine-grained per-phase device costs from a one-round probe (see
        ``SE_TPU_TELEMETRY_PHASES``); informational — probe time is charged
        to the ``probe`` phase, not to the rounds."""
        ev: Dict[str, Any] = {
            "event": "phase_probe",
            "phases": {k: float(v) for k, v in phases.items()},
        }
        if note:
            ev["note"] = note
        self._emit(ev)

    def finish(self, model=None, **outcome) -> None:
        """Close the stream: charge the un-marked tail to ``finalize``,
        add the ``host_other`` remainder so phases sum EXACTLY to wall,
        emit ``fit_end``, flush the JSONL sink, and attach
        ``model.fit_history_``."""
        if self._finished:
            return
        self._finished = True
        self._unregister()
        self.phase_mark("finalize")
        wall = time.perf_counter() - self._t0
        with self._lock:
            phases = dict(self._phases)
        other = wall - sum(phases.values())
        if abs(other) > 1e-9:
            phases["host_other"] = other
        c1, s1 = compile_snapshot()
        ev: Dict[str, Any] = {
            "event": "fit_end",
            "family": self.family,
            "wall_s": wall,
            "rounds": self._rounds,
            "phases": phases,
            "compile_count": c1 - self._compile0[0],
            "compile_s": s1 - self._compile0[1],
            "host_blocked_us": self._host_blocked_s * 1e6,
        }
        if wall > 0:
            # live copy for the online watchdog (docs/operator.md): the
            # host-blocked share of the most recent finished fit
            _GLOBAL.gauge("fit/host_blocked_share").set(
                self._host_blocked_s / wall
            )
        mem = device_memory_stats()
        if mem:
            ev["memory"] = mem
        ev.update(outcome)
        self._emit_root_span(wall, rounds=self._rounds)
        self._emit(ev)
        self.flush()
        if model is not None:
            model.fit_history_ = self.history()

    def abort(self, error: BaseException, **outcome) -> None:
        """Terminal record for a fit that raised mid-round: emit
        ``fit_aborted`` (exception type + message, last completed round,
        phase breakdown) and flush the JSONL sink, so every stream ends
        with a terminal record even when ``fit()`` never returns."""
        if self._finished:
            return
        self._finished = True
        self._unregister()
        self.phase_mark("aborted")
        wall = time.perf_counter() - self._t0
        with self._lock:
            phases = dict(self._phases)
        ev: Dict[str, Any] = {
            "event": "fit_aborted",
            "family": self.family,
            "wall_s": wall,
            "rounds": self._rounds,
            "error_type": type(error).__name__,
            "error": str(error)[:500],
            "phases": phases,
        }
        ev.update(outcome)
        self._emit_root_span(wall, error=type(error).__name__)
        self._emit(ev)
        # fsync: abort runs on crash paths (preemption, guard abort)
        # where the process may be killed before the page cache drains
        self.flush(fsync=True)

    def _unregister(self) -> None:
        st = _stack()
        if self in st:
            st.remove(self)

    # -- consumption ------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def history(self) -> Dict[str, np.ndarray]:
        """Recorded rounds as aligned arrays — the ``fit_history_`` payload
        (round, learner_index, duration_s, loss, step_size; loss/step_size
        are NaN where a family does not produce them)."""
        ends = [e for e in self.events() if e["event"] == "round_end"]
        if not ends:
            return {
                "round": np.zeros(0, np.int64),
                "learner_index": np.zeros(0, np.int64),
                "duration_s": np.zeros(0, np.float64),
                "loss": np.zeros(0, np.float64),
                "step_size": np.zeros(0, np.float64),
            }
        return {
            "round": np.array([e["round"] for e in ends], np.int64),
            "learner_index": np.array(
                [e["learner_index"] for e in ends], np.int64
            ),
            "duration_s": np.array(
                [e.get("duration_s", np.nan) for e in ends], np.float64
            ),
            "loss": np.array(
                [e.get("loss", np.nan) for e in ends], np.float64
            ),
            "step_size": np.array(
                [e.get("step_size", np.nan) for e in ends], np.float64
            ),
        }

    @staticmethod
    def phases_enabled() -> bool:
        """Whether the opt-in fine-phase probe should run (it costs one
        extra single-round compile + execution per fit)."""
        return os.environ.get(PHASES_ENV, "") not in ("", "0")


class _DisabledFitTelemetry(FitTelemetry):
    """Shared no-op: every method returns immediately, no state mutates.

    Audit discipline: every ``FitTelemetry`` method with side effects or
    allocations must be overridden here — inherited implementations run
    against state this ``__init__`` never creates.  The inherited
    surface as of the tracing plane: ``start``/``phases_enabled``
    (class/static, sinkless), ``span`` (overridden), everything else
    overridden below.  ``round_chunk``/``host_blocked`` take ``*a, **kw``
    /positional so their kwarg drift since PR 1 (``divisor``,
    ``round_cost``, ``phase``) cannot break the disabled path."""

    enabled = False
    trace_id = ""

    def __init__(self):  # noqa: D401 - deliberately skip parent init
        self.family = ""
        self.fit_id = ""

    def emit(self, event, **fields):
        # override: the inherited emit() builds the event dict before
        # handing it to _emit — a dead allocation on every robustness
        # event when telemetry is off
        pass

    def _emit(self, event):
        pass

    def phase_mark(self, name):
        pass

    # -- tracing: hand out the shared null objects, allocate nothing ------

    def trace_context(self):
        return NULL_CONTEXT

    def begin_span(self, name, parent=None, thread=None, annotate=True,
                   **attrs):
        return NULL_SPAN

    def emit_span(self, name, ts, dur_s, parent=None, thread=None,
                  **fields):
        return ""

    def _emit_root_span(self, wall, **attrs):
        pass

    @contextlib.contextmanager
    def span(self, name):
        yield

    def round_chunk(self, *a, **kw):
        return 0.0

    def flush(self, fsync=False):
        return 0

    def host_blocked(self, seconds):
        pass

    def blocking_read(self, fence):
        pass

    def member_fit(self, *a, **kw):
        pass

    def phase_probe(self, *a, **kw):
        pass

    def finish(self, model=None, **outcome):
        if model is not None and not hasattr(model, "fit_history_"):
            # the attribute is part of the fitted-model contract whether or
            # not telemetry ran; empty arrays keep downstream code uniform
            model.fit_history_ = self.history()

    def abort(self, error, **outcome):
        pass

    def events(self):
        return []

    def history(self):
        return {
            "round": np.zeros(0, np.int64),
            "learner_index": np.zeros(0, np.int64),
            "duration_s": np.zeros(0, np.float64),
            "loss": np.zeros(0, np.float64),
            "step_size": np.zeros(0, np.float64),
        }


_DISABLED = _DisabledFitTelemetry()


# -- active-fit stack (terminal fit_aborted records) -----------------------
#
# Each live FitTelemetry registers on a thread-local stack at start() and
# unregisters at finish()/abort().  The instrumented_fit wrapper snapshots
# the depth before running a fit body and, when the body raises, aborts
# everything pushed above that snapshot — so nested fits (GBM's init model,
# stacking's threaded members) each get their own terminal record without
# the families having to thread try/except through every loop.

_FIT_TLS = threading.local()


def _stack() -> list:
    st = getattr(_FIT_TLS, "items", None)
    if st is None:
        st = _FIT_TLS.items = []
    return st


def active_fit_depth() -> int:
    """Depth of this thread's live-fit stack (see instrumented_fit)."""
    return len(_stack())


def abort_active_fits(depth: int, error: BaseException) -> None:
    """Abort (emit ``fit_aborted`` + flush) every telemetry registered on
    this thread above ``depth``, innermost first; then leave a flight-
    recorder dump — guard aborts and host losses are exactly the deaths
    the black box exists for (telemetry/flight.py)."""
    st = _stack()
    path = None
    aborted = False
    while len(st) > depth:
        telem = st.pop()
        aborted = True
        path = path or getattr(telem, "_path", None)
        try:
            telem.abort(error)
        except Exception:
            logger.exception("failed to flush fit_aborted record")
    if aborted:
        _flight.dump_flight(
            reason=f"fit_abort:{type(error).__name__}", error=error,
            telemetry_path=path,
        )
