"""Causal tracing plane: spans over the repo's concurrent machinery.

Every unit of work — a fit, a (possibly speculative) round chunk, a shard
load / prefetch wait, a checkpoint save, a fleet request with its hedges
and replays, an engine warmup tier — becomes a :class:`Span` with a
stable ``trace_id`` / ``span_id`` / ``parent_id``.  Spans are just one
more telemetry event type (``"event": "span"``) emitted through the
existing ``FitTelemetry._emit`` / ``emit_event`` chokepoints, so the
JSONL stream, ``tools/telemetry_report.py`` and every other consumer
keep working unchanged; ``tools/trace_viewer.py`` turns the same stream
into a Chrome/Perfetto ``trace_event`` JSON with one track per
thread/replica and flow arrows for hedges, replays and invalidated
speculative chunks (docs/tracing.md).

Propagation rules (the part a flat event stream cannot express):

- Same thread, same subsystem: pass the parent :class:`Span` to
  ``begin_span(..., parent=...)``.
- Across a thread or process boundary: capture ``span.context()`` (a
  :class:`TraceContext` — two strings, safe to close over or pickle) on
  the origin side and hand it to ``begin_span``/``emit_span`` on the
  far side.  The prefetcher worker → consumer and fit thread →
  checkpoint-writer seams both do this.
- Causality between *sibling* spans (a hedge twin racing its primary, a
  replay re-dispatch, a commit invalidating the speculative tail) is a
  flow: allocate ``new_flow_id()``, record it in the source span's
  ``flow_out`` list and the sink span's ``flow_in`` — the viewer renders
  the arrow.

Worker threads that must stay JAX- and telemetry-free (the shard
prefetcher's contract) don't begin spans at all: the consumer
reconstructs the worker's span after the fact from measured wall-clock
timings via :meth:`Tracer.emit_span`.

``SE_TPU_TRACE_ANNOTATIONS=1`` additionally wraps every span begun and
ended on one thread in a ``jax.profiler.TraceAnnotation`` so host spans
line up with device activity inside a jax profiler capture.  The import
is lazy and failures degrade to no annotation — a host with no jax can
still emit and view spans.

Overhead discipline: with no telemetry sink the disabled ``FitTelemetry``
singleton hands out :data:`NULL_SPAN` / :data:`NULL_TRACER`, whose
methods are empty — the traced hot paths pay one attribute lookup and
one no-op call (<1% of fit wall, bench-pinned ``trace_overhead_pct``).
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "Span",
    "TraceContext",
    "Tracer",
    "NULL_SPAN",
    "NULL_TRACER",
    "new_trace_id",
    "new_span_id",
    "new_flow_id",
    "trace_annotations_enabled",
    "TRACE_ANNOTATIONS_ENV",
]

#: opt-in gate for jax.profiler.TraceAnnotation wrapping (off by default:
#: annotations cost a host call per span even outside a profiler capture)
TRACE_ANNOTATIONS_ENV = "SE_TPU_TRACE_ANNOTATIONS"

# one process-wide monotone counter feeds every id family; ids embed the
# pid so streams appended by multiple processes (the serving smoke's
# export/serve/fleet trio) never collide
_seq = itertools.count(1)


def new_trace_id() -> str:
    """A fresh trace id (one per causally-connected timeline: a fit, a
    router's lifetime)."""
    return f"t{os.getpid():x}.{next(_seq):x}"


def new_span_id() -> str:
    """A fresh span id, unique within the process's stream."""
    return f"s{os.getpid():x}.{next(_seq):x}"


def new_flow_id() -> int:
    """A fresh flow id (Perfetto flow ``id`` — an int) tying a source
    span's ``flow_out`` to a sink span's ``flow_in``."""
    return (os.getpid() << 24) | (next(_seq) & 0xFFFFFF)


def trace_annotations_enabled() -> bool:
    """Whether spans also enter ``jax.profiler.TraceAnnotation`` scopes."""
    return os.environ.get(TRACE_ANNOTATIONS_ENV, "") not in ("", "0")


def _enter_annotation(name: str):
    if not trace_annotations_enabled():
        return None
    try:  # lazy: tracing must work on a jax-free host
        from jax.profiler import TraceAnnotation
    except Exception:  # pragma: no cover - depends on install
        return None
    ann = TraceAnnotation(name)
    try:
        ann.__enter__()
    except Exception:  # pragma: no cover - profiler backend quirk
        return None
    return ann


def _exit_annotation(ann) -> None:
    if ann is not None:
        try:
            ann.__exit__(None, None, None)
        except Exception:  # pragma: no cover - cross-thread end
            pass


class TraceContext:
    """The two strings that cross a thread/process boundary.

    Truthiness doubles as "is tracing live": the disabled path hands out
    :data:`NULL_CONTEXT`, which is falsy."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str = "", span_id: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id

    def __bool__(self) -> bool:
        return bool(self.trace_id)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


NULL_CONTEXT = TraceContext()


class Span:
    """One unit of work on the causal timeline.

    Use as a context manager, or call :meth:`end` in a ``finally`` —
    the graftlint ``unclosed-span`` rule enforces that one of the two is
    syntactically guaranteed.  ``end()`` is idempotent; an exceptional
    ``with``-exit records the exception type as an ``error`` attribute.
    """

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "thread", "attrs",
        "_emit", "_ts", "_t0", "_done", "_ann",
    )

    def __init__(
        self,
        emit: Callable[[Dict[str, Any]], None],
        name: str,
        trace_id: str,
        parent_id: str = "",
        thread: Optional[str] = None,
        annotate: bool = True,
        **attrs: Any,
    ):
        self._emit = emit
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.thread = thread
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self._ts = time.time()
        self._t0 = time.perf_counter()
        self._done = False
        # annotate=False for spans that END on a different thread (fleet
        # request spans resolve on a replica worker): TraceAnnotation is
        # same-thread scoped
        self._ann = _enter_annotation(name) if annotate else None

    def add(self, **attrs: Any) -> None:
        """Attach attributes to the span before (or at) ``end``."""
        self.attrs.update(attrs)

    def context(self) -> TraceContext:
        """The propagation handle for a child begun on another thread."""
        return TraceContext(self.trace_id, self.span_id)

    def end(self, **attrs: Any) -> None:
        if self._done:
            return
        self._done = True
        dur_s = time.perf_counter() - self._t0
        _exit_annotation(self._ann)
        self._ann = None
        if attrs:
            self.attrs.update(attrs)
        rec: Dict[str, Any] = {
            "event": "span",
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "ts": self._ts,
            "dur_s": dur_s,
            "pid": os.getpid(),
        }
        if self.thread:
            rec["thread"] = self.thread
        rec.update(self.attrs)
        self._emit(rec)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.end(error=exc_type.__name__)
        else:
            self.end()
        return False

    def __bool__(self) -> bool:
        return True


class _NullSpan:
    """The disabled path's span: every method is an empty no-op."""

    __slots__ = ()
    name = ""
    trace_id = ""
    span_id = ""
    parent_id = ""

    def add(self, **attrs: Any) -> None:
        pass

    def context(self) -> TraceContext:
        return NULL_CONTEXT

    def end(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def __bool__(self) -> bool:
        return False


NULL_SPAN = _NullSpan()


class Tracer:
    """Span factory bound to one emit chokepoint and one ``trace_id``.

    ``FitTelemetry`` owns one per fit (emitting through ``_emit`` so
    spans ride the fit's JSONL flush); ``FleetRouter`` owns one per
    router lifetime (emitting immediately through ``emit_event``)."""

    __slots__ = ("trace_id", "thread", "_emit")

    def __init__(
        self,
        emit: Callable[[Dict[str, Any]], None],
        trace_id: Optional[str] = None,
        thread: Optional[str] = None,
    ):
        self._emit = emit
        self.trace_id = trace_id or new_trace_id()
        self.thread = thread

    def begin_span(
        self,
        name: str,
        parent: Any = None,
        thread: Optional[str] = None,
        annotate: bool = True,
        **attrs: Any,
    ) -> Span:
        """Start a span.  ``parent`` is a :class:`Span`, a
        :class:`TraceContext`, or None (a root on this tracer's trace).
        The caller must guarantee ``end()`` on every path (``with`` or
        try/finally — graftlint ``unclosed-span``)."""
        trace_id = self.trace_id
        parent_id = ""
        if parent is not None:
            p_trace = getattr(parent, "trace_id", "")
            if p_trace:
                trace_id = p_trace
                parent_id = getattr(parent, "span_id", "")
        return Span(
            self._emit, name, trace_id, parent_id=parent_id,
            thread=thread or self.thread, annotate=annotate, **attrs,
        )

    def emit_span(
        self,
        name: str,
        ts: float,
        dur_s: float,
        parent: Any = None,
        thread: Optional[str] = None,
        flow_in: Optional[int] = None,
        flow_out: Optional[List[int]] = None,
        **attrs: Any,
    ) -> str:
        """Emit an already-finished span from measured timings — the
        reconstruction path for work done on a thread that must stay
        telemetry-free (the shard-prefetch worker).  Returns the new
        span's id so the caller can parent further spans under it."""
        trace_id = self.trace_id
        parent_id = ""
        if parent is not None:
            p_trace = getattr(parent, "trace_id", "")
            if p_trace:
                trace_id = p_trace
                parent_id = getattr(parent, "span_id", "")
        span_id = new_span_id()
        rec: Dict[str, Any] = {
            "event": "span",
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent_id,
            "ts": float(ts),
            "dur_s": float(dur_s),
            "pid": os.getpid(),
        }
        if thread or self.thread:
            rec["thread"] = thread or self.thread
        if flow_in is not None:
            rec["flow_in"] = flow_in
        if flow_out:
            rec["flow_out"] = list(flow_out)
        rec.update(attrs)
        self._emit(rec)
        return span_id


class _NullTracer:
    """Disabled tracer: hands out :data:`NULL_SPAN`, emits nothing."""

    __slots__ = ()
    trace_id = ""
    thread = None

    def begin_span(self, name, parent=None, thread=None, annotate=True,
                   **attrs) -> _NullSpan:
        return NULL_SPAN

    def emit_span(self, name, ts, dur_s, parent=None, thread=None,
                  flow_in=None, flow_out=None, **attrs) -> str:
        return ""

    def __bool__(self) -> bool:
        return False


NULL_TRACER = _NullTracer()
