"""Model-quality observability plane: on-device drift sketches, staged
attribution, and shadow scoring (docs/quality.md).

The systems planes (tracing, podview, operator) watch how the process
runs; this module watches what the fleet is actually *predicting*.
Three layers, all feeding the existing telemetry planes:

- **Feature-drift sketches** — the training-time quantile bins (the
  binned representation XGBoost's GPU path is built on, arXiv
  1806.11248) double as reference feature distributions for free:
  ``pack()`` ships the fitted thresholds + per-feature training bin
  occupancy inside the :class:`PackedModel`, the serving engine's
  bucketed predict programs ALSO emit a per-feature bin-count histogram
  of the served rows (fused into the same cached program — zero extra
  compiles, zero extra dispatches), and :class:`DriftMonitor`
  accumulates those exact integer histograms host-side into rolling
  windows scored as PSI/KL per feature.
- **Staged attribution** — :func:`staged_attribution` decomposes a
  request over the ensemble prefixes the engine already pre-warmed
  (``PackedModel.take(k)`` tiers): per-stage margins against the full
  model and a per-member-disagreement uncertainty score, flagged in
  ``FleetResponse`` for sampled requests.
- **Shadow scoring** — :class:`ShadowScorer` leases a candidate model
  from a ``ModelRegistry`` and scores a sampled fraction of live
  traffic: prediction divergence immediately, label-delayed accuracy
  deltas when ``record_label`` is called.

Everything lands in the existing planes: ``drift_window`` /
``shadow_eval`` / ``quality_alert`` events through the JSONL sinks,
``quality/*`` sources + gauges in ``global_metrics()`` (rendered by the
OpenMetrics exporter and the ``/qualityz`` endpoint), and the watchdog's
``quality_psi_max`` / ``shadow_divergence`` rules flip ``/healthz``
degraded with the existing hysteresis.

Device reads here are all of *already-materialized* host arrays (the
engine hands histograms over as numpy); the tier-2 ``quality`` graftlint
contract lints this file for unfenced blocking reads with the
telemetry-module exemption bypassed.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "psi",
    "kl_divergence",
    "histogram_distribution",
    "coarsen_counts",
    "prediction_divergence",
    "drift_reference_from_ctx",
    "DriftMonitor",
    "ShadowScorer",
    "staged_attribution",
]


# ---------------------------------------------------------------------------
# sketch math: pure host-side functions over integer bin counts
# ---------------------------------------------------------------------------


def histogram_distribution(
    counts: np.ndarray, smoothing: float = 1e-3
) -> np.ndarray:
    """Laplace-smoothed probability distribution(s) from bin counts.

    Accepts ``[B]`` or ``[d, B]`` integer counts; smoothing adds
    ``smoothing`` pseudo-count per bin so empty bins never produce
    infinities in the log-ratio scores below (the standard PSI
    stabilizer)."""
    c = np.asarray(counts, np.float32) + float(smoothing)
    return c / np.sum(c, axis=-1, keepdims=True)


def psi(
    reference: np.ndarray, observed: np.ndarray, smoothing: float = 1e-3
) -> np.ndarray:
    """Population Stability Index between bin-count histograms.

    ``sum((p - q) * ln(p / q))`` with ``q`` the reference distribution
    and ``p`` the observed one, both Laplace-smoothed.  Accepts ``[B]``
    counts (returns a scalar array) or ``[d, B]`` per-feature counts
    (returns ``[d]``).  Conventional reading: < 0.1 stable, 0.1-0.25
    moderate shift, > 0.25 major shift (the default alert threshold)."""
    q = histogram_distribution(reference, smoothing)
    p = histogram_distribution(observed, smoothing)
    return np.sum((p - q) * np.log(p / q), axis=-1)


def kl_divergence(
    reference: np.ndarray, observed: np.ndarray, smoothing: float = 1e-3
) -> np.ndarray:
    """``KL(observed || reference)`` between bin-count histograms, same
    shapes/smoothing conventions as :func:`psi`."""
    q = histogram_distribution(reference, smoothing)
    p = histogram_distribution(observed, smoothing)
    return np.sum(p * np.log(p / q), axis=-1)


def coarsen_counts(counts: np.ndarray, groups: int) -> np.ndarray:
    """Sum adjacent bins into ``groups`` near-equal groups along the last
    axis.  The training bins are QUANTILE bins (equiprobable by
    construction), so adjacent grouping preserves the equal-mass property
    — this is how the monitor gets standard-practice 10-20-cell PSI out
    of a 64-bin sketch.  Scoring at full resolution would drown in
    sampling noise: for B equiprobable cells the null expectation is
    ``E[PSI] ~ B/N_window + B/N_reference``, so 64 cells at a 512-row
    window sit at ~0.25 — the alert threshold — while 16 groups sit at a
    quarter of it (docs/quality.md#windows)."""
    c = np.asarray(counts)
    B = c.shape[-1]
    g = max(1, min(int(groups), B))
    edges = np.linspace(0, B, g + 1).astype(int)
    return np.stack(
        [c[..., edges[i]: edges[i + 1]].sum(axis=-1) for i in range(g)],
        axis=-1,
    )


def prediction_divergence(
    primary: np.ndarray, shadow: np.ndarray, classification: bool
) -> float:
    """Scalar divergence between two prediction vectors for the same
    rows: label disagreement rate for classifiers, mean-absolute
    difference normalized by the primary's mean magnitude for
    regressors."""
    a = np.asarray(primary, np.float32).ravel()
    b = np.asarray(shadow, np.float32).ravel()
    if classification:
        return float(np.mean(a != b))
    scale = float(np.mean(np.abs(a)))
    return float(np.mean(np.abs(a - b)) / (scale + 1e-12))


def drift_reference_from_ctx(ctx: Any) -> Optional[Dict[str, Any]]:
    """Training-time drift reference from a binned fit context.

    The tree-family ``make_fit_ctx`` already computed the quantile
    thresholds and the binned matrix ``Xb`` — the reference occupancy is
    one host-side bincount per feature, no extra device program (the fit
    compile budgets stay pinned).  Returns ``None`` for contexts without
    a binned representation (non-tree base learners)."""
    if not isinstance(ctx, dict):
        return None
    if "Xb" not in ctx or "thresholds" not in ctx:
        return None
    Xb = np.asarray(ctx["Xb"])
    thr = np.asarray(ctx["thresholds"], np.float32)
    if Xb.ndim != 2 or thr.ndim != 2 or Xb.shape[1] != thr.shape[0]:
        return None
    d, max_bins = thr.shape[0], thr.shape[1] + 1
    occ = np.zeros((d, max_bins), np.int32)
    for f in range(d):
        occ[f] = np.bincount(
            Xb[:, f].astype(np.int64), minlength=max_bins
        )[:max_bins]
    return {
        "thresholds": thr,
        "occupancy": occ,
        "rows": int(Xb.shape[0]),
    }


# ---------------------------------------------------------------------------
# DriftMonitor: rolling-window PSI/KL scoring of served-row histograms
# ---------------------------------------------------------------------------


class DriftMonitor:
    """Accumulate per-feature bin-count histograms of served rows into
    tumbling row-count windows and score each window against the
    training reference (PSI + KL per feature).

    The engine hands over EXACT integer histograms (one per compiled
    dispatch, already padding-corrected), so window scores are invariant
    to request batching order and to which shape bucket served each
    request — summing integer histograms commutes.  Each completed
    window emits a ``drift_window`` event, updates the
    ``quality/<stream>`` registry source + ``quality/psi_max`` gauge,
    and raise/clear transitions of ``psi_max`` across ``psi_threshold``
    emit ``quality_alert`` events.  The watchdog's ``quality_psi_max``
    rule adds /healthz hysteresis on top (docs/quality.md)."""

    def __init__(
        self,
        thresholds: np.ndarray,
        reference: np.ndarray,
        *,
        window_rows: int = 2048,
        smoothing: float = 1e-3,
        psi_threshold: float = 0.25,
        score_groups: int = 16,
        max_windows: int = 64,
        top_n: int = 5,
        stream: str = "quality",
        telemetry_path: Optional[str] = None,
        registry=None,
    ):
        from spark_ensemble_tpu.telemetry.events import global_metrics

        self.thresholds = np.asarray(thresholds, np.float32)
        self.reference = np.asarray(reference, np.int64)
        if (
            self.reference.ndim != 2
            or self.reference.shape[0] != self.thresholds.shape[0]
            or self.reference.shape[1] != self.thresholds.shape[1] + 1
        ):
            raise ValueError(
                f"reference occupancy shape {self.reference.shape} does not "
                f"match thresholds {self.thresholds.shape} "
                "(want [d, max_bins])"
            )
        self.window_rows = int(window_rows)
        self.smoothing = float(smoothing)
        self.psi_threshold = float(psi_threshold)
        self.score_groups = int(score_groups)
        self.top_n = int(top_n)
        # accumulation stays at full sketch resolution; scoring coarsens
        # both sides identically (see coarsen_counts for the noise math)
        self._reference_scored = coarsen_counts(
            self.reference, self.score_groups
        )
        self._stream = stream
        self._telemetry_path = telemetry_path
        self._registry = (
            registry if registry is not None else global_metrics()
        )
        d, B = self.reference.shape
        # padded rows are all-zero: they land in the bin holding 0.0 per
        # feature; the engine reports pad counts so they subtract out here
        self._zero_bin = np.array(
            [
                int(np.searchsorted(self.thresholds[f], 0.0, side="left"))
                for f in range(d)
            ],
            np.int64,
        )
        self._lock = threading.Lock()
        self._current = np.zeros((d, B), np.int64)
        self._current_rows = 0
        self._rows_total = 0
        self._windows = 0
        self._history: "collections.deque" = collections.deque(
            maxlen=int(max_windows)
        )
        self._last_psi: Optional[np.ndarray] = None
        self._last_kl: Optional[np.ndarray] = None
        self._alert_active = False
        self._closed = False
        self._source_name = f"quality/{stream}"
        self._registry.register_source(self._source_name, self.snapshot)

    # -- accumulation ------------------------------------------------------

    def observe(self, counts: np.ndarray, pad_rows: int = 0) -> None:
        """Fold one dispatch's histogram (``int[d, B]``) into the current
        window; ``pad_rows`` zero-rows the engine padded into the bucket
        are subtracted from each feature's zero bin, so the window holds
        the served rows exactly regardless of bucket size."""
        if self._closed:
            return
        c = np.asarray(counts, np.int64)
        if c.shape != self.reference.shape:
            raise ValueError(
                f"histogram shape {c.shape} does not match reference "
                f"{self.reference.shape}"
            )
        if pad_rows:
            c = c.copy()
            c[np.arange(c.shape[0]), self._zero_bin] -= int(pad_rows)
            np.maximum(c, 0, out=c)
        rows = int(c[0].sum())
        completed: List[Tuple[int, int, np.ndarray]] = []
        with self._lock:
            self._current += c
            self._current_rows += rows
            self._rows_total += rows
            while self._current_rows >= self.window_rows:
                self._windows += 1
                completed.append(
                    (self._windows, self._current_rows, self._current)
                )
                self._current = np.zeros_like(self.reference)
                self._current_rows = 0
        for idx, wrows, window in completed:
            self._score_window(idx, wrows, window)

    def _score_window(
        self, index: int, rows: int, window: np.ndarray
    ) -> None:
        from spark_ensemble_tpu.telemetry.events import emit_event

        scored = coarsen_counts(window, self.score_groups)
        psi_f = psi(self._reference_scored, scored, self.smoothing)
        kl_f = kl_divergence(self._reference_scored, scored, self.smoothing)
        psi_max = float(np.max(psi_f))
        kl_max = float(np.max(kl_f))
        order = np.argsort(psi_f)[::-1][: self.top_n]
        top = {f"f{int(f)}": float(psi_f[f]) for f in order}
        with self._lock:
            self._last_psi = psi_f
            self._last_kl = kl_f
            self._history.append(
                {"index": index, "rows": rows, "psi_max": psi_max,
                 "kl_max": kl_max}
            )
            was_active = self._alert_active
            self._alert_active = psi_max > self.psi_threshold
            transition = (
                "raised" if self._alert_active and not was_active
                else "cleared" if was_active and not self._alert_active
                else None
            )
        self._registry.gauge("quality/psi_max").set(psi_max)
        self._registry.gauge("quality/kl_max").set(kl_max)
        self._registry.histogram("quality/window_psi_max").record(psi_max)
        self._registry.counter("quality/windows").inc()
        emit_event(
            "drift_window",
            path=self._telemetry_path,
            fit_id=self._stream,
            window=index,
            rows=rows,
            psi_max=psi_max,
            kl_max=kl_max,
            psi_mean=float(np.mean(psi_f)),
            drifted_features=int(np.sum(psi_f > self.psi_threshold)),
            top=top,
            alert=self._alert_active,
        )
        if transition is not None:
            self._registry.counter("quality/alerts_total").inc()
            emit_event(
                "quality_alert",
                path=self._telemetry_path,
                fit_id=self._stream,
                state=transition,
                metric="psi_max",
                value=psi_max,
                threshold=self.psi_threshold,
                window=index,
            )

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``quality/<stream>`` source payload: last-window scores,
        totals, alert state, top drifting features.  Probed live by the
        watchdog (``psi_max``) and rendered by /metrics + /qualityz."""
        with self._lock:
            psi_f = self._last_psi
            out: Dict[str, Any] = {
                "kind": "drift",
                "rows_total": self._rows_total,
                "windows": self._windows,
                "window_rows": self.window_rows,
                "current_rows": self._current_rows,
                "psi_threshold": self.psi_threshold,
                "alert_active": self._alert_active,
            }
            if psi_f is not None:
                order = np.argsort(psi_f)[::-1][: self.top_n]
                out.update(
                    psi_max=float(np.max(psi_f)),
                    psi_mean=float(np.mean(psi_f)),
                    kl_max=float(np.max(self._last_kl)),
                    drifted_features=int(
                        np.sum(psi_f > self.psi_threshold)
                    ),
                    top={f"f{int(f)}": float(psi_f[f]) for f in order},
                )
            return out

    def feature_psi(self) -> Optional[np.ndarray]:
        """Per-feature PSI of the last completed window (``[d]``), or
        ``None`` before the first window closes."""
        with self._lock:
            return None if self._last_psi is None else self._last_psi.copy()

    def close(self) -> None:
        """Unregister the live source (owner shutdown); the watchdog's
        quality rule freezes once no monitor is live."""
        self._closed = True
        self._registry.unregister_source(self._source_name)


# ---------------------------------------------------------------------------
# staged attribution over pre-warmed ensemble-prefix tiers
# ---------------------------------------------------------------------------


def staged_attribution(
    engine,
    X,
    method: str = "predict",
    uncertainty_threshold: float = 0.5,
    full=None,
) -> Dict[str, Any]:
    """Per-request margin decomposition over the engine's pre-warmed
    ensemble prefixes (``PackedModel.take(k)`` tier programs).

    For each configured tier ``k`` the request is re-served through the
    first-``k``-member prefix — every program involved was AOT-compiled
    at warmup, so this performs zero compiles (it does add one dispatch
    per tier, which is why the fleet only runs it on a sampled fraction
    of requests).  ``margins[k]`` is the prefix's disagreement with the
    full model (label-disagreement rate for classifier ``predict``,
    normalized mean-absolute difference otherwise); ``uncertainty`` is
    the maximum disagreement across tiers — members past the smallest
    prefix still flipping the answer is exactly per-member disagreement,
    the cheap ensemble uncertainty score.  ``full`` short-circuits the
    full-model serve when the caller already holds the delivered answer
    (the fleet's sampled path re-uses it — tiers are the only extra
    dispatches)."""
    tiers = tuple(engine.prefix_tiers)
    if full is None:
        full = engine.predict(X, method=method)
    full_f = np.asarray(full, np.float32)
    classification = bool(
        engine.packed.is_classifier and method == "predict"
    )
    margins: Dict[str, float] = {}
    disagreements: List[float] = []
    for k in tiers:
        pk = engine.predict(X, method=method, tier=k)
        dis = prediction_divergence(full_f, pk, classification)
        margins[str(int(k))] = dis
        disagreements.append(dis)
    uncertainty = float(max(disagreements)) if disagreements else 0.0
    return {
        "tiers": [int(k) for k in tiers],
        "margins": margins,
        "uncertainty": uncertainty,
        "flagged": uncertainty > float(uncertainty_threshold),
    }


# ---------------------------------------------------------------------------
# ShadowScorer: registry-driven candidate evaluation on sampled traffic
# ---------------------------------------------------------------------------


class ShadowScorer:
    """Score a candidate model against live primary traffic.

    Every ``1/fraction``-th ``observe()`` call (deterministic counter —
    no RNG, so CI runs are reproducible) leases the candidate engine
    from the :class:`ModelRegistry` (pin-until-reply, so a hot-swap can
    never free it mid-score), predicts the same rows, and records the
    prediction divergence against the primary's served output.  When
    ground truth arrives later, :meth:`record_label` joins it back by
    request id and accumulates the label-delayed accuracy delta
    (candidate minus primary; positive = candidate better).

    Emits one ``shadow_eval`` event per sampled request, keeps a rolling
    divergence over the last ``window`` evals in the
    ``quality/<stream>`` source + ``quality/shadow_divergence`` gauge
    (the watchdog's ``shadow_divergence`` rule), and raise/clear
    transitions across ``divergence_threshold`` emit ``quality_alert``
    events."""

    def __init__(
        self,
        registry,
        candidate: str,
        *,
        fraction: float = 0.25,
        method: str = "predict",
        classification: Optional[bool] = None,
        divergence_threshold: float = 0.25,
        window: int = 64,
        label_buffer: int = 1024,
        stream: str = "shadow",
        telemetry_path: Optional[str] = None,
        metrics=None,
    ):
        from spark_ensemble_tpu.telemetry.events import global_metrics

        if not (0.0 < float(fraction) <= 1.0):
            raise ValueError(f"fraction must be in (0, 1]; got {fraction}")
        self._registry = registry
        self._candidate = candidate
        self._period = max(1, int(round(1.0 / float(fraction))))
        self._method = method
        self._classification = classification
        self._threshold = float(divergence_threshold)
        self._stream = stream
        self._telemetry_path = telemetry_path
        self._metrics = (
            metrics if metrics is not None else global_metrics()
        )
        self._lock = threading.Lock()
        self._seq = 0
        self._evals = 0
        self._sampled_rows = 0
        self._errors = 0
        self._window: "collections.deque" = collections.deque(
            maxlen=int(window)
        )
        self._pending: "collections.OrderedDict" = collections.OrderedDict()
        self._label_buffer = int(label_buffer)
        self._labeled_rows = 0
        self._primary_score = 0.0
        self._shadow_score = 0.0
        self._alert_active = False
        self._closed = False
        self._source_name = f"quality/{stream}"
        self._metrics.register_source(self._source_name, self.snapshot)

    # -- live scoring ------------------------------------------------------

    def observe(
        self, X, primary, request_id: Optional[Any] = None
    ) -> Optional[Dict[str, Any]]:
        """Maybe shadow-score one served request: returns the eval record
        for sampled requests, ``None`` for the rest.  The primary's
        answer was already delivered to the caller — shadow scoring rides
        AFTER the reply, off the request's critical path."""
        with self._lock:
            if self._closed:
                return None
            self._seq += 1
            if (self._seq - 1) % self._period != 0:
                return None
        try:
            with self._registry.lease(self._candidate) as eng:
                classification = self._classification
                if classification is None:
                    classification = bool(
                        eng.packed.is_classifier
                        and self._method == "predict"
                    )
                shadow = eng.predict(X, method=self._method)
        except Exception:  # noqa: BLE001 - a sick candidate never breaks serving
            with self._lock:
                self._errors += 1
            return None
        primary_f = np.asarray(primary, np.float32)
        shadow_f = np.asarray(shadow, np.float32)
        div = prediction_divergence(primary_f, shadow_f, classification)
        rows = int(np.shape(primary_f)[0]) if primary_f.ndim else 1
        with self._lock:
            self._evals += 1
            self._sampled_rows += rows
            self._window.append(div)
            rolling = float(np.mean(self._window))
            evals = self._evals
            if request_id is not None:
                self._pending[request_id] = (
                    primary_f, shadow_f, classification,
                )
                while len(self._pending) > self._label_buffer:
                    self._pending.popitem(last=False)
            was_active = self._alert_active
            self._alert_active = rolling > self._threshold
            transition = (
                "raised" if self._alert_active and not was_active
                else "cleared" if was_active and not self._alert_active
                else None
            )
        self._metrics.gauge("quality/shadow_divergence").set(rolling)
        self._metrics.counter("quality/shadow_evals").inc()
        from spark_ensemble_tpu.telemetry.events import emit_event

        record = {
            "candidate": self._candidate,
            "rows": rows,
            "divergence": div,
            "rolling_divergence": rolling,
            "evals": evals,
        }
        emit_event(
            "shadow_eval",
            path=self._telemetry_path,
            fit_id=self._stream,
            **record,
        )
        if transition is not None:
            self._metrics.counter("quality/alerts_total").inc()
            emit_event(
                "quality_alert",
                path=self._telemetry_path,
                fit_id=self._stream,
                state=transition,
                metric="shadow_divergence",
                value=rolling,
                threshold=self._threshold,
            )
        return record

    # -- label-delayed accuracy --------------------------------------------

    def record_label(self, request_id: Any, y_true) -> bool:
        """Join delayed ground truth back to a shadow-scored request;
        returns ``False`` when the id was never sampled (or already aged
        out of the buffer).  Scores: accuracy for classifiers, negative
        mean-absolute error for regressors — either way the delta is
        candidate minus primary, positive meaning the candidate wins."""
        with self._lock:
            entry = self._pending.pop(request_id, None)
        if entry is None:
            return False
        primary_f, shadow_f, classification = entry
        y = np.asarray(y_true, np.float32).ravel()
        a = primary_f.ravel()[: y.size]
        b = shadow_f.ravel()[: y.size]
        if classification:
            p_score = float(np.mean(a == y))
            s_score = float(np.mean(b == y))
        else:
            p_score = -float(np.mean(np.abs(a - y)))
            s_score = -float(np.mean(np.abs(b - y)))
        with self._lock:
            self._labeled_rows += int(y.size)
            self._primary_score += p_score
            self._shadow_score += s_score
            n = max(
                1, self._labeled_rows // max(1, y.size)
            )  # per-request averaging
            delta = (self._shadow_score - self._primary_score) / n
        self._metrics.gauge("quality/shadow_accuracy_delta").set(delta)
        return True

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            rolling = (
                float(np.mean(self._window)) if self._window else None
            )
            n_req = max(
                1, self._evals
            )
            out: Dict[str, Any] = {
                "kind": "shadow",
                "candidate": self._candidate,
                "period": self._period,
                "requests_seen": self._seq,
                "evals": self._evals,
                "sampled_rows": self._sampled_rows,
                "errors": self._errors,
                "threshold": self._threshold,
                "alert_active": self._alert_active,
                "labeled_rows": self._labeled_rows,
            }
            if rolling is not None:
                out["divergence"] = rolling
            if self._labeled_rows:
                out["accuracy_delta"] = (
                    self._shadow_score - self._primary_score
                ) / n_req
            return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._metrics.unregister_source(self._source_name)
