"""Version seam for jax APIs this package straddles.

pyproject pins ``jax>=0.8`` (the ``jax.shard_map``/``check_vma`` API), but
the package must still *import* — and as much as possible *run* — on older
runtimes (the reference deployment images lag the pin).  Every module that
needs ``shard_map`` imports it from here instead of ``jax`` directly:

- jax >= 0.8: ``jax.shard_map`` with varying-axes tracking controlled by
  ``check_vma=``.
- older jax (< 0.4.35 era API): ``jax.experimental.shard_map.shard_map``
  whose equivalent knob is ``check_rep=`` — the wrapper translates, so call
  sites write the NEW spelling only.

See also ``ops.collective.pvary_like_shard`` for the matching
pcast/pvary/no-op seam inside shard_map bodies.
"""

from __future__ import annotations

try:  # jax >= 0.8: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the ``check_vma`` knob translated to the
    running jax's spelling (``check_rep`` pre-0.8)."""
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )
