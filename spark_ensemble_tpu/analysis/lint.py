"""Tier-1 engine: AST lint over the package, with justified suppressions.

The engine owns everything rule-independent: file discovery, parsing,
comment/suppression extraction, the rule registry, and JSONL rendering.
Rules (:mod:`~spark_ensemble_tpu.analysis.rules`) are small visitor
classes registered with :func:`register_rule`; each sees a
:class:`FileContext` (source, AST, import map, traced-scope map) and
yields :class:`Finding` records.

Suppression syntax — one comment, on the offending line or the line
directly above it::

    x = jax.device_get(out)  # graftlint: ignore[unfenced-blocking-read] -- warmup read, untimed

The justification after ``--`` is **mandatory**: a bare
``# graftlint: ignore[rule]`` is itself reported as
``suppression-missing-reason`` and does not suppress anything, so every
silenced finding in the repo carries a human-readable reason
(docs/static_analysis.md).
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

#: repo-relative targets a bare ``graftlint`` run lints (tests/ and
#: website/ are intentionally excluded: tests read device values freely)
DEFAULT_TARGETS = (
    "spark_ensemble_tpu",
    "tools",
    "bench.py",
    "__graft_entry__.py",
)

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*ignore\[([A-Za-z0-9_\-, ]+)\]\s*(?:--\s*(\S.*))?\s*$"
)


@dataclass
class Finding:
    """One lint finding: ``file:line`` + rule id + message, plus the
    suppression state resolved by the engine."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: Optional[str] = None

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_record(self) -> dict:
        """The JSONL record shape shared with the telemetry tooling
        (``tools/telemetry_report.py`` conventions: flat JSON object per
        line, snake_case keys)."""
        rec = {
            "event": "lint_finding",
            "rule": self.rule,
            "file": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
        }
        if self.justification:
            rec["justification"] = self.justification
        return rec


@dataclass
class Suppression:
    rules: Tuple[str, ...]
    reason: Optional[str]
    comment_line: int
    target_line: int
    used: bool = False


class LintRule:
    """Base class for pluggable rules.

    Subclasses set ``id`` (kebab-case, stable — it is the suppression
    token and the JSONL key) and ``doc`` (one paragraph rendered into the
    rule catalogue), and implement :meth:`check`.  Each rule has a
    minimal positive and negative fixture under ``tests/fixtures/lint/``
    named ``<id with _>_bad.py`` / ``<id with _>_ok.py``.
    """

    id: str = ""
    doc: str = ""

    def check(self, ctx: "FileContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=ctx.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


_REGISTRY: Dict[str, LintRule] = {}


def register_rule(cls):
    """Class decorator adding a rule to the registry (instantiated once;
    rules are stateless across files)."""
    rule = cls()
    if not rule.id:
        raise ValueError(f"{cls.__name__} must set a rule id")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> Dict[str, LintRule]:
    from spark_ensemble_tpu.analysis import rules as _rules  # noqa: F401

    return dict(_REGISTRY)


@dataclass
class FileContext:
    """Everything a rule may want about one file, parsed once."""

    path: str
    relpath: str
    src: str
    lines: List[str]
    tree: ast.Module
    comments: Dict[int, str] = field(default_factory=dict)
    _imports: Optional[object] = None
    _traced: Optional[dict] = None
    _parents: Optional[dict] = None

    @property
    def imports(self):
        if self._imports is None:
            from spark_ensemble_tpu.analysis.rules import ImportMap

            self._imports = ImportMap(self.tree)
        return self._imports

    @property
    def traced(self) -> dict:
        """Map of function nodes traced by JAX (jit/vmap/grad/lax control
        flow) -> :class:`~spark_ensemble_tpu.analysis.rules.TracedScope`."""
        if self._traced is None:
            from spark_ensemble_tpu.analysis.rules import find_traced_scopes

            self._traced = find_traced_scopes(self.tree, self.imports)
        return self._traced

    @property
    def parents(self) -> dict:
        if self._parents is None:
            parents: dict = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    parents[child] = parent
            self._parents = parents
        return self._parents

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing FunctionDef/AsyncFunctionDef, or None."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def in_traced_scope(self, node: ast.AST):
        """The innermost traced scope ``node`` sits in, or None.  Nested
        defs inside a traced function are traced too (tracing follows the
        call)."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in self.traced:
                return self.traced[cur]
            cur = self.parents.get(cur)
        return None


def _collect_comments(src: str) -> Dict[int, str]:
    comments: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        pass
    return comments


def _parse_suppressions(
    comments: Dict[int, str], lines: List[str]
) -> Tuple[Dict[int, List[Suppression]], List[Finding]]:
    """Suppression map (target line -> suppressions) + the findings the
    suppressions themselves generate (missing justification)."""
    by_line: Dict[int, List[Suppression]] = {}
    meta: List[Finding] = []
    for line_no, text in comments.items():
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = tuple(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        reason = (m.group(2) or "").strip() or None
        code = lines[line_no - 1][: lines[line_no - 1].find("#")].strip()
        if code:
            target = line_no  # trailing comment: suppress its own line
        else:
            # full-line comment: suppress the next line carrying code
            target = line_no + 1
            while target <= len(lines) and not lines[target - 1].strip():
                target += 1
        sup = Suppression(rules, reason, line_no, target)
        if reason is None:
            meta.append(
                Finding(
                    rule="suppression-missing-reason",
                    path="",  # engine fills the relpath
                    line=line_no,
                    col=0,
                    message=(
                        "graftlint suppression without a justification: "
                        "append ` -- <reason>` (a bare ignore suppresses "
                        "nothing)"
                    ),
                )
            )
        else:
            by_line.setdefault(target, []).append(sup)
    return by_line, meta


def lint_file(
    path: str,
    repo_root: Optional[str] = None,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint one file; returns ALL findings with suppressed ones marked
    (callers gate on the unsuppressed subset)."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    relpath = (
        os.path.relpath(path, repo_root) if repo_root else path
    )
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                rule="syntax-error",
                path=relpath,
                line=e.lineno or 0,
                col=e.offset or 0,
                message=f"cannot parse: {e.msg}",
            )
        ]
    lines = src.splitlines()
    ctx = FileContext(
        path=path,
        relpath=relpath,
        src=src,
        lines=lines,
        tree=tree,
        comments=_collect_comments(src),
    )
    suppressions, findings = _parse_suppressions(ctx.comments, lines)
    for f_ in findings:
        f_.path = relpath
    rules = all_rules()
    wanted = set(select) if select else None
    for rule_id, rule in sorted(rules.items()):
        if wanted is not None and rule_id not in wanted:
            continue
        for finding in rule.check(ctx):
            findings.append(finding)
    for finding in findings:
        if finding.rule == "suppression-missing-reason":
            continue  # the meta rule cannot be suppressed
        for sup in suppressions.get(finding.line, []):
            if finding.rule in sup.rules or "all" in sup.rules:
                finding.suppressed = True
                finding.justification = sup.reason
                sup.used = True
    return findings


def discover_files(targets: Iterable[str], repo_root: str) -> List[str]:
    out: List[str] = []
    for target in targets:
        full = (
            target
            if os.path.isabs(target)
            else os.path.join(repo_root, target)
        )
        if os.path.isfile(full):
            out.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git")
            ]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    return sorted(set(out))


def lint_paths(
    targets: Optional[Iterable[str]] = None,
    repo_root: Optional[str] = None,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint ``targets`` (files or directories; default: the package,
    tools/, bench.py) relative to ``repo_root``."""
    if repo_root is None:
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    files = discover_files(targets or DEFAULT_TARGETS, repo_root)
    findings: List[Finding] = []
    for path in files:
        findings.extend(lint_file(path, repo_root=repo_root, select=select))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def write_jsonl(findings: Iterable[Finding], path: str) -> None:
    """One finding per line, in the flat-JSON-record shape the telemetry
    tooling reads and diffs (``tools/telemetry_report.py``)."""
    with open(path, "w") as f:
        for finding in findings:
            f.write(json.dumps(finding.to_record(), sort_keys=True) + "\n")
