"""Tier-2: traced program contracts for the public entry points.

Tier 1 reads source; this tier reads **programs**.  It fits and predicts
every ensemble family on tiny canonical shape classes with a
program-call observer registered at the ``cached_program`` /
``_predict_program`` chokepoint (:func:`~spark_ensemble_tpu.models.base.
observe_program_calls`), abstractly re-traces each distinct program once
(``jax.make_jaxpr``), and asserts the machine-checkable contracts the
performance subsystems depend on:

- **compile budgets**: the number of distinct ``(program tag, abstract
  argument signature)`` pairs each entry point dispatches, pinned
  against the committed ``analysis/contracts.json`` baseline.  Counting
  *signatures* rather than backend compiles makes the budget immune to
  cache warmth, the persistent compilation cache, and chaos-retry
  replays (a retry re-calls the same signature); a NEW signature is
  exactly what jit would retrace on, so drift here is retrace drift.
- **no f64**: no float64/complex128 aval anywhere in any traced jaxpr
  (the f32 dtype policy, enforced end-to-end).
- **no host callbacks**: no ``pure_callback``/``io_callback``/debug
  callback primitives inside round-loop programs — a host callback in a
  round body re-serializes the dispatch pipeline the lookahead exists
  to overlap.
- **collective axes**: every ``axis_name`` appearing in any program is
  one of the blessed mesh axes ``{dcn_data, data, member}``.
- **donation consumed** (serving, non-CPU backends only): warming the
  engine must not raise "donated buffers were not usable" warnings.
- **serving warmup**: exactly ``len(methods) x len(buckets)`` AOT
  programs, and steady-state serving performs zero backend compiles.

Tracing runs under a scrubbed environment (chaos, device patience,
telemetry phases, pipeline depth pinned; autotune forced ``off``) so
the observed program set is a pure function of the code — the property
that lets ``contracts.json`` live in git.
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: mesh axis names blessed by the distributed design (docs/distributed.md)
ALLOWED_AXES = frozenset({"dcn_data", "data", "member"})

#: jaxpr primitives that call back into the host
_CALLBACK_PRIMITIVES = frozenset(
    {"pure_callback", "io_callback", "debug_callback", "callback",
     "host_callback", "outside_call", "infeed", "outfeed"}
)

#: program tags that form the per-round hot loop — host callbacks are
#: forbidden specifically there (a callback per round stalls the pipeline)
_ROUND_LOOP_TAGS = ("chunk", "round", "fit", "scan")

#: canonical shape class every family is traced on: small enough for CPU
#: CI, large enough to exercise binning/bucketing
_N, _D, _K = 64, 6, 3

_BASELINE_PATH = os.path.join(os.path.dirname(__file__), "contracts.json")


@dataclass
class ContractViolation:
    contract: str  # budget | f64 | host-callback | axis-name | donation | serving
    entry_point: str
    message: str

    def to_record(self) -> dict:
        return {
            "event": "contract_violation",
            "contract": self.contract,
            "entry_point": self.entry_point,
            "message": self.message,
        }


@dataclass
class ContractReport:
    """Outcome of one contract trace: per-entry-point program budgets plus
    every violation found (empty == the repo honors its contracts)."""

    budgets: Dict[str, int] = field(default_factory=dict)
    violations: List[ContractViolation] = field(default_factory=list)
    skipped: Dict[str, str] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def baseline(self) -> dict:
        """The committed-baseline shape: versioned, sorted, timestamp-free
        (byte-stable across runs, so git diffs are semantic)."""
        return {
            "version": 1,
            "entry_points": {k: self.budgets[k] for k in sorted(self.budgets)},
        }


class _ProgramRecorder:
    """Observer for :func:`observe_program_calls`: counts distinct
    (tag, signature) programs and abstractly re-traces each one once."""

    def __init__(self):
        self._lock = threading.Lock()
        self.programs: Dict[Tuple[str, tuple], Any] = {}

    def __call__(self, tag, sig, fn, args, kwargs):
        key = (tag, sig)
        with self._lock:
            if key in self.programs:
                return
            self.programs[key] = None
        jaxpr = None
        try:
            import jax

            jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
        except Exception:  # abstract re-trace is best-effort per program
            jaxpr = None
        with self._lock:
            self.programs[key] = jaxpr

    def count(self) -> int:
        return len(self.programs)


def _scrubbed_env():
    """Pin every behavior-bearing env knob to the canonical contract
    configuration for the enclosed trace (restored on exit)."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        saved = {
            k: os.environ.pop(k)
            for k in list(os.environ)
            if k.startswith("SE_TPU_")
        }
        os.environ["SE_TPU_AUTOTUNE"] = "off"
        os.environ["SE_TPU_PIPELINE"] = "0"
        try:
            yield
        finally:
            for k in list(os.environ):
                if k.startswith("SE_TPU_"):
                    del os.environ[k]
            os.environ.update(saved)

    return _scope()


def _canonical_data(classification: bool):
    rng = np.random.default_rng(7)
    X = rng.standard_normal((_N, _D)).astype(np.float32)
    if classification:
        y = (np.arange(_N) % _K).astype(np.int32)
        rng.shuffle(y)
    else:
        y = (X @ rng.standard_normal(_D) + 0.1 * rng.standard_normal(_N)).astype(
            np.float32
        )
    return X, y


def _entry_points() -> Dict[str, dict]:
    """Constructors for the canonical contract fixtures: every family,
    classifier + regressor, smallest configs that still run the real
    round drivers."""
    import spark_ensemble_tpu as se

    def tree_r():
        return se.DecisionTreeRegressor(max_depth=3)

    def tree_c():
        return se.DecisionTreeClassifier(max_depth=3)

    return {
        "gbm_regressor": dict(
            make=lambda: se.GBMRegressor(
                base_learner=tree_r(), num_base_learners=3, seed=0
            ),
            classification=False,
        ),
        "gbm_classifier": dict(
            make=lambda: se.GBMClassifier(
                base_learner=tree_r(), num_base_learners=3, seed=0
            ),
            classification=True,
        ),
        "boosting_regressor": dict(
            make=lambda: se.BoostingRegressor(
                base_learner=tree_r(), num_base_learners=3, seed=0
            ),
            classification=False,
        ),
        "boosting_classifier": dict(
            make=lambda: se.BoostingClassifier(
                base_learner=tree_c(), num_base_learners=3, seed=0
            ),
            classification=True,
        ),
        "bagging_regressor": dict(
            make=lambda: se.BaggingRegressor(
                base_learner=tree_r(), num_base_learners=3, seed=0
            ),
            classification=False,
        ),
        "bagging_classifier": dict(
            make=lambda: se.BaggingClassifier(
                base_learner=tree_c(), num_base_learners=3, seed=0
            ),
            classification=True,
        ),
        "stacking_regressor": dict(
            make=lambda: se.StackingRegressor(
                base_learners=[tree_r(), se.LinearRegression()],
                stacker=se.LinearRegression(),
            ),
            classification=False,
        ),
        "stacking_classifier": dict(
            make=lambda: se.StackingClassifier(
                base_learners=[tree_c(), se.LogisticRegression()],
                stacker=se.LogisticRegression(),
            ),
            classification=True,
        ),
    }


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def _iter_eqns(jaxpr):
    """Every equation in a (Closed)Jaxpr, recursing into sub-jaxprs
    (pjit bodies, scan/while/cond branches, custom-call closures)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in getattr(inner, "eqns", ()):
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(value):
    if hasattr(value, "eqns") or hasattr(value, "jaxpr"):
        yield value
    elif isinstance(value, (tuple, list)):
        for item in value:
            yield from _sub_jaxprs(item)


def _iter_avals(jaxpr):
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for v in list(getattr(inner, "invars", ())) + list(
        getattr(inner, "outvars", ())
    ):
        aval = getattr(v, "aval", None)
        if aval is not None:
            yield aval
    for eqn in _iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None:
                yield aval


def _check_jaxpr(entry: str, tag: str, jaxpr, out: List[ContractViolation]):
    wide = set()
    for aval in _iter_avals(jaxpr):
        dt = str(getattr(aval, "dtype", ""))
        # int64 index arithmetic is tolerated; wide FLOATS are the policy
        # violation (they double bandwidth through every histogram)
        if dt in ("float64", "complex128") and dt not in wide:
            wide.add(dt)
            out.append(
                ContractViolation(
                    "f64",
                    entry,
                    f"program `{tag}` carries a {dt} value: f32 "
                    "dtype policy violation",
                )
            )
    is_round_loop = any(t in tag for t in _ROUND_LOOP_TAGS)
    for eqn in _iter_eqns(jaxpr):
        prim = getattr(eqn.primitive, "name", str(eqn.primitive))
        if prim in _CALLBACK_PRIMITIVES and is_round_loop:
            out.append(
                ContractViolation(
                    "host-callback",
                    entry,
                    f"round-loop program `{tag}` embeds host callback "
                    f"primitive `{prim}`: re-serializes the dispatch "
                    "pipeline",
                )
            )
        axis = eqn.params.get("axis_name")
        if axis is not None:
            axes = axis if isinstance(axis, (tuple, list)) else (axis,)
            for a in axes:
                if isinstance(a, str) and a not in ALLOWED_AXES:
                    out.append(
                        ContractViolation(
                            "axis-name",
                            entry,
                            f"program `{tag}` uses collective axis "
                            f"`{a}` outside the blessed mesh axes "
                            f"{sorted(ALLOWED_AXES)}",
                        )
                    )


# ---------------------------------------------------------------------------
# the trace
# ---------------------------------------------------------------------------


def _trace_family(name: str, spec: dict, report: ContractReport) -> None:
    import jax

    from spark_ensemble_tpu.models.base import observe_program_calls

    X, y = _canonical_data(spec["classification"])
    est = spec["make"]()

    rec = _ProgramRecorder()
    with observe_program_calls(rec):
        model = est.fit(X, y)
    report.budgets[f"{name}.fit"] = rec.count()
    for (tag, _), jaxpr in rec.programs.items():
        if jaxpr is not None:
            _check_jaxpr(f"{name}.fit", tag, jaxpr, report.violations)

    if name.startswith("stacking"):
        # stacking fits heterogeneous members EAGERLY (no cached-program
        # dispatch — the 0 budget above pins exactly that), so its fit-side
        # dtype/callback coverage comes from abstractly tracing each base
        # learner's functional fit instead
        _check_stacking_member_fits(name, est, X, y, spec, report)

    methods = ["predict"]
    if spec["classification"]:
        methods.append("predict_proba")
    Xs = jax.ShapeDtypeStruct((_N, _D), np.float32)
    for method in methods:
        if not hasattr(model, method):
            continue
        rec = _ProgramRecorder()
        with observe_program_calls(rec):
            getattr(model, method)(X)
        report.budgets[f"{name}.{method}"] = rec.count()
        for (tag, _), jaxpr in rec.programs.items():
            if jaxpr is not None:
                _check_jaxpr(
                    f"{name}.{method}", tag, jaxpr, report.violations
                )
        # whole-entry-point jaxpr: traces THROUGH the per-program plumbing
        # (covers families whose predicts run eagerly, e.g. stacking
        # members) — the authoritative no-f64/no-callback/axis surface
        try:
            full = jax.make_jaxpr(getattr(model, method))(Xs)
        except Exception as e:  # noqa: BLE001 - any trace failure is a skip
            report.skipped[f"{name}.{method}.jaxpr"] = (
                f"entry point not abstractly traceable: {e!r:.120}"
            )
        else:
            _check_jaxpr(
                f"{name}.{method}", "full_entry", full, report.violations
            )


def _check_stacking_member_fits(
    name: str, est, X, y, spec: dict, report: ContractReport
) -> None:
    import jax

    from spark_ensemble_tpu.models.base import as_f32

    num_classes = _K if spec["classification"] else None
    key = jax.random.PRNGKey(0)
    y_aval = jax.ShapeDtypeStruct((_N,), np.float32)
    w_aval = jax.ShapeDtypeStruct((_N,), np.float32)
    for base in est._bases():
        ctx = base.make_fit_ctx(
            as_f32(X), num_classes if base.is_classifier else None
        )
        label = f"member_fit:{type(base).__name__}"
        try:
            jaxpr = jax.make_jaxpr(
                lambda yy, ww, _b=base, _c=ctx: _b.fit_from_ctx(
                    _c, yy, ww, None, key
                )
            )(y_aval, w_aval)
        except Exception as e:  # noqa: BLE001
            report.skipped[f"{name}.fit.{label}"] = (
                f"member fit not abstractly traceable: {e!r:.120}"
            )
            continue
        _check_jaxpr(f"{name}.fit", label, jaxpr, report.violations)


def _trace_serving(report: ContractReport) -> None:
    import jax

    from spark_ensemble_tpu.serving.engine import InferenceEngine
    from spark_ensemble_tpu.telemetry.events import compile_snapshot

    import spark_ensemble_tpu as se

    X, y = _canonical_data(False)
    model = se.GBMRegressor(
        base_learner=se.DecisionTreeRegressor(max_depth=3),
        num_base_learners=3,
        seed=0,
    ).fit(X, y)

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        engine = InferenceEngine(
            model,
            methods=("predict",),
            min_bucket=8,
            max_batch_size=32,
            warm=True,
        )
    try:
        expected = len(engine._methods) * len(engine.buckets)
        got = len(engine._compiled)
        report.budgets["serving.warmup"] = got
        if got != expected:
            report.violations.append(
                ContractViolation(
                    "serving",
                    "serving.warmup",
                    f"warmup compiled {got} programs, expected "
                    f"len(methods) x len(buckets) = {expected}",
                )
            )
        if jax.default_backend() == "cpu":
            report.skipped["serving.donation"] = (
                "buffer donation is not implemented on the cpu backend"
            )
        else:
            unusable = [
                w for w in caught
                if "donated" in str(w.message).lower()
                and "not usable" in str(w.message).lower()
            ]
            if unusable:
                report.violations.append(
                    ContractViolation(
                        "donation",
                        "serving.warmup",
                        "donated request buffers were not consumed: "
                        + str(unusable[0].message)[:200],
                    )
                )
        # steady state: serving several real batch sizes after warmup must
        # perform zero backend compiles (the whole point of the buckets)
        before = compile_snapshot()[0]
        for n in (1, 7, 9, 30):
            engine.predict(X[:n])
        after = compile_snapshot()[0]
        if after != before:
            report.violations.append(
                ContractViolation(
                    "serving",
                    "serving.steady_state",
                    f"{after - before} backend compile(s) during warmed "
                    "steady-state serving (must be zero)",
                )
            )
    finally:
        engine.stop()


def _trace_fleet(report: ContractReport) -> None:
    """Trace the serving-fleet warmup contract (serving/fleet.py).

    The fleet's compile budget is O(methods x buckets x (1 + prefix
    tiers)) and **independent of the replica count** — replicas are
    clones sharing one compiled-program map.  Steady-state fleet serving,
    including degraded prefix-tier serves, must perform zero backend
    compiles after warmup."""
    from spark_ensemble_tpu.serving.fleet import FleetRouter
    from spark_ensemble_tpu.telemetry.events import compile_snapshot

    import spark_ensemble_tpu as se

    X, y = _canonical_data(False)
    model = se.GBMRegressor(
        base_learner=se.DecisionTreeRegressor(max_depth=3),
        num_base_learners=3,
        seed=0,
    ).fit(X, y)

    router = FleetRouter(
        model,
        replicas=2,
        methods=("predict",),
        prefix_tiers=(2,),
        min_bucket=8,
        max_batch_size=32,
    )
    try:
        engine = router._base
        expected = (
            len(engine._methods)
            * len(engine.buckets)
            * (1 + len(engine.prefix_tiers))
        )
        got = len(engine._compiled)
        report.budgets["fleet.warmup"] = got
        if got != expected:
            report.violations.append(
                ContractViolation(
                    "serving",
                    "fleet.warmup",
                    f"fleet warmup compiled {got} programs, expected "
                    "len(methods) x len(buckets) x (1 + len(prefix_tiers)) "
                    f"= {expected} (shared across replicas)",
                )
            )
        # steady state across replicas AND tiers: routed full-model and
        # degraded prefix serves must both hit pre-warmed programs
        before = compile_snapshot()[0]
        for n in (1, 7, 30):
            router.predict(X[:n])
        for rep in router._replicas:
            rep.engine.predict(X[:5], tier=2)
        after = compile_snapshot()[0]
        if after != before:
            report.violations.append(
                ContractViolation(
                    "serving",
                    "fleet.steady_state",
                    f"{after - before} backend compile(s) during warmed "
                    "fleet serving incl. prefix tiers (must be zero)",
                )
            )
    finally:
        router.stop()

    # rolling hot swap (docs/autopilot.md): a registry-backed swap under a
    # warmed fleet pin-leases the new version's engine and shares its warm
    # programs into every replica clone, so the swap itself compiles
    # NOTHING (``fleet.swap_compiles``) — pinned both by the swap's own
    # counter and the process-wide compile snapshot
    from spark_ensemble_tpu.serving import ModelRegistry, pack

    registry = ModelRegistry(
        capacity=4, methods=("predict",), min_bucket=8, max_batch_size=32,
    )
    registry.register("prod", model, warm=True)
    registry.register("next", pack(model).take(2), warm=True)
    fleet = FleetRouter.from_registry(registry, "prod", replicas=2)
    try:
        fleet.predict(X[:5])
        before = compile_snapshot()[0]
        info = fleet.swap_model("next")
        fleet.predict(X[:5])
        after = compile_snapshot()[0]
        got = max(int(info["swap_compiles"]), after - before)
        report.budgets["fleet.swap_compiles"] = got
        if got != 0:
            report.violations.append(
                ContractViolation(
                    "serving",
                    "fleet.swap_compiles",
                    f"rolling hot swap performed {got} backend compile(s); "
                    "both versions are registry-warmed, so a swap must "
                    "rebind replicas without compiling",
                )
            )
    finally:
        fleet.stop()
        registry.close()


def _trace_autopilot(report: ContractReport) -> None:
    """Trace the autopilot control loop's code budget (docs/autopilot.md).

    The autopilot thread sits between the watchdog's verdicts and the
    fleet's control plane; like the operator threads it must contain no
    unfenced blocking reads (``autopilot.lint``) — a control loop that
    blocks on device values can stall the very fleet it is healing.
    Linted with absolute paths so the blanket fence-module exemptions the
    repo-wide lint applies cannot mask a regression here."""
    from spark_ensemble_tpu.analysis.lint import lint_file
    from spark_ensemble_tpu.serving import autopilot

    findings = [
        f
        for f in lint_file(
            os.path.abspath(autopilot.__file__),
            select=["unfenced-blocking-read"],
        )
        if not f.suppressed
    ]
    report.budgets["autopilot.lint"] = len(findings)
    for f in findings:
        report.violations.append(
            ContractViolation(
                "autopilot",
                "autopilot.lint",
                f"unfenced blocking read in the autopilot loop: "
                f"{f.path}:{f.line}: {f.message}",
            )
        )


def _trace_streaming(report: ContractReport) -> None:
    """Trace the out-of-core streaming fit entry points (data/streaming.py).

    The steady-state contract: a streaming fit dispatches a FIXED set of
    cached programs regardless of how many shards the store holds — every
    shard is addressed through a traced index (lax.dynamic_index_in_dim),
    so sweeping more shards re-enters the same compiled accumulation
    programs instead of tracing new ones.  Each family is traced at two
    shard counts: the distinct-program count pins the ``.fit_streaming``
    budget, and any growth between the two is flagged as a ``streaming``
    violation (a per-shard retrace would re-serialize the sweep behind
    the compiler)."""
    import tempfile

    from spark_ensemble_tpu.data import write_shards
    from spark_ensemble_tpu.models.base import observe_program_calls

    import spark_ensemble_tpu as se

    for name, classification in (
        ("gbm_regressor", False),
        ("gbm_classifier", True),
    ):
        X, y = _canonical_data(classification)
        entry = f"{name}.fit_streaming"
        counts: Dict[int, int] = {}
        failed = False
        for shard_rows in (32, 16):  # _N=64 rows -> 2 shards, then 4
            with tempfile.TemporaryDirectory(
                prefix="graftlint-shards-"
            ) as tmp:
                store = write_shards(
                    X,
                    os.path.join(tmp, "store"),
                    max_bins=64,
                    shard_rows=shard_rows,
                )
                est_cls = (
                    se.GBMClassifier if classification else se.GBMRegressor
                )
                est = est_cls(
                    base_learner=se.DecisionTreeRegressor(max_depth=3),
                    num_base_learners=3,
                    seed=0,
                )
                rec = _ProgramRecorder()
                try:
                    with observe_program_calls(rec):
                        est.fit_streaming(store, y)
                except Exception as e:  # noqa: BLE001
                    report.skipped[entry] = (
                        f"streaming fit not traceable: {e!r:.120}"
                    )
                    failed = True
                    break
                counts[store.num_shards] = rec.count()
                for (tag, _), jaxpr in rec.programs.items():
                    if jaxpr is not None:
                        _check_jaxpr(entry, tag, jaxpr, report.violations)
        if failed:
            continue
        (s_a, count_a), (s_b, count_b) = sorted(counts.items())
        report.budgets[entry] = count_a
        if count_a != count_b:
            report.violations.append(
                ContractViolation(
                    "streaming",
                    entry,
                    f"program count grew with shard count ({s_a} shards: "
                    f"{count_a} programs, {s_b} shards: {count_b}): the "
                    "shard sweep must reuse one compiled program set per "
                    "level, not trace per shard",
                )
            )


def _trace_streaming_dist(report: ContractReport) -> None:
    """Trace the pod-scale distributed streaming fit (parallel/elastic.py).

    The elastic plane's budget contract extends the streaming one across
    the mesh: a distributed-histogram fit dispatches a FIXED set of
    cached programs regardless of BOTH the shard count and the row-mesh
    width — each host's sweep walks its manifest slice through one
    step-indexed program set, and the cross-host reduce is one program
    per accumulator rank.  Traced at two mesh widths x two shard counts;
    any variation is a ``distributed`` violation (a per-host or
    per-shard retrace would stall every host behind the compiler at pod
    scale)."""
    import tempfile

    import jax

    from spark_ensemble_tpu.data import write_shards
    from spark_ensemble_tpu.models.base import observe_program_calls
    from spark_ensemble_tpu.parallel.mesh import data_member_mesh

    import spark_ensemble_tpu as se

    entry = "gbm_regressor.fit_streaming_dist"
    if len(jax.devices()) < 4:
        report.skipped[entry] = (
            "distributed trace needs >= 4 devices (canonical CI env "
            "forces 8 virtual CPU devices)"
        )
        return
    X, y = _canonical_data(False)
    counts: Dict[Tuple[int, int], int] = {}
    for width in (2, 4):
        mesh = data_member_mesh(width, member=1)
        for shard_rows in (32, 16):  # _N=64 rows -> 2 shards, then 4
            with tempfile.TemporaryDirectory(
                prefix="graftlint-dist-shards-"
            ) as tmp:
                store = write_shards(
                    X,
                    os.path.join(tmp, "store"),
                    max_bins=64,
                    shard_rows=shard_rows,
                )
                est = se.GBMRegressor(
                    base_learner=se.DecisionTreeRegressor(max_depth=3),
                    num_base_learners=3,
                    seed=0,
                )
                rec = _ProgramRecorder()
                try:
                    with observe_program_calls(rec):
                        est.fit_streaming(store, y, mesh=mesh)
                except Exception as e:  # noqa: BLE001
                    report.skipped[entry] = (
                        f"distributed streaming fit not traceable: {e!r:.120}"
                    )
                    return
                counts[(width, store.num_shards)] = rec.count()
                for (tag, _), jaxpr in rec.programs.items():
                    if jaxpr is not None:
                        _check_jaxpr(entry, tag, jaxpr, report.violations)
    report.budgets[entry] = counts[(2, 2)]
    if len(set(counts.values())) != 1:
        report.violations.append(
            ContractViolation(
                "distributed",
                entry,
                "program count varies with (mesh width, shard count): "
                f"{ {f'{w}x{s}': c for (w, s), c in sorted(counts.items())} }"
                "; the distributed sweep must reuse one compiled program "
                "set per level across hosts and steps",
            )
        )


def _trace_megabatch(report: ContractReport) -> None:
    """Trace the megabatch sweep engine (models/gbm_sweep.py).

    The sweep contract: a candidate batch dispatches a FIXED set of
    cached programs regardless of how many candidates it holds — lanes
    travel the config axis of ONE vmapped round program per chunk shape,
    so doubling the sweep re-enters the same compiled set instead of
    tracing per candidate (the whole point of the megabatch refactor;
    docs/selection.md#megabatch-sweeps).  Traced at 16 and 32 candidates
    with 32 pinned as one slab (`configs_per_dispatch`); the 16-candidate
    count pins the ``gbm_regressor.fit_sweep`` budget and any growth
    between the two is a ``megabatch`` violation."""
    from spark_ensemble_tpu.autotune import override
    from spark_ensemble_tpu.models.base import observe_program_calls
    from spark_ensemble_tpu.models.gbm_sweep import fit_sweep

    import spark_ensemble_tpu as se

    entry = "gbm_regressor.fit_sweep"
    X, y = _canonical_data(False)
    base = se.GBMRegressor(
        base_learner=se.DecisionTreeRegressor(max_depth=3),
        num_base_learners=3,
        seed=0,
    )
    counts: Dict[int, int] = {}
    for n_cands in (16, 32):
        ests = [
            base.copy(learning_rate=0.05 + 0.01 * i, seed=i)
            for i in range(n_cands)
        ]
        rec = _ProgramRecorder()
        try:
            # both batches must run at ONE slab width: a 16-lane and a
            # 32-lane slab are different chunk shapes (both O(1), but the
            # growth check below wants identical program sets)
            with override(configs_per_dispatch=16):
                with observe_program_calls(rec):
                    fit_sweep(ests, X, y)
        except Exception as e:  # noqa: BLE001
            report.skipped[entry] = f"sweep not traceable: {e!r:.120}"
            return
        counts[n_cands] = rec.count()
        for (tag, _), jaxpr in rec.programs.items():
            if jaxpr is not None:
                _check_jaxpr(entry, tag, jaxpr, report.violations)
    (c_a, count_a), (c_b, count_b) = sorted(counts.items())
    report.budgets[entry] = count_a
    if count_a != count_b:
        report.violations.append(
            ContractViolation(
                "megabatch",
                entry,
                f"program count grew with candidate count ({c_a} "
                f"candidates: {count_a} programs, {c_b}: {count_b}): the "
                "sweep must batch candidates over the vmapped config "
                "axis, not trace per candidate",
            )
        )


def _trace_sampling(report: ContractReport) -> None:
    """Trace the gradient-based sampling stage (models/gbm.py GOSS/MVS).

    The ladder contract: the traced-program inventory depends on the
    compacted row BUCKET only, never on the sample rates — the
    rate-derived scalars (k_top/k_rand/amp/lambda) ride the dispatch as
    traced device operands, so two fits whose rates land in the same
    pow2 bucket must re-enter the SAME compiled program set.  Traced at
    GOSS (0.2, 0.1) and (0.3, 0.15) over the canonical 64-row fixture
    with the bucket floor pinned low enough that both land in the
    32-row bucket; any program-set difference is a ``sampling``
    violation and the first pair pins the ``gbm_regressor.fit_sampled``
    budget."""
    from spark_ensemble_tpu.autotune import override
    from spark_ensemble_tpu.models.base import observe_program_calls

    import spark_ensemble_tpu as se

    entry = "gbm_regressor.fit_sampled"
    X, y = _canonical_data(False)
    base = se.GBMRegressor(
        base_learner=se.DecisionTreeRegressor(max_depth=3),
        num_base_learners=3,
        sampling="goss",
        seed=0,
    )
    sets: Dict[Tuple[float, float], frozenset] = {}
    for rates in ((0.2, 0.1), (0.3, 0.15)):
        rec = _ProgramRecorder()
        try:
            with override(sample_bucket_floor=16):
                with observe_program_calls(rec):
                    base.copy(top_rate=rates[0], other_rate=rates[1]).fit(
                        X, y
                    )
        except Exception as e:  # noqa: BLE001
            report.skipped[entry] = f"sampled fit not traceable: {e!r:.120}"
            return
        sets[rates] = frozenset(rec.programs)
        for (tag, _), jaxpr in rec.programs.items():
            if jaxpr is not None:
                _check_jaxpr(entry, tag, jaxpr, report.violations)
    (r_a, set_a), (r_b, set_b) = sorted(sets.items())
    report.budgets[entry] = len(set_a)
    if set_a != set_b:
        diff = sorted(
            tag for tag, _ in set_a.symmetric_difference(set_b)
        )
        report.violations.append(
            ContractViolation(
                "sampling",
                entry,
                f"program set varies with sample rates ({r_a}: "
                f"{len(set_a)} programs, {r_b}: {len(set_b)}; differing "
                f"tags {diff[:6]}): rates must stay traced operands — "
                "only the pow2 row bucket may shape a program",
            )
        )


def _trace_tracing(report: ContractReport) -> None:
    """Trace the causal-tracing plane's own budget (telemetry/trace.py).

    Spans are a pure host-side construct: beginning, nesting, ending and
    reconstructing them must dispatch ZERO cached device programs — the
    pin that lets tracing stay enabled in production fits without
    touching any compile budget.  Also sanity-checks the span records
    themselves (one per unit of work, all on one trace)."""
    from spark_ensemble_tpu.models.base import observe_program_calls
    from spark_ensemble_tpu.telemetry.trace import Tracer

    sink: List[Dict[str, Any]] = []
    tracer = Tracer(sink.append, thread="contract")
    rec = _ProgramRecorder()
    with observe_program_calls(rec):
        with tracer.begin_span("fit", family="contract") as root:
            with tracer.begin_span("round_chunk", parent=root, chunk_seq=0):
                pass
            tracer.emit_span(
                "shard_load", 0.0, 1e-3, parent=root.context(),
                thread="se-tpu-shard",
            )
    report.budgets["tracing.spans"] = rec.count()
    if len(sink) != 3 or any(
        s["trace_id"] != tracer.trace_id for s in sink
    ):
        report.violations.append(
            ContractViolation(
                "tracing",
                "tracing.spans",
                f"expected 3 span records on trace {tracer.trace_id}, got "
                f"{[s.get('name') for s in sink]}",
            )
        )


def _trace_operator(report: ContractReport) -> None:
    """Trace the live operator plane's own budget (docs/operator.md).

    Two pins.  First, a full scrape — OpenMetrics render, ``/programz``
    rows, a watchdog tick, the ``/healthz`` verdict — over an inventory
    populated by a real fit must dispatch ZERO cached device programs
    (``operator.scrape``): scraping a production process can never be
    the thing that compiles or recomputes.  Second, the watchdog and
    exporter sources must contain no unfenced blocking reads
    (``operator.lint``): linted here with absolute paths, which bypasses
    the blanket ``telemetry/`` fence-module exemption the repo-wide lint
    applies, so the operator threads are held to the *device-producer*
    standard even though they live in the telemetry package."""
    from spark_ensemble_tpu.analysis.lint import lint_file
    from spark_ensemble_tpu.models.base import observe_program_calls
    from spark_ensemble_tpu.telemetry import exporter, programz, watchdog

    import spark_ensemble_tpu as se

    X, y = _canonical_data(False)
    inventory = programz.enable()
    inventory.clear()
    try:
        se.GBMRegressor(
            base_learner=se.DecisionTreeRegressor(max_depth=3),
            num_base_learners=3,
            seed=0,
        ).fit(X, y)
        inventory.analyze_pending()  # shallow: zero backend compiles
        dog = watchdog.Watchdog(interval_s=3600.0)
        rec = _ProgramRecorder()
        with observe_program_calls(rec):
            text = exporter.render_openmetrics()
            rows = inventory.rows(top=10)
            dog.evaluate_once()
            verdict = dog.verdict()
        report.budgets["operator.scrape"] = rec.count()
        problems = exporter.validate_openmetrics(text)
        if problems:
            report.violations.append(
                ContractViolation(
                    "operator",
                    "operator.scrape",
                    "the /metrics exposition fails the OpenMetrics "
                    f"checker: {problems[:3]}",
                )
            )
        if not rows or verdict.get("status") not in ("ok", "degraded"):
            report.violations.append(
                ContractViolation(
                    "operator",
                    "operator.scrape",
                    f"scrape returned no inventory rows ({len(rows)}) or "
                    f"a malformed verdict ({verdict.get('status')!r})",
                )
            )
    finally:
        programz.disable()
        inventory.clear()
    findings = []
    for mod in (watchdog, exporter):
        findings.extend(
            f
            for f in lint_file(
                os.path.abspath(mod.__file__),
                select=["unfenced-blocking-read"],
            )
            if not f.suppressed
        )
    report.budgets["operator.lint"] = len(findings)
    for f in findings:
        report.violations.append(
            ContractViolation(
                "operator",
                "operator.lint",
                f"unfenced blocking read in an operator thread: "
                f"{f.path}:{f.line}: {f.message}",
            )
        )


def _trace_quality(report: ContractReport) -> None:
    """Trace the model-quality plane's budget (docs/quality.md).

    Three pins.  ``quality.warmup``: a drift-enabled engine warms EXACTLY
    as many compiled programs as a drift-off twin — the bin sketch is
    fused into the existing bucket programs, never compiled beside them.
    ``quality.serve_dispatches_per_request``: serving warmed requests
    with the sketch on stays ONE device dispatch per request (counted by
    wrapping the engine's program cache — AOT programs bypass the
    ``observe_program_calls`` chokepoint), performs zero backend
    compiles, and returns outputs bit-identical to the drift-off twin.
    ``quality.lint``: ``telemetry/quality.py`` carries no unfenced
    blocking reads — linted with the absolute path, which bypasses the
    blanket ``telemetry/`` fence-module exemption, because the drift
    monitor runs inline on serving threads."""
    from spark_ensemble_tpu.analysis.lint import lint_file
    from spark_ensemble_tpu.serving.engine import InferenceEngine
    from spark_ensemble_tpu.serving.export import pack
    from spark_ensemble_tpu.telemetry import quality
    from spark_ensemble_tpu.telemetry.events import compile_snapshot

    import spark_ensemble_tpu as se

    X, y = _canonical_data(False)
    model = se.GBMRegressor(
        base_learner=se.DecisionTreeRegressor(max_depth=3),
        num_base_learners=3,
        seed=0,
    ).fit(X, y)
    packed = pack(model)
    if packed.quality is None:
        report.violations.append(
            ContractViolation(
                "quality",
                "quality.warmup",
                "pack(model) carries no drift reference (PackedModel."
                "quality is None) — fit must capture the bin occupancy "
                "the sketch scores against",
            )
        )
        return
    off = InferenceEngine(
        packed, methods=("predict",), min_bucket=8, max_batch_size=32,
        warm=True, drift=False,
    )
    on = InferenceEngine(
        packed, methods=("predict",), min_bucket=8, max_batch_size=32,
        warm=True, drift=True,
    )
    try:
        n_off, n_on = len(off._compiled), len(on._compiled)
        report.budgets["quality.warmup"] = n_on
        if n_on != n_off:
            report.violations.append(
                ContractViolation(
                    "quality",
                    "quality.warmup",
                    f"drift-on engine warmed {n_on} programs vs {n_off} "
                    "with drift off — the sketch must fuse into the "
                    "existing bucket programs, not compile beside them",
                )
            )
        sizes = (1, 7, 9, 30)
        calls = [0]

        def _counted(fn):
            def inner(*a, **k):
                calls[0] += 1
                return fn(*a, **k)

            return inner

        on._compiled = {k: _counted(v) for k, v in on._compiled.items()}
        before = compile_snapshot()[0]
        for n in sizes:
            got = on.predict(X[:n])
            want = off.predict(X[:n])
            if not np.array_equal(np.asarray(got), np.asarray(want)):
                report.violations.append(
                    ContractViolation(
                        "quality",
                        "quality.serve_dispatches_per_request",
                        f"drift-on predictions diverge from the drift-off "
                        f"twin at n={n} — the sketch must be a pure "
                        "side-output, never touch the prediction",
                    )
                )
                break
        after = compile_snapshot()[0]
        per, rem = divmod(calls[0], len(sizes))
        report.budgets["quality.serve_dispatches_per_request"] = (
            per if not rem else calls[0]
        )
        if rem or after != before:
            report.violations.append(
                ContractViolation(
                    "quality",
                    "quality.serve_dispatches_per_request",
                    f"{calls[0]} dispatch(es) and {after - before} backend "
                    f"compile(s) serving {len(sizes)} warmed drift-on "
                    "requests (must be one dispatch per request, zero "
                    "compiles)",
                )
            )
    finally:
        on.stop()
        off.stop()
    findings = [
        f
        for f in lint_file(
            os.path.abspath(quality.__file__),
            select=["unfenced-blocking-read"],
        )
        if not f.suppressed
    ]
    report.budgets["quality.lint"] = len(findings)
    for f in findings:
        report.violations.append(
            ContractViolation(
                "quality",
                "quality.lint",
                f"unfenced blocking read on the quality plane: "
                f"{f.path}:{f.line}: {f.message}",
            )
        )


def trace_contracts(
    entry_points: Optional[List[str]] = None,
) -> ContractReport:
    """Fit/predict every family (plus serving warmup) on the canonical
    shape classes under the scrubbed environment, and return the budgets
    and intrinsic violations (f64 / host-callback / axis / donation /
    serving).  Budget *drift* is judged separately by
    :func:`check_contracts` against the committed baseline."""
    report = ContractReport()
    specs = _entry_points()
    wanted = set(entry_points) if entry_points else None
    with _scrubbed_env():
        for name, spec in specs.items():
            if wanted is not None and name not in wanted:
                continue
            _trace_family(name, spec, report)
        if wanted is None or "serving" in wanted:
            _trace_serving(report)
        if wanted is None or "fleet" in wanted:
            _trace_fleet(report)
        if wanted is None or "streaming" in wanted:
            _trace_streaming(report)
        if wanted is None or "distributed" in wanted:
            _trace_streaming_dist(report)
        if wanted is None or "megabatch" in wanted:
            _trace_megabatch(report)
        if wanted is None or "sampling" in wanted:
            _trace_sampling(report)
        if wanted is None or "tracing" in wanted:
            _trace_tracing(report)
        if wanted is None or "operator" in wanted:
            _trace_operator(report)
        if wanted is None or "autopilot" in wanted:
            _trace_autopilot(report)
        if wanted is None or "quality" in wanted:
            _trace_quality(report)
    return report


def load_baseline(path: Optional[str] = None) -> Optional[dict]:
    path = path or _BASELINE_PATH
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def check_contracts(
    baseline: Optional[dict] = None,
    report: Optional[ContractReport] = None,
    entry_points: Optional[List[str]] = None,
) -> ContractReport:
    """Trace (unless a ``report`` is supplied) and verify the budgets
    against ``baseline`` (default: the committed ``contracts.json``).
    Budget drift — an entry point dispatching MORE or FEWER distinct
    programs than pinned — is appended as ``budget`` violations with the
    one-command fix in the message."""
    if report is None:
        report = trace_contracts(entry_points)
    if baseline is None:
        baseline = load_baseline()
    if baseline is None:
        report.violations.append(
            ContractViolation(
                "budget",
                "*",
                "no committed baseline (analysis/contracts.json); "
                "generate one with `python tools/graftlint.py "
                "--update-baseline`",
            )
        )
        return report
    pinned: Dict[str, int] = baseline.get("entry_points", {})
    for entry in sorted(set(pinned) | set(report.budgets)):
        if entry_points and not any(
            entry.startswith(e) for e in entry_points
        ):
            continue
        want, got = pinned.get(entry), report.budgets.get(entry)
        if want is None:
            report.violations.append(
                ContractViolation(
                    "budget",
                    entry,
                    f"entry point not in the committed baseline (traces "
                    f"{got} programs); re-pin with `python "
                    "tools/graftlint.py --update-baseline`",
                )
            )
        elif got is None:
            continue  # partial trace: entry not requested this run
        elif got != want:
            report.violations.append(
                ContractViolation(
                    "budget",
                    entry,
                    f"compile budget drift: {got} distinct programs vs "
                    f"{want} pinned; if intentional re-pin with `python "
                    "tools/graftlint.py --update-baseline`",
                )
            )
    return report


def update_baseline(path: Optional[str] = None) -> dict:
    """Regenerate ``analysis/contracts.json`` from a fresh trace (the
    ``--update-baseline`` flow) and return the written baseline."""
    report = trace_contracts()
    base = report.baseline()
    path = path or _BASELINE_PATH
    with open(path, "w", encoding="utf-8") as f:
        json.dump(base, f, indent=2, sort_keys=True)
        f.write("\n")
    return base
