"""JAX-aware static analysis: the ``graftlint`` two-tier gate.

The performance subsystems layered onto this package (lookahead dispatch
pipeline, autotuned execution, fused Pallas round kernel, AOT serving,
chaos runtime) all rest on invariants nothing in the type system checks:
bit-identity requires PRNG keys derived from absolute round indices (never
reused), throughput requires zero retraces and bounded compiles, and the
pipeline requires no unfenced blocking host reads inside the dispatch
window.  This package checks those invariants mechanically, before a
change lands:

- **Tier 1** (:mod:`~spark_ensemble_tpu.analysis.lint`): a visitor-based
  AST linter with JAX-specific pluggable rules
  (:mod:`~spark_ensemble_tpu.analysis.rules`) — key reuse, Python
  branching on traced values, non-hashable ``static_argnums``, jitted
  closures over mutable state, unfenced blocking reads, f64 upcasts, host
  calls inside jitted scope.  Findings carry ``file:line`` + rule id;
  ``# graftlint: ignore[rule] -- reason`` suppresses with a mandatory
  justification.
- **Tier 2** (:mod:`~spark_ensemble_tpu.analysis.contracts`): an
  abstract-tracing program-contract checker that traces the public
  ``fit``/``predict``/``predict_proba`` entry points of all four ensemble
  families (plus the serving-engine warmup path) on canonical shape
  classes and asserts machine-checkable contracts — program-count budgets
  pinned against the committed ``analysis/contracts.json`` baseline, no
  f64 in any jaxpr, no host callbacks, donation consumed, collective axis
  names confined to the ``{dcn_data, data, member}`` mesh.

Both tiers run from ``tools/graftlint.py`` (also the ``graftlint``
console script) and gate CI (docs/static_analysis.md).
"""

from spark_ensemble_tpu.analysis.contracts import (
    ContractReport,
    ContractViolation,
    check_contracts,
    trace_contracts,
    update_baseline,
)
from spark_ensemble_tpu.analysis.lint import (
    Finding,
    LintRule,
    all_rules,
    lint_file,
    lint_paths,
    register_rule,
)

# importing the rules module populates the registry
from spark_ensemble_tpu.analysis import rules as _rules  # noqa: F401

__all__ = [
    "Finding",
    "LintRule",
    "all_rules",
    "lint_file",
    "lint_paths",
    "register_rule",
    "ContractReport",
    "ContractViolation",
    "check_contracts",
    "trace_contracts",
    "update_baseline",
]
