"""The JAX-aware lint rules (tier 1 of graftlint).

Every rule is a small, conservative AST check: it flags only patterns it
can see locally and resolves names through the module's imports, so the
false-positive rate stays near zero at the cost of missing exotic
constructions.  Each rule documents its exact trigger in ``doc`` (the
rule catalogue in docs/static_analysis.md is generated from these) and
has a minimal positive + negative fixture under ``tests/fixtures/lint/``.

Shared machinery here:

- :class:`ImportMap` resolves dotted names through the module's imports
  (``jnp.float64`` -> ``jax.numpy.float64``).
- :func:`find_traced_scopes` marks the functions JAX will trace —
  jit-decorated defs, defs passed to ``jax.jit``/``vmap``/``grad``/
  ``lax.scan``-family combinators, and everything lexically nested in
  them — along with their static argument names.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from spark_ensemble_tpu.analysis.lint import (
    FileContext,
    Finding,
    LintRule,
    register_rule,
)


class ImportMap:
    """Resolve AST name/attribute chains to canonical dotted paths."""

    def __init__(self, tree: ast.Module):
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:  # relative imports stay package-local
                    continue
                for a in node.names:
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted path for a Name/Attribute chain with the import alias
        expanded, or None for non-name expressions."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        head = self.aliases.get(cur.id, cur.id)
        return ".".join([head] + list(reversed(parts)))


# canonical prefixes (after alias expansion) that mean "this function is
# traced by JAX"; the int tuples name the positional args that are traced
# callables
_TRACING_WRAPPERS: Dict[str, Tuple[int, ...]] = {
    "jax.jit": (0,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.map": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),
}


@dataclass
class TracedScope:
    node: ast.AST  # FunctionDef | Lambda
    reason: str
    static_names: Set[str] = field(default_factory=set)

    @property
    def params(self) -> List[str]:
        args = self.node.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        if args.vararg:
            names.append(args.vararg.arg)
        return [n for n in names if n != "self"]


def _static_names_from_call(call: ast.Call, fn_node) -> Set[str]:
    """Static parameter NAMES for the wrapped function, from literal
    ``static_argnums``/``static_argnames`` keywords on a jit call."""
    names: Set[str] = set()
    pos: List[str] = []
    if isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fn_node.args
        pos = [x.arg for x in a.posonlyargs + a.args]
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        try:
            val = ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            continue
        items = val if isinstance(val, (tuple, list)) else (val,)
        for item in items:
            if isinstance(item, str):
                names.add(item)
            elif isinstance(item, int) and 0 <= item < len(pos):
                names.add(pos[item])
    return names


def find_traced_scopes(tree: ast.Module, imports: ImportMap) -> dict:
    """Map of def/lambda node -> :class:`TracedScope` for every function
    JAX traces.  Name-based matching is module-wide (a local def jitted
    two scopes away still matches); over-approximation is acceptable —
    rules built on this are themselves conservative."""
    scopes: dict = {}
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    def mark(node, reason, static: Set[str]):
        if node in scopes:
            scopes[node].static_names |= static
        else:
            scopes[node] = TracedScope(node, reason, set(static))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                path = imports.resolve(target)
                if path in _TRACING_WRAPPERS:
                    static: Set[str] = set()
                    if isinstance(dec, ast.Call):
                        static = _static_names_from_call(dec, node)
                    mark(node, path, static)
                elif (
                    path in ("functools.partial", "partial")
                    and isinstance(dec, ast.Call)
                    and dec.args
                    and imports.resolve(dec.args[0]) in _TRACING_WRAPPERS
                ):
                    mark(
                        node,
                        imports.resolve(dec.args[0]),
                        _static_names_from_call(dec, node),
                    )
        elif isinstance(node, ast.Call):
            path = imports.resolve(node.func)
            if path not in _TRACING_WRAPPERS:
                continue
            for idx in _TRACING_WRAPPERS[path]:
                if idx >= len(node.args):
                    continue
                arg = node.args[idx]
                if isinstance(arg, ast.Lambda):
                    static = (
                        _static_names_from_call(node, arg)
                        if path == "jax.jit"
                        else set()
                    )
                    mark(arg, path, static)
                elif isinstance(arg, ast.Name):
                    for fn in defs_by_name.get(arg.id, []):
                        static = (
                            _static_names_from_call(node, fn)
                            if path == "jax.jit"
                            else set()
                        )
                        mark(fn, path, static)
    return scopes


def _call_path(ctx: FileContext, node: ast.AST) -> Optional[str]:
    return ctx.imports.resolve(node)


def _walk_scope(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested defs/lambdas
    (they are separate scopes)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# rule: key-reuse
# ---------------------------------------------------------------------------

#: jax.random functions that CONSUME a key (same key in -> same draw out);
#: ``split`` is included — splitting the same key twice yields identical
#: children.  ``fold_in`` derives and is exempt unless folded with the
#: same literal twice.
_KEY_CONSUMERS = {
    "ball", "bernoulli", "beta", "binomial", "bits", "categorical",
    "cauchy", "chisquare", "choice", "dirichlet", "double_sided_maxwell",
    "exponential", "gamma", "geometric", "gumbel", "laplace", "loggamma",
    "logistic", "lognormal", "maxwell", "multivariate_normal", "normal",
    "orthogonal", "pareto", "permutation", "poisson", "rademacher",
    "randint", "rayleigh", "shuffle", "split", "t", "truncated_normal",
    "uniform", "wald", "weibull_min",
}

_KEY_PARAM_HINT = ("key", "keys", "rng", "prng")


def _is_key_name(name: str) -> bool:
    low = name.lower()
    return low in _KEY_PARAM_HINT or low.endswith("_key") or low.endswith("_rng")


@register_rule
class KeyReuseRule(LintRule):
    id = "key-reuse"
    doc = (
        "A PRNG key variable is consumed by two `jax.random.*` draws "
        "(including `split`) without being re-derived in between — the "
        "second draw repeats the first's randomness bit-for-bit.  Thread "
        "keys with `key, sub = jax.random.split(key)` or derive with "
        "`jax.random.fold_in(key, step)`; `fold_in` with distinct data is "
        "exempt, folding the same literal twice is flagged."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        fns: List[ast.AST] = [ctx.tree]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.append(node)
        for fn in fns:
            yield from self._check_scope(ctx, fn)

    def _key_vars(self, ctx, fn) -> Set[str]:
        """Names that plausibly hold PRNG keys in this scope: parameters
        with key-ish names plus assignment targets of PRNGKey/split/
        fold_in results."""
        names: Set[str] = set()
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = fn.args
            for arg in a.posonlyargs + a.args + a.kwonlyargs:
                if _is_key_name(arg.arg):
                    names.add(arg.arg)
        for node in _walk_scope(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            path = _call_path(ctx, node.value.func) or ""
            if path in (
                "jax.random.PRNGKey", "jax.random.key",
                "jax.random.split", "jax.random.fold_in",
            ):
                for target in node.targets:
                    elts = (
                        target.elts
                        if isinstance(target, (ast.Tuple, ast.List))
                        else [target]
                    )
                    for e in elts:
                        if isinstance(e, ast.Name):
                            names.add(e.id)
        return names

    def _check_scope(self, ctx, fn) -> Iterator[Finding]:
        key_vars = self._key_vars(ctx, fn)
        if not key_vars:
            return
        # statement-ordered linear scan: consumption marks the var dirty,
        # any reassignment of the var resets it
        consumed: Dict[str, int] = {}
        fold_literals: Dict[Tuple[str, object], int] = {}

        class _V(ast.NodeVisitor):
            def __init__(self, outer):
                self.findings: List[Finding] = []
                self.outer = outer

            def visit_FunctionDef(self, node):  # separate scope
                return

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                return

            def visit_Call(self, node):
                path = _call_path(ctx, node.func) or ""
                if path.startswith("jax.random.") and node.args:
                    arg0 = node.args[0]
                    op = path.rsplit(".", 1)[1]
                    if isinstance(arg0, ast.Name) and arg0.id in key_vars:
                        if op in _KEY_CONSUMERS:
                            prev = consumed.get(arg0.id)
                            if prev is not None:
                                self.findings.append(
                                    self.outer.finding(
                                        ctx, node,
                                        f"PRNG key `{arg0.id}` consumed "
                                        f"again by jax.random.{op} (first "
                                        f"consumed on line {prev}) without "
                                        "re-derivation: identical randomness",
                                    )
                                )
                            else:
                                consumed[arg0.id] = node.lineno
                        elif op == "fold_in" and len(node.args) > 1:
                            try:
                                lit = ast.literal_eval(node.args[1])
                            except (ValueError, SyntaxError):
                                lit = None
                            if lit is not None:
                                k = (arg0.id, lit)
                                prev = fold_literals.get(k)
                                if prev is not None:
                                    self.findings.append(
                                        self.outer.finding(
                                            ctx, node,
                                            f"`fold_in({arg0.id}, {lit!r})` "
                                            f"repeats line {prev}: both "
                                            "derive the SAME child key",
                                        )
                                    )
                                else:
                                    fold_literals[k] = node.lineno
                self.generic_visit(node)

            def visit_Assign(self, node):
                self.visit(node.value)  # RHS reads before LHS rebinds
                for target in node.targets:
                    elts = (
                        target.elts
                        if isinstance(target, (ast.Tuple, ast.List))
                        else [target]
                    )
                    for e in elts:
                        if isinstance(e, ast.Name):
                            consumed.pop(e.id, None)
                            for k in [
                                fk for fk in fold_literals if fk[0] == e.id
                            ]:
                                fold_literals.pop(k, None)

            def visit_If(self, node):
                # branches are mutually exclusive draws, not reuse: run each
                # with its own state and merge only the fall-through paths
                self.visit(node.test)
                base = (dict(consumed), dict(fold_literals))
                taken = []
                for branch in (node.body, node.orelse):
                    consumed.clear()
                    consumed.update(base[0])
                    fold_literals.clear()
                    fold_literals.update(base[1])
                    for stmt in branch:
                        self.visit(stmt)
                    if not _terminates(branch):
                        taken.append(
                            (dict(consumed), dict(fold_literals))
                        )
                consumed.clear()
                fold_literals.clear()
                if taken:
                    # a key counts as consumed after the If only if EVERY
                    # fall-through branch consumed it
                    for name in set.intersection(
                        *[set(c) for c, _ in taken]
                    ):
                        consumed[name] = min(c[name] for c, _ in taken)
                    for k in set.intersection(
                        *[set(f) for _, f in taken]
                    ):
                        fold_literals[k] = min(f[k] for _, f in taken)

        def _terminates(branch) -> bool:
            return bool(branch) and isinstance(
                branch[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
            )

        visitor = _V(self)
        body = fn.body if hasattr(fn, "body") else []
        for stmt in body:
            visitor.visit(stmt)
        yield from visitor.findings


# ---------------------------------------------------------------------------
# rule: traced-branch
# ---------------------------------------------------------------------------


@register_rule
class TracedBranchRule(LintRule):
    id = "traced-branch"
    doc = (
        "A Python `if`/`while` inside a jit/vmap/lax-traced function "
        "branches on a NON-static parameter — at trace time the test is a "
        "tracer, which raises `TracerBoolConversionError` at best and "
        "silently specializes at worst.  Use `jax.lax.cond`/`jnp.where`, "
        "or move the value to `static_argnums`.  Tests on static "
        "attributes (`.ndim`, `.shape`, `.dtype`, `.size`, `len()`) and "
        "`is None` checks are exempt (those are static at trace time)."
    )

    _STATIC_ATTRS = ("ndim", "shape", "dtype", "size", "aval", "sharding")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn_node, scope in ctx.traced.items():
            if isinstance(fn_node, ast.Lambda):
                continue  # lambdas cannot contain if/while statements
            traced_params = set(scope.params) - scope.static_names
            if not traced_params:
                continue
            for node in _walk_scope(fn_node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                name = self._traced_name_in_test(node.test, traced_params)
                if name:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        ctx, node,
                        f"Python `{kind}` on traced argument `{name}` "
                        f"inside a {scope.reason}-traced function: the "
                        "test is a tracer at trace time (use lax.cond/"
                        "jnp.where or static_argnums)",
                    )

    def _traced_name_in_test(self, test, traced) -> Optional[str]:
        # `x is None` / `x is not None`: static pytree-structure checks
        if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            return None
        banned_parents: Set[int] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Attribute) and node.attr in self._STATIC_ATTRS:
                for sub in ast.walk(node.value):
                    banned_parents.add(id(sub))
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("len", "isinstance", "getattr", "hasattr")
            ):
                for sub in ast.walk(node):
                    banned_parents.add(id(sub))
        for node in ast.walk(test):
            if (
                isinstance(node, ast.Name)
                and node.id in traced
                and id(node) not in banned_parents
            ):
                return node.id
        return None


# ---------------------------------------------------------------------------
# rule: static-args
# ---------------------------------------------------------------------------


@register_rule
class StaticArgsRule(LintRule):
    id = "static-args"
    doc = (
        "`static_argnums`/`static_argnames` declared with non-int/str "
        "literals, or a locally-visible call that passes an array-valued "
        "or unhashable (list/dict/set literal, `np.array(...)`, "
        "`jnp.asarray(...)`) argument in a static position — jit hashes "
        "static arguments, so these fail with `Non-hashable static "
        "arguments` or, worse, retrace per call."
    )

    _ARRAY_CALLS = (
        "numpy.array", "numpy.asarray", "numpy.zeros", "numpy.ones",
        "jax.numpy.array", "jax.numpy.asarray", "jax.numpy.zeros",
        "jax.numpy.ones", "jax.numpy.arange",
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        jitted_static: Dict[str, List[int]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_path(ctx, node.func) != "jax.jit":
                continue
            for kw in node.keywords:
                if kw.arg not in ("static_argnums", "static_argnames"):
                    continue
                want = int if kw.arg == "static_argnums" else str
                try:
                    val = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    continue
                items = val if isinstance(val, (tuple, list)) else (val,)
                bad = [
                    i for i in items
                    if not isinstance(i, want) or isinstance(i, bool)
                ]
                if bad:
                    yield self.finding(
                        ctx, kw.value,
                        f"{kw.arg} must be {want.__name__} literals; got "
                        f"{bad!r}",
                    )
                elif kw.arg == "static_argnums":
                    # remember positions for the local call-site check
                    parent = ctx.parents.get(node)
                    if isinstance(parent, ast.Assign):
                        for t in parent.targets:
                            if isinstance(t, ast.Name):
                                jitted_static[t.id] = [
                                    i for i in items if isinstance(i, int)
                                ]
        if not jitted_static:
            return
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted_static
            ):
                continue
            for pos in jitted_static[node.func.id]:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                reason = None
                if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    reason = "an unhashable container literal"
                elif (
                    isinstance(arg, ast.Call)
                    and (_call_path(ctx, arg.func) or "") in self._ARRAY_CALLS
                ):
                    reason = "an array value"
                if reason:
                    yield self.finding(
                        ctx, arg,
                        f"argument {pos} of `{node.func.id}` is static "
                        f"(static_argnums) but receives {reason}: jit "
                        "hashes static args",
                    )


# ---------------------------------------------------------------------------
# rule: jit-mutable-closure
# ---------------------------------------------------------------------------


@register_rule
class JitMutableClosureRule(LintRule):
    id = "jit-mutable-closure"
    doc = (
        "A traced function closes over state that is mutated: a "
        "module-level list/dict/set literal, a name `.append`/`.update`/"
        "`.extend`-mutated or item-assigned in the enclosing scope, or a "
        "name REBOUND after the traced def.  jit captures closures as "
        "trace-time constants — later mutations are silently invisible "
        "to the compiled program (stale-constant bugs)."
    )

    _MUTATORS = ("append", "extend", "update", "add", "insert", "pop",
                 "setdefault", "clear", "remove")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        module_mutables: Set[str] = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, (ast.List, ast.Dict, ast.Set)
            ):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and not t.id.isupper():
                        module_mutables.add(t.id)
        for fn_node, scope in ctx.traced.items():
            if isinstance(fn_node, ast.Lambda):
                continue
            local = self._bound_names(fn_node)
            loads = self._loaded_names(fn_node)
            free = loads - local - set(scope.params) - set(
                ctx.imports.aliases
            ) - {"self", "cls"}
            if not free:
                continue
            enclosing = ctx.enclosing_function(fn_node)
            mutated = self._mutations(ctx, enclosing, fn_node)
            for name in sorted(free):
                if name in module_mutables:
                    yield self.finding(
                        ctx, fn_node,
                        f"traced function `{getattr(fn_node, 'name', '?')}` "
                        f"closes over module-level mutable `{name}`: jit "
                        "freezes it at trace time",
                    )
                elif name in mutated:
                    yield self.finding(
                        ctx, fn_node,
                        f"traced function `{getattr(fn_node, 'name', '?')}` "
                        f"closes over `{name}`, which is "
                        f"{mutated[name]} in the enclosing scope: the "
                        "compiled program keeps the trace-time value",
                    )

    def _bound_names(self, fn) -> Set[str]:
        out: Set[str] = set()
        for node in _walk_scope(fn):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                out.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.add(node.name)
            elif isinstance(node, ast.comprehension):
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def _loaded_names(self, fn) -> Set[str]:
        # walk the BODY only: names in the def's own decorators/argument
        # defaults are evaluated at def time (the `body(..., t=tables)`
        # capture-by-value idiom), not closure reads
        out: Set[str] = set()
        for stmt in fn.body:
            for node in ast.walk(stmt):  # nested defs DO read the closure
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load
                ):
                    out.add(node.id)
        return out

    def _mutations(self, ctx, enclosing, fn_node) -> Dict[str, str]:
        """Names mutated/rebound in the enclosing function scope, with a
        human-readable description.  Rebinds BEFORE the def are ordinary
        setup, only later ones invalidate the captured value."""
        out: Dict[str, str] = {}
        if enclosing is None:
            return out
        def_line = fn_node.lineno
        for node in _walk_scope(enclosing):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in self._MUTATORS
                and isinstance(node.func.value, ast.Name)
            ):
                out[node.func.value.id] = (
                    f"`.{node.func.attr}()`-mutated (line {node.lineno})"
                )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                if node.lineno <= def_line:
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Subscript) and isinstance(
                        t.value, ast.Name
                    ):
                        out[t.value.id] = (
                            f"item-assigned (line {node.lineno})"
                        )
                    elif isinstance(t, ast.Name) and isinstance(
                        node, ast.AugAssign
                    ):
                        out[t.id] = f"rebound (line {node.lineno})"
                    elif isinstance(t, ast.Name) and node.lineno > def_line:
                        out.setdefault(
                            t.id, f"rebound after the def (line {node.lineno})"
                        )
        return out


# ---------------------------------------------------------------------------
# rule: unfenced-blocking-read
# ---------------------------------------------------------------------------

#: modules that ARE the fence implementation: reads there are the
#: measurement, not a hazard
_FENCE_MODULES = (
    "spark_ensemble_tpu/telemetry/",
    "spark_ensemble_tpu/utils/instrumentation.py",
    "spark_ensemble_tpu/utils/profiling.py",
)

#: calls whose results live on device — wrapping them directly in a host
#: conversion is a synchronous device->host fetch
_DEVICE_PRODUCERS = ("predict", "predict_proba", "predict_raw")


@register_rule
class UnfencedBlockingReadRule(LintRule):
    id = "unfenced-blocking-read"
    doc = (
        "A blocking device read — `jax.block_until_ready`, "
        "`.block_until_ready()`, `jax.device_get`, a bare concurrent-"
        "futures `.result()` join (the data plane's shard waits), or "
        "`np.asarray`/`float`/`int` wrapped directly around a "
        "`.predict*()` or `jax.random.*` result — outside a timed "
        "fence.  Unfenced reads "
        "serialize the host against the device inside the dispatch "
        "window, the stall the lookahead pipeline (execution.py) exists "
        "to hide, and unmeasured ones corrupt the `host_blocked_us` "
        "accounting.  A read is fenced when it sits between a "
        "`t = time.perf_counter()` assignment and a "
        "`time.perf_counter() - t` readout in the same function, inside "
        "a `with telem.span(...)` block, or is charged via "
        "`FitTelemetry.blocking_read`/`host_blocked`.  The telemetry and "
        "instrumentation modules (the fence implementation) are exempt."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        rel = ctx.relpath.replace("\\", "/")
        if any(rel.startswith(m) or rel == m for m in _FENCE_MODULES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            desc = self._blocking_desc(ctx, node)
            if desc is None:
                continue
            if self._is_fenced(ctx, node):
                continue
            yield self.finding(
                ctx, node,
                f"unfenced blocking device read ({desc}): wrap in a "
                "perf_counter fence / telem.span, charge it via "
                "FitTelemetry.blocking_read, or suppress with a reason",
            )

    def _blocking_desc(self, ctx, node: ast.Call) -> Optional[str]:
        path = _call_path(ctx, node.func)
        if path in ("jax.block_until_ready", "jax.device_get"):
            return path
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
        ):
            return ".block_until_ready()"
        # a bare concurrent-futures join: `<fut>.result()` with no timeout
        # parks the host exactly like a device read (the prefetcher's
        # shard waits, data/prefetch.py); timeout-bounded joins in tools
        # and tests are outside the dispatch-window hazard
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "result"
            and not node.args
            and not node.keywords
        ):
            return ".result() future join"
        # host conversion wrapped DIRECTLY around a device-producing call
        if path in ("numpy.asarray", "numpy.array", "float", "int", "bool"):
            for arg in node.args[:1]:
                inner = arg
                # peel one conversion layer: float(np.mean(np.asarray(...)))
                if inner is not None and isinstance(inner, ast.Call):
                    ipath = _call_path(ctx, inner.func) or ""
                    if isinstance(
                        inner.func, ast.Attribute
                    ) and inner.func.attr in _DEVICE_PRODUCERS:
                        return f"host conversion of `.{inner.func.attr}()`"
                    if ipath.startswith("jax.random."):
                        return f"host conversion of `{ipath}`"
        return None

    def _is_fenced(self, ctx, node) -> bool:
        fn = ctx.enclosing_function(node)
        if fn is None:
            return False
        # inside `with <x>.span(...)` / `with <x>.blocking_read(...)`
        cur = ctx.parents.get(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    e = item.context_expr
                    if (
                        isinstance(e, ast.Call)
                        and isinstance(e.func, ast.Attribute)
                        and e.func.attr in ("span", "blocking_read")
                    ):
                        return True
            cur = ctx.parents.get(cur)
        # timed fence: a perf_counter assignment at-or-above the read and
        # a `perf_counter() - t` readout at-or-below it
        line = node.lineno
        starts: List[int] = []
        ends: List[int] = []
        charges: List[int] = []
        for sub in _walk_scope(fn):
            if isinstance(sub, ast.Assign) and isinstance(
                sub.value, ast.Call
            ):
                if _call_path(ctx, sub.value.func) == "time.perf_counter":
                    starts.append(sub.lineno)
            elif isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Sub):
                if (
                    isinstance(sub.left, ast.Call)
                    and _call_path(ctx, sub.left.func) == "time.perf_counter"
                ):
                    ends.append(sub.lineno)
            elif isinstance(sub, ast.Call) and isinstance(
                sub.func, ast.Attribute
            ):
                # a telem.blocking_read/host_blocked/round_chunk(fence=...)
                # call: the wait is charged there, so reads BELOW it touch
                # already-fenced arrays and do not block
                if sub.func.attr in (
                    "blocking_read", "host_blocked", "round_chunk"
                ):
                    charges.append(sub.lineno)
        if any(c <= line for c in charges):
            return True
        return any(s <= line for s in starts) and any(
            e >= line for e in ends
        )


# ---------------------------------------------------------------------------
# rule: f64-upcast
# ---------------------------------------------------------------------------


@register_rule
class F64UpcastRule(LintRule):
    id = "f64-upcast"
    doc = (
        "An explicit float64 on the device path — `jnp.float64`, a jnp "
        "constructor with `dtype` float64/'float64', `.astype(jnp."
        "float64)`, or `jax.config.update('jax_enable_x64', True)` — "
        "violating the package's f32 dtype policy (every kernel, packed "
        "model and histogram is f32; a single f64 literal silently "
        "doubles bandwidth or fails under the default x64-disabled "
        "config).  Host-side `np.float64` accounting is exempt."
    )

    _JNP_CONSTRUCTORS = (
        "jax.numpy.array", "jax.numpy.asarray", "jax.numpy.zeros",
        "jax.numpy.ones", "jax.numpy.full", "jax.numpy.arange",
        "jax.numpy.linspace", "jax.numpy.empty",
    )

    def _is_f64(self, ctx, node) -> bool:
        if isinstance(node, ast.Constant) and node.value in (
            "float64", "f64", "double"
        ):
            return True
        path = _call_path(ctx, node)
        return path in ("jax.numpy.float64", "numpy.float64") and (
            path == "jax.numpy.float64"
        )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            path = _call_path(ctx, node) if isinstance(
                node, (ast.Attribute, ast.Name)
            ) else None
            if path == "jax.numpy.float64":
                parent = ctx.parents.get(node)
                yield self.finding(
                    ctx, parent if parent is not None else node,
                    "`jnp.float64` violates the f32 dtype policy "
                    "(docs/overview.md): device arrays are f32 end-to-end",
                )
            if not isinstance(node, ast.Call):
                continue
            cpath = _call_path(ctx, node.func) or ""
            if cpath in self._JNP_CONSTRUCTORS:
                for kw in node.keywords:
                    if kw.arg == "dtype" and self._is_f64(ctx, kw.value):
                        yield self.finding(
                            ctx, node,
                            f"`{cpath.replace('jax.numpy', 'jnp')}` with a "
                            "float64 dtype: f32 policy violation",
                        )
                for arg in node.args[1:]:
                    if self._is_f64(ctx, arg):
                        yield self.finding(
                            ctx, node,
                            f"`{cpath.replace('jax.numpy', 'jnp')}` with a "
                            "float64 dtype: f32 policy violation",
                        )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and node.args
                and self._is_f64(ctx, node.args[0])
            ):
                yield self.finding(
                    ctx, node,
                    "`.astype(float64)` on the device path: f32 policy "
                    "violation",
                )
            elif (
                cpath == "jax.config.update"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "jax_enable_x64"
                and len(node.args) > 1
                and isinstance(node.args[1], ast.Constant)
                and node.args[1].value
            ):
                yield self.finding(
                    ctx, node,
                    "enabling jax_enable_x64 flips every default dtype to "
                    "f64: forbidden by the f32 policy",
                )


# ---------------------------------------------------------------------------
# rule: host-call-in-jit
# ---------------------------------------------------------------------------


@register_rule
class HostCallInJitRule(LintRule):
    id = "host-call-in-jit"
    doc = (
        "A host-side call — `time.time`/`time.perf_counter`, "
        "`np.random.*`, stdlib `random.*`, `os.environ` reads, `print`, "
        "`datetime.now` — inside a traced function.  These execute ONCE "
        "at trace time and bake their value into the compiled program: a "
        "timestamp never advances, 'randomness' repeats per call, env "
        "flips are ignored.  Resolve host values before the jit boundary "
        "and pass them as arguments (jax.debug.print is the traced-safe "
        "print)."
    )

    _BANNED_PREFIXES = (
        "time.", "numpy.random.", "random.", "os.environ", "os.getenv",
        "datetime.",
    )
    _BANNED_EXACT = ("print", "input", "open")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn_node, scope in ctx.traced.items():
            for node in ast.walk(fn_node):
                if not isinstance(node, ast.Call):
                    continue
                path = _call_path(ctx, node.func) or ""
                hit = None
                if path in self._BANNED_EXACT:
                    hit = path
                else:
                    for pre in self._BANNED_PREFIXES:
                        if path.startswith(pre):
                            hit = path
                            break
                if hit:
                    yield self.finding(
                        ctx, node,
                        f"host call `{hit}` inside a {scope.reason}-traced "
                        "function runs ONCE at trace time (its result is a "
                        "baked-in constant); hoist it out of the traced "
                        "scope",
                    )


# ---------------------------------------------------------------------------
# rule: unclosed-span
# ---------------------------------------------------------------------------

#: call names that start a telemetry span (telemetry/trace.py)
_SPAN_STARTERS = ("begin_span", "start_span", "trace_span")


@register_rule
class UnclosedSpanRule(LintRule):
    id = "unclosed-span"
    doc = (
        "A telemetry span is started (`begin_span`/`start_span`/"
        "`trace_span`) but its end is not syntactically guaranteed: the "
        "result is discarded as a bare statement, or bound to a local "
        "name that is neither entered as `with <name>:` nor `.end()`-ed "
        "inside a `try/finally` in the same function.  A span that can "
        "skip its `end()` on an exception path never emits — the trace "
        "silently loses the exact unit of work that failed "
        "(docs/tracing.md).  Handing the span to another call, returning "
        "it, storing it in a container/attribute, or using the `with` "
        "form are all fine — ownership moved somewhere that ends it."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            else:
                continue
            if fname not in _SPAN_STARTERS:
                continue
            msg = self._classify(ctx, node)
            if msg is not None:
                yield self.finding(ctx, node, msg)

    def _classify(self, ctx, call: ast.Call) -> Optional[str]:
        """None == the span's end is guaranteed (or ownership moved);
        a message == flag it.  Conservative: only the two provably-leaky
        shapes (dropped result, local bind with no with/finally end) are
        flagged."""
        cur: ast.AST = call
        parent = ctx.parents.get(cur)
        while parent is not None:
            if isinstance(parent, ast.withitem):
                return None  # `with ...begin_span(...):` — exit guaranteed
            if isinstance(parent, ast.Call) and cur is not parent.func:
                return None  # handed to another call (append, ctor, ...)
            if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
                return None  # ownership transferred to the caller
            if isinstance(parent, ast.Expr):
                return (
                    "span started and immediately discarded: nothing can "
                    "ever end it (use `with`, or bind it and end in a "
                    "finally)"
                )
            if isinstance(parent, ast.Assign):
                return self._check_assign(ctx, parent, call)
            if isinstance(
                parent,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.Module),
            ):
                return None
            cur, parent = parent, ctx.parents.get(parent)
        return None

    def _check_assign(self, ctx, assign: ast.Assign, call) -> Optional[str]:
        names: List[str] = []
        for t in assign.targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            else:
                # attribute/subscript/tuple target: the span lives in a
                # structure whose owner is responsible for ending it
                return None
        fn = ctx.enclosing_function(call) or ctx.tree
        for name in names:
            if self._end_guaranteed(fn, name):
                return None
        name = names[0] if names else "?"
        return (
            f"span bound to `{name}` with no guaranteed end in this "
            f"function: enter it (`with {name}:`) or call `{name}.end()` "
            "inside a try/finally"
        )

    @staticmethod
    def _end_guaranteed(fn, name: str) -> bool:
        for sub in _walk_scope(fn):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    e = item.context_expr
                    if isinstance(e, ast.Name) and e.id == name:
                        return True
            elif isinstance(sub, ast.Try):
                for stmt in sub.finalbody:
                    for n in ast.walk(stmt):
                        if (
                            isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)
                            and n.func.attr in ("end", "__exit__")
                            and isinstance(n.func.value, ast.Name)
                            and n.func.value.id == name
                        ):
                            return True
        return False
