"""``graftlint`` command line: both tiers, one exit code.

Exit 0 iff the repo is clean — zero unsuppressed tier-1 findings and
(with ``--contracts``) zero contract violations.  Suppressed findings
are listed (with their justifications) but never fail the run; the
``--jsonl`` artifact carries every finding, suppressed or not, in the
flat-record shape ``tools/telemetry_report.py`` reads.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description=(
            "JAX-aware static analysis for spark_ensemble_tpu: AST lint "
            "(tier 1) + traced program contracts (tier 2)."
        ),
    )
    p.add_argument(
        "targets",
        nargs="*",
        help="files/directories to lint (default: the package, tools/, "
        "bench.py, __graft_entry__.py)",
    )
    p.add_argument(
        "--select",
        action="append",
        metavar="RULE",
        help="run only these rule ids (repeatable)",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalogue (id + doc) and exit",
    )
    p.add_argument(
        "--jsonl",
        metavar="PATH",
        help="write every finding (and contract violation) as JSONL",
    )
    p.add_argument(
        "--no-lint",
        action="store_true",
        help="skip tier 1 (contracts only)",
    )
    p.add_argument(
        "--contracts",
        action="store_true",
        help="also run tier 2: trace fit/predict of every family + the "
        "serving warmup and check budgets against analysis/contracts.json",
    )
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="re-trace and rewrite analysis/contracts.json, then exit",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="list suppressed findings with their justifications",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    from spark_ensemble_tpu.analysis import lint as lint_mod

    if args.list_rules:
        for rule_id, rule in sorted(lint_mod.all_rules().items()):
            print(f"{rule_id}")
            print(f"    {rule.doc}")
        return 0

    if args.update_baseline:
        from spark_ensemble_tpu.analysis import contracts as contracts_mod

        base = contracts_mod.update_baseline()
        print(
            f"wrote {contracts_mod._BASELINE_PATH} "
            f"({len(base['entry_points'])} entry points)"
        )
        for entry, n in sorted(base["entry_points"].items()):
            print(f"  {entry}: {n} programs")
        return 0

    records: List[dict] = []
    failures = 0

    if not args.no_lint:
        findings = lint_mod.lint_paths(
            targets=args.targets or None, select=args.select
        )
        records.extend(f.to_record() for f in findings)
        for f in findings:
            if f.suppressed:
                if args.show_suppressed:
                    print(
                        f"{f.location()}: {f.rule} [suppressed: "
                        f"{f.justification}]"
                    )
                continue
            failures += 1
            print(f"{f.location()}:{f.col}: {f.rule}: {f.message}")
        n_sup = sum(1 for f in findings if f.suppressed)
        print(
            f"graftlint tier 1: {failures} finding(s), "
            f"{n_sup} suppressed (justified)"
        )

    if args.contracts:
        from spark_ensemble_tpu.analysis import contracts as contracts_mod

        report = contracts_mod.check_contracts()
        records.extend(v.to_record() for v in report.violations)
        for entry, n in sorted(report.budgets.items()):
            records.append(
                {"event": "contract_budget", "entry_point": entry,
                 "programs": n}
            )
        for v in report.violations:
            failures += 1
            print(f"contract {v.contract}: {v.entry_point}: {v.message}")
        for entry, why in sorted(report.skipped.items()):
            print(f"contract skipped: {entry}: {why}")
        print(
            f"graftlint tier 2: {len(report.budgets)} entry points, "
            f"{len(report.violations)} violation(s)"
        )

    if args.jsonl:
        with open(args.jsonl, "w") as f:
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")

    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
