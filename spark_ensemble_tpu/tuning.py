"""Model selection: ParamGridBuilder, CrossValidator, TrainValidationSplit.

The reference relies on Spark's tuning stack — `docs/example.md` wraps a
``BaggingClassifier`` in a ``CrossValidator`` over a ``ParamGridBuilder``
grid with a ``MulticlassClassificationEvaluator``.  This module supplies the
TPU-native equivalents over the framework's array-based estimators.

Design notes (vs Spark):
- Fold assignment is a hash-free ``jax.random.permutation`` split (Spark
  uses per-row Bernoulli hashing); folds are near-equal-sized.
- Each (param-map, fold) fit is an independent jit-compiled program run in a
  host loop — the analogue of ``CrossValidator``'s driver-side ``Future``
  pool (`parallelism` is accepted for API parity).
- Folds are **weight masks**, not row subsets: every candidate fits on the
  FULL feature matrix with held-out rows carrying ``sample_weight = 0``
  (inert in every estimator — GBM stats, boosting reweighting and bagging
  resampling all scale by the weight), and evaluates on the held-out rows
  with their true weights.  Identical shapes across folds mean every fold
  reuses the same compiled round programs AND — via ``share_binning`` —
  the same feature-binning fit context, computed once per search instead
  of once per (param-map, fold) candidate.
- ``CrossValidatorModel.avg_metrics`` matches Spark's name/meaning; the
  best map refits on the full data, like Spark.
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from spark_ensemble_tpu.evaluation import Evaluator
from spark_ensemble_tpu.models.base import (
    Estimator,
    Model,
    as_f32,
    mesh_fit_kwargs,
    shared_fit_context,
)
from spark_ensemble_tpu.params import Param, gt_eq, in_array, in_range
from spark_ensemble_tpu.telemetry.events import emit_event

logger = logging.getLogger(__name__)


class ParamGridBuilder:
    """Cartesian-product grids of estimator params (Spark ``ParamGridBuilder``)."""

    def __init__(self):
        self._grid: Dict[str, Sequence[Any]] = {}

    def add_grid(self, name: str, values: Sequence[Any]) -> "ParamGridBuilder":
        self._grid[name] = list(values)
        return self

    def base_on(self, fixed: Dict[str, Any]) -> "ParamGridBuilder":
        for name, value in fixed.items():
            self._grid[name] = [value]
        return self

    def build(self) -> List[Dict[str, Any]]:
        names = list(self._grid)
        combos = itertools.product(*(self._grid[n] for n in names))
        return [dict(zip(names, c)) for c in combos]


def _kfold_indices(n: int, num_folds: int, seed: int) -> List[np.ndarray]:
    """Shuffled, near-equal fold membership arrays (bool[n] per fold)."""
    # graftlint: ignore[unfenced-blocking-read] -- one-off fold-plan setup read before any fit dispatch
    perm = np.asarray(jax.random.permutation(jax.random.PRNGKey(seed), n))
    folds = []
    for f in range(num_folds):
        mask = np.zeros((n,), bool)
        mask[perm[f::num_folds]] = True
        folds.append(mask)
    return folds


def _full_num_classes(estimator, y):
    """Class count over the FULL label set, computed once per search: a
    fold's train split may miss the top class.  None for regressors."""
    if not getattr(estimator, "is_classifier", False):
        return None
    from spark_ensemble_tpu.models.base import infer_num_classes

    return infer_num_classes(y)


_MESH_WARNED: set = set()


def _mesh_kw(estimator, mesh):
    """See ``models.base.mesh_fit_kwargs``; tuning also warns — once per
    estimator type, not once per (map, fold) candidate — so a sweep
    silently running single-device is visible without flooding the logs."""
    kw = mesh_fit_kwargs(estimator, mesh)
    if mesh is not None and not kw:
        name = type(estimator).__name__
        if name not in _MESH_WARNED:
            _MESH_WARNED.add(name)
            logger.warning(
                "%s.fit has no mesh support; tuning runs it single-device",
                name,
            )
    return kw


def _fit_and_eval(
    estimator, pmap, evaluator, X, y, w, train_mask, eval_mask,
    num_classes=None, mesh=None,
):
    """Weight-mask fold fit: train on the FULL ``X``/``y`` with held-out
    rows zero-weighted (inert — every estimator scales its statistics by
    the sample weight), evaluate on the held-out rows with their true
    weights.  Keeping ``X`` whole keeps every candidate's input shapes —
    and its ``id(X)``-keyed shared fit context — identical across folds."""
    est = estimator.copy(**pmap)
    kw = _mesh_kw(est, mesh)
    base_w = w if w is not None else np.ones((X.shape[0],), np.float32)
    wt = np.where(train_mask, base_w, 0.0).astype(np.float32)
    if num_classes is not None:
        model = est.fit(X, y, sample_weight=wt, num_classes=num_classes, **kw)
    else:
        model = est.fit(X, y, sample_weight=wt, **kw)
    Xe, ye = X[eval_mask], y[eval_mask]
    we = w[eval_mask] if w is not None else None
    return model, evaluator.evaluate(model, Xe, ye, sample_weight=we)


class _TuningParams(Estimator):
    estimator = Param(None, is_estimator=True, doc="estimator to tune")
    evaluator = Param(
        None, is_estimator=True,
        doc="metric (RegressionEvaluator / *ClassificationEvaluator); "
        "its is_larger_better drives model selection",
    )
    estimator_param_maps = Param(
        None, doc="list of param dicts (ParamGridBuilder.build())"
    )
    parallelism = Param(1, gt_eq(1), doc="API parity; fits run back-to-back")
    seed = Param(0, doc="fold-split PRNG seed")
    share_binning = Param(
        True,
        doc="compute each base-learner family's fit context (feature "
        "binning / bin assignment) ONCE per search and reuse it across "
        "param maps, folds and the best-map refit — sound because "
        "weight-mask folds fit every candidate on the identical full X.  "
        "Toggling only skips the memoization; scores are bit-identical "
        "either way (distinct binning configs in the grid still get "
        "distinct contexts via the learner's config key)",
    )
    megabatch = Param(
        "auto", in_array(["off", "auto", "on"]),
        doc="train the sweep's (param-map, fold) candidates as vmapped "
        "megabatch dispatches instead of one fit per candidate "
        "(models/gbm_sweep.py; docs/selection.md#megabatch-sweeps).  "
        "Scores are pinned bit-identical to the sequential loop.  "
        "'auto' (default) batches GBM candidates and silently falls "
        "back to sequential fits for unsupported estimators, under "
        "a mesh, or when share_binning=False (a megabatch IS shared "
        "binning); 'on' raises instead of falling back (and allows "
        "mesh config-axis sharding, which is allclose — not bitwise); "
        "'off' pins the sequential loop",
    )

    def _maps(self) -> List[Dict[str, Any]]:
        return list(self.estimator_param_maps or [{}])

    def _binning_scope(self):
        """Context manager the search loop runs under: a shared fit-ctx
        scope when ``share_binning``, else a no-op."""
        if self.share_binning:
            return shared_fit_context()
        return contextlib.nullcontext()

    def _emit_candidate(self, mi, fi, metric, model, wall_s, megabatch):
        """Per-candidate telemetry + log line (satellite of the megabatch
        PR: sweeps used to discard everything but a logger.info)."""
        logger.info(
            "%s map %d fold %d: %.5f%s", type(self).__name__, mi, fi,
            metric, " [megabatch]" if megabatch else "",
        )
        emit_event(
            "tuning_candidate",
            path=self.telemetry_path or None,
            tuner=type(self).__name__,
            map_index=int(mi),
            fold=int(fi),
            metric=float(metric),
            rounds=int(getattr(model, "num_members", 0) or 0),
            wall_s=float(wall_s),
            megabatch=bool(megabatch),
        )

    def _candidate_metrics(
        self, X, y, w, maps, eval_masks, evaluator, k, mesh,
    ) -> np.ndarray:
        """Fit + evaluate every (param-map, fold) candidate ->
        ``metrics[map, fold]``.

        Under ``megabatch`` != 'off', candidates that share every
        structural param train as ONE vmapped program per round chunk
        (``fit_sweep``) — same member arrays bitwise, so the evaluator
        scores are bit-identical to the sequential loop (pinned by
        tests/test_megabatch.py); only fit order and wall attribution
        differ.  Structurally distinct grid entries form separate
        megabatch groups; unsupported candidates fall back to sequential
        fits ('auto') or raise ('on')."""
        mode = self.megabatch
        base_w = (
            w if w is not None else np.ones((X.shape[0],), np.float32)
        )
        metrics = np.zeros((len(maps), len(eval_masks)))
        cands = [
            (mi, fi, pmap, eval_mask)
            for fi, eval_mask in enumerate(eval_masks)
            for mi, pmap in enumerate(maps)
        ]

        def score(model, eval_mask):
            Xe, ye = X[eval_mask], y[eval_mask]
            we = w[eval_mask] if w is not None else None
            return evaluator.evaluate(model, Xe, ye, sample_weight=we)

        seq: List[tuple] = []
        groups: Dict[Any, List[tuple]] = {}
        if mode != "off" and not self.share_binning:
            # a megabatch IS shared binning — every lane trains on one
            # binned matrix — so an explicit opt-out wins over 'auto'
            if mode == "on":
                raise ValueError(
                    "megabatch='on' requires share_binning=True: every "
                    "sweep lane trains on the shared binned matrix"
                )
            mode = "off"
        if mode != "off":
            from spark_ensemble_tpu.models.gbm_sweep import (
                sweep_group_key,
                sweep_unsupported_reason,
            )

            for cand in cands:
                est = self.estimator.copy(**cand[2])
                reason = sweep_unsupported_reason(est, mesh)
                if reason is None and mode == "auto" and mesh is not None:
                    reason = (
                        "mesh sweeps stay sequential under "
                        "megabatch='auto' (config-axis sharding is "
                        "allclose, not bit-identical)"
                    )
                if reason is not None:
                    if mode == "on":
                        raise ValueError(f"megabatch='on': {reason}")
                    seq.append(cand)
                else:
                    groups.setdefault(sweep_group_key(est), []).append(
                        (cand, est)
                    )
        else:
            seq = cands

        for items in groups.values():
            from spark_ensemble_tpu.models.gbm_sweep import fit_sweep

            ests = [est for _, est in items]
            wts = [
                np.where(~cand[3], base_w, 0.0).astype(np.float32)
                for cand, _ in items
            ]
            t0 = time.perf_counter()
            models = fit_sweep(
                ests, X, y, sample_weights=wts, num_classes=k,
                mesh=mesh if mode == "on" else None,
                telemetry_path=self.telemetry_path or None,
            )
            # per-candidate wall is the batched dispatch amortized over
            # the group — the honest number; per-round device attribution
            # lives in the sweep_chunk events
            per_wall = (time.perf_counter() - t0) / max(1, len(items))
            for (cand, _), model in zip(items, models):
                mi, fi, _, eval_mask = cand
                metrics[mi, fi] = score(model, eval_mask)
                self._emit_candidate(
                    mi, fi, metrics[mi, fi], model, per_wall, True
                )

        for cand in seq:
            mi, fi, pmap, eval_mask = cand
            t0 = time.perf_counter()
            model, metric = _fit_and_eval(
                self.estimator, pmap, evaluator, X, y, w, ~eval_mask,
                eval_mask, num_classes=k, mesh=mesh,
            )
            metrics[mi, fi] = metric
            self._emit_candidate(
                mi, fi, metric, model, time.perf_counter() - t0, False
            )
        return metrics


class CrossValidator(_TuningParams):
    """k-fold CV over a param grid (Spark ``CrossValidator``)."""

    num_folds = Param(3, gt_eq(2), doc="cross-validation folds")

    def fit(self, X, y, sample_weight=None, mesh=None) -> "CrossValidatorModel":
        """Fit; ``mesh`` flows into every (param-map, fold) estimator fit,
        so each candidate trains distributed — the analogue of Spark CV
        launching cluster jobs per fold."""
        X = as_f32(np.asarray(X))  # one conversion => id-stable across fits
        y = np.asarray(y)
        w = None if sample_weight is None else np.asarray(sample_weight)
        evaluator: Evaluator = self.evaluator
        maps = self._maps()
        folds = _kfold_indices(X.shape[0], self.num_folds, self.seed)
        k = _full_num_classes(self.estimator, y)
        with self._binning_scope():
            metrics = self._candidate_metrics(
                X, y, w, maps, folds, evaluator, k, mesh,
            )
            avg = metrics.mean(axis=1)
            best_idx = int(
                np.argmax(avg) if evaluator.is_larger_better else np.argmin(avg)
            )
            best_est = self.estimator.copy(**maps[best_idx])
            best_model = best_est.fit(
                X, y, sample_weight=w, **_mesh_kw(best_est, mesh)
            )
        return CrossValidatorModel(
            best_model=best_model,
            avg_metrics=avg.tolist(),
            fold_metrics=metrics.tolist(),
            best_index=best_idx,
            **self.get_params(),
        )


class CrossValidatorModel(Model, CrossValidator):
    def __init__(
        self,
        best_model: Optional[Model] = None,
        avg_metrics: Optional[List[float]] = None,
        fold_metrics=None,
        best_index: int = 0,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.best_model = best_model
        self.avg_metrics = avg_metrics or []
        self.fold_metrics = fold_metrics or []
        self.best_index = best_index

    def predict(self, X):
        return self.best_model.predict(X)

    def predict_raw(self, X):
        return self.best_model.predict_raw(X)

    def predict_proba(self, X):
        return self.best_model.predict_proba(X)


class TrainValidationSplit(_TuningParams):
    """Single random train/validation split sweep (Spark ``TrainValidationSplit``)."""

    train_ratio = Param(
        0.75,
        in_range(0.0, 1.0, lower_inclusive=False, upper_inclusive=False),
        doc="fraction of rows in the training split",
    )

    def fit(
        self, X, y, sample_weight=None, mesh=None
    ) -> "TrainValidationSplitModel":
        """Fit; ``mesh`` flows into every candidate fit (see CrossValidator)."""
        X = as_f32(np.asarray(X))  # one conversion => id-stable across fits
        y = np.asarray(y)
        w = None if sample_weight is None else np.asarray(sample_weight)
        evaluator: Evaluator = self.evaluator
        maps = self._maps()
        n = X.shape[0]
        # graftlint: ignore[unfenced-blocking-read] -- one-off split-plan setup read before any fit dispatch
        perm = np.asarray(jax.random.permutation(jax.random.PRNGKey(self.seed), n))
        n_train = int(n * self.train_ratio)
        train_mask = np.zeros((n,), bool)
        train_mask[perm[:n_train]] = True
        eval_mask = ~train_mask
        k = _full_num_classes(self.estimator, y)
        with self._binning_scope():
            metrics = self._candidate_metrics(
                X, y, w, maps, [eval_mask], evaluator, k, mesh,
            )[:, 0]
            best_idx = int(
                np.argmax(metrics)
                if evaluator.is_larger_better
                else np.argmin(metrics)
            )
            best_est = self.estimator.copy(**maps[best_idx])
            best_model = best_est.fit(
                X, y, sample_weight=w, **_mesh_kw(best_est, mesh)
            )
        return TrainValidationSplitModel(
            best_model=best_model,
            validation_metrics=metrics.tolist(),
            best_index=best_idx,
            **self.get_params(),
        )


class TrainValidationSplitModel(Model, TrainValidationSplit):
    def __init__(
        self,
        best_model: Optional[Model] = None,
        validation_metrics: Optional[List[float]] = None,
        best_index: int = 0,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.best_model = best_model
        self.validation_metrics = validation_metrics or []
        self.best_index = best_index

    def predict(self, X):
        return self.best_model.predict(X)

    def predict_raw(self, X):
        return self.best_model.predict_raw(X)

    def predict_proba(self, X):
        return self.best_model.predict_proba(X)
