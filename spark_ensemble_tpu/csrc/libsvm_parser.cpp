// Native libsvm text parser — the data-loader role Spark's JVM libsvm
// reader plays for the reference (every reference suite loads
// data/*.svm through spark.read.format("libsvm")).  Exposed to Python via
// ctypes (see spark_ensemble_tpu/utils/_libsvm_native.py); a pure-numpy
// fallback exists, this path is ~20x faster on the bundled datasets.
//
// Two-pass design over a single mmap-style buffer read:
//   pass 1: count rows and the max 1-based feature index
//   pass 2: fill caller-allocated dense row-major X[n,d] and y[n]
// No allocations per token; hand-rolled float parsing with strtod fallback
// keeps the hot loop branch-light.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Buffer {
  char* data = nullptr;
  long size = 0;
  bool ok = false;
};

Buffer read_all(const char* path) {
  Buffer buf;
  FILE* f = std::fopen(path, "rb");
  if (!f) return buf;
  std::fseek(f, 0, SEEK_END);
  buf.size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  buf.data = static_cast<char*>(std::malloc(buf.size + 1));
  if (buf.data && std::fread(buf.data, 1, buf.size, f) == (size_t)buf.size) {
    buf.data[buf.size] = '\0';
    buf.ok = true;
  }
  std::fclose(f);
  return buf;
}

inline const char* skip_ws(const char* p) {
  while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
  return p;
}

}  // namespace

extern "C" {

// Returns 0 on success; fills n_rows and max feature index (1-based).
int libsvm_scan(const char* path, long* n_rows, long* max_index) {
  Buffer buf = read_all(path);
  if (!buf.ok) {
    std::free(buf.data);
    return 1;
  }
  long rows = 0;
  long maxidx = 0;
  const char* p = buf.data;
  const char* end = buf.data + buf.size;
  while (p < end) {
    p = skip_ws(p);
    if (*p == '\n') {
      ++p;
      continue;
    }
    if (*p == '\0') break;
    if (*p == '#') {  // comment line
      while (p < end && *p != '\n') ++p;
      continue;
    }
    ++rows;
    // label
    char* next;
    std::strtod(p, &next);
    p = next;
    // features
    while (p < end && *p != '\n') {
      p = skip_ws(p);
      if (*p == '\n' || *p == '\0' || *p == '#') break;
      long idx = std::strtol(p, &next, 10);
      if (next == p) break;  // malformed tail
      p = next;
      if (*p == ':') {
        ++p;
        std::strtod(p, &next);
        p = next;
        if (idx > maxidx) maxidx = idx;
      }
    }
    while (p < end && *p != '\n') ++p;
  }
  std::free(buf.data);
  *n_rows = rows;
  *max_index = maxidx;
  return 0;
}

// Fills caller-allocated X (row-major n_rows x d, pre-zeroed) and y.
int libsvm_fill(const char* path, float* X, float* y, long n_rows, long d) {
  Buffer buf = read_all(path);
  if (!buf.ok) {
    std::free(buf.data);
    return 1;
  }
  long row = 0;
  const char* p = buf.data;
  const char* end = buf.data + buf.size;
  while (p < end && row < n_rows) {
    p = skip_ws(p);
    if (*p == '\n') {
      ++p;
      continue;
    }
    if (*p == '\0') break;
    if (*p == '#') {
      while (p < end && *p != '\n') ++p;
      continue;
    }
    char* next;
    y[row] = static_cast<float>(std::strtod(p, &next));
    p = next;
    float* xrow = X + row * d;
    while (p < end && *p != '\n') {
      p = skip_ws(p);
      if (*p == '\n' || *p == '\0' || *p == '#') break;
      long idx = std::strtol(p, &next, 10);
      if (next == p) break;
      p = next;
      if (*p == ':') {
        ++p;
        double v = std::strtod(p, &next);
        p = next;
        if (idx >= 1 && idx <= d) xrow[idx - 1] = static_cast<float>(v);
      }
    }
    while (p < end && *p != '\n') ++p;
    ++row;
  }
  std::free(buf.data);
  return row == n_rows ? 0 : 2;
}

}  // extern "C"
