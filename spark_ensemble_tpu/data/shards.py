"""On-disk bit-packed bin shards: the out-of-core training format.

``write_shards`` bins a dataset ONCE (the same ``compute_bins`` /
``bin_features`` pair every resident fit uses) and stores the bit-packed
bin matrix (ops/binning.py ``pack_bins``) as row shards, each a
``.npz`` holding the ``u32[rows, W]`` packed words.  The directory is
sealed by a ``manifest.json`` carrying the format version, the dataset
geometry and a sha256 per file — the same versioned, atomically renamed,
hash-verified discipline as training checkpoints
(utils/checkpoint.py), so a truncated write or a stale/corrupted shard
is a hard error at ``ShardStore.open``, never silent wrong math.

The default shard height equals the stream histogram tier's chunk rows
(``stream_chunk_rows``, ops/tree.py ``_STREAM_CHUNK_ROWS``): a shard
sweep in ``data/streaming.py`` then accumulates histograms across
program calls in EXACTLY the per-chunk order of the resident
``hist="stream"`` scan, which is what makes the streaming fit
bit-identical to the resident fit on the same binned data
(tests/test_streaming.py pins it).

Only the bin matrix lives out of core — it is the round loop's dominant
operand (``n*d`` cells re-read every tree level).  Labels, weights and
carried predictions are ``O(n)`` vectors and stay resident.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_ensemble_tpu.autotune.resolve import resolve as _tuned
from spark_ensemble_tpu.ops.binning import (
    bin_features,
    compute_bins,
    pack_bins,
)
from spark_ensemble_tpu.utils.checkpoint import _file_sha256

#: on-disk format version; bumped on any layout change so an old store
#: is rejected instead of misread (mirrors _CHECKPOINT_FORMAT)
SHARD_FORMAT = 1

#: default rows per shard — MUST mirror ops/tree.py _STREAM_CHUNK_ROWS
#: (the "shard_rows" tunable's default; bit-identity with the resident
#: stream tier needs shard height == stream chunk height)
DEFAULT_SHARD_ROWS = 32768

_MANIFEST = "manifest.json"
_THRESHOLDS = "thresholds.npz"


def _sha_entry(path: str) -> Dict[str, Any]:
    return {"sha256": _file_sha256(path), "bytes": os.path.getsize(path)}


def write_shards(
    X,
    directory: str,
    *,
    max_bins: int = 64,
    shard_rows: Optional[int] = None,
    bits: int = 0,
    overwrite: bool = False,
) -> "ShardStore":
    """Bin + pack ``X`` into a sealed shard directory -> opened store.

    One pass: quantile thresholds over the full matrix (identical to the
    resident fit's ``compute_bins``), then per-shard ``bin_features`` +
    ``pack_bins`` (row-wise, so per-shard packing equals slicing a
    whole-matrix packing).  Written to a temp dir and atomically renamed
    into place; a crash mid-write leaves no half-readable store.
    """
    X = np.asarray(X, np.float32)
    if X.ndim != 2:
        raise ValueError(f"X must be 2-d, got shape {X.shape}")
    n, d = X.shape
    if shard_rows is None:
        shard_rows = min(int(_tuned("shard_rows", DEFAULT_SHARD_ROWS, n=n)), n)
    shard_rows = max(1, int(shard_rows))
    num_shards = -(-n // shard_rows)

    directory = os.path.abspath(directory)
    if os.path.exists(os.path.join(directory, _MANIFEST)) and not overwrite:
        raise FileExistsError(
            f"shard store already exists at {directory} "
            "(pass overwrite=True to replace it)"
        )
    parent = os.path.dirname(directory) or "."
    os.makedirs(parent, exist_ok=True)

    bins = compute_bins(jnp.asarray(X), max_bins)
    thresholds = np.asarray(bins.thresholds, np.float32)

    tmp = tempfile.mkdtemp(dir=parent, prefix=".shards-tmp-")
    try:
        shards: List[Dict[str, Any]] = []
        bits_resolved = None
        words_per_row = None
        for s in range(num_shards):
            lo = s * shard_rows
            hi = min(n, lo + shard_rows)
            Xb = bin_features(jnp.asarray(X[lo:hi]), bins)
            cb = pack_bins(Xb, max_bins, bits=bits)
            if bits_resolved is None:
                bits_resolved = int(cb.bits)
                words_per_row = int(cb.packed.shape[1])
            fname = f"shard-{s:05d}.npz"
            fpath = os.path.join(tmp, fname)
            np.savez(fpath, packed=np.asarray(cb.packed, np.uint32))
            shards.append(
                {"index": s, "file": fname, "rows": hi - lo, **_sha_entry(fpath)}
            )
        tpath = os.path.join(tmp, _THRESHOLDS)
        np.savez(tpath, thresholds=thresholds)
        manifest = {
            "format": SHARD_FORMAT,
            "n": n,
            "d": d,
            "max_bins": int(max_bins),
            "bits": bits_resolved,
            "words_per_row": words_per_row,
            "shard_rows": int(shard_rows),
            "thresholds": {"file": _THRESHOLDS, **_sha_entry(tpath)},
            "shards": shards,
        }
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1)
        if os.path.exists(directory):
            # overwrite: swap the old store out of the way first so the
            # final rename stays a single atomic transition
            old = tempfile.mkdtemp(dir=parent, prefix=".shards-old-")
            os.rename(directory, os.path.join(old, "store"))
            shutil.rmtree(old, ignore_errors=True)
        os.rename(tmp, directory)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return ShardStore.open(directory)


class ShardStore:
    """Read handle on a sealed shard directory (see ``write_shards``).

    ``open`` verifies the manifest's format version and every listed
    file's size + sha256 before any math runs — a shard store is trusted
    the way a checkpoint is trusted, by hash, not by mtime.
    """

    def __init__(self, directory: str, manifest: Dict[str, Any],
                 thresholds: np.ndarray,
                 verified_shards: Optional[frozenset] = None):
        self.directory = directory
        self._manifest = manifest
        self._thresholds = thresholds
        #: None = every shard verified (full open); otherwise the subset
        #: whose bytes this host checked — reads outside it are refused
        self._verified_shards = verified_shards

    # -- geometry ------------------------------------------------------
    @property
    def n(self) -> int:
        return int(self._manifest["n"])

    @property
    def d(self) -> int:
        return int(self._manifest["d"])

    @property
    def max_bins(self) -> int:
        return int(self._manifest["max_bins"])

    @property
    def bits(self) -> int:
        return int(self._manifest["bits"])

    @property
    def words_per_row(self) -> int:
        return int(self._manifest["words_per_row"])

    @property
    def shard_rows(self) -> int:
        return int(self._manifest["shard_rows"])

    @property
    def num_shards(self) -> int:
        return len(self._manifest["shards"])

    @property
    def thresholds(self) -> np.ndarray:
        """f32[d, max_bins-1] split thresholds — identical to the
        resident fit ctx's (same ``compute_bins`` over the same X)."""
        return self._thresholds

    @property
    def packed_nbytes(self) -> int:
        """Total bytes of packed bin words across all shards — the
        operand the out-of-core budget is measured against."""
        return sum(int(s["bytes"]) for s in self._manifest["shards"])

    def shard_meta(self, i: int) -> Dict[str, Any]:
        return self._manifest["shards"][i]

    @property
    def verified_shards(self) -> Optional[frozenset]:
        """Shard indices whose bytes were hash-verified at ``open``;
        ``None`` means all of them (a full open)."""
        return self._verified_shards

    # -- IO ------------------------------------------------------------
    @classmethod
    def open(
        cls,
        directory: str,
        verify: bool = True,
        shards: Optional[Sequence[int]] = None,
    ) -> "ShardStore":
        """Open a sealed store, optionally verifying only ``shards``.

        With ``shards=`` (a host opening its manifest partition), only
        the named entries plus the thresholds file pay existence/size/
        sha256 checks — a host never touches the bytes of other hosts'
        slices.  The *manifest* is still checked in full: per-entry row
        counts must tile ``n`` exactly and indices must be dense, so a
        subset open cannot disagree with the global row count or bin
        thresholds that every other host derives from the same manifest.
        Reads outside the verified subset raise.
        """
        directory = os.path.abspath(directory)
        mpath = os.path.join(directory, _MANIFEST)
        if not os.path.exists(mpath):
            raise FileNotFoundError(f"no shard manifest at {mpath}")
        with open(mpath) as f:
            manifest = json.load(f)
        fmt = manifest.get("format")
        if fmt != SHARD_FORMAT:
            raise ValueError(
                f"shard store format {fmt} unsupported "
                f"(expected {SHARD_FORMAT}); re-run write_shards"
            )
        all_shards = manifest["shards"]
        num_shards = len(all_shards)
        # manifest-internal geometry: the global n every host agrees on
        # must equal the sum of per-shard rows, laid out densely
        rows_total = 0
        for pos, ent in enumerate(all_shards):
            if int(ent["index"]) != pos:
                raise ValueError(
                    f"shard manifest entry {pos} has index {ent['index']} "
                    "— manifest is not dense; refusing to partition it"
                )
            if not 1 <= int(ent["rows"]) <= int(manifest["shard_rows"]):
                raise ValueError(
                    f"shard {pos} claims {ent['rows']} rows, outside "
                    f"[1, {manifest['shard_rows']}]"
                )
            rows_total += int(ent["rows"])
        if rows_total != int(manifest["n"]):
            raise ValueError(
                f"shard rows sum to {rows_total} but manifest n is "
                f"{manifest['n']} — global row count disagrees"
            )
        verified: Optional[frozenset] = None
        if shards is None:
            entries = list(all_shards) + [manifest["thresholds"]]
        else:
            subset = [int(i) for i in shards]
            if len(set(subset)) != len(subset):
                raise ValueError(f"duplicate shard indices in subset: {subset}")
            bad = [i for i in subset if not 0 <= i < num_shards]
            if bad:
                raise ValueError(
                    f"shard subset {bad} out of range for a "
                    f"{num_shards}-shard manifest"
                )
            entries = [all_shards[i] for i in subset] + [manifest["thresholds"]]
            verified = frozenset(subset)
        for ent in entries:
            fpath = os.path.join(directory, ent["file"])
            if not os.path.exists(fpath):
                raise FileNotFoundError(f"shard store missing {fpath}")
            size = os.path.getsize(fpath)
            if size != int(ent["bytes"]):
                raise ValueError(
                    f"shard store file {ent['file']} is {size} bytes, "
                    f"manifest says {ent['bytes']} — truncated or stale"
                )
            if verify and _file_sha256(fpath) != ent["sha256"]:
                raise ValueError(
                    f"shard store file {ent['file']} failed its sha256 "
                    "check — corrupted or tampered"
                )
        with np.load(os.path.join(directory, manifest["thresholds"]["file"])) as z:
            thresholds = np.asarray(z["thresholds"], np.float32)
        return cls(directory, manifest, thresholds, verified_shards=verified)

    def load_shard(self, i: int) -> np.ndarray:
        """Shard ``i``'s packed words, zero-padded to ``shard_rows``
        (u32[shard_rows, W]).  Zero words unpack to bin-0 rows, and every
        consumer pairs them with all-zero value channels, so the padding
        contributes exactly 0.0 to every statistic — same rule as the
        resident stream tier's row padding."""
        if self._verified_shards is not None and i not in self._verified_shards:
            raise ValueError(
                f"shard {i} is outside this handle's verified subset "
                f"(opened with shards={sorted(self._verified_shards)}); "
                "re-open with the full manifest or a wider subset"
            )
        ent = self._manifest["shards"][i]
        with np.load(os.path.join(self.directory, ent["file"])) as z:
            packed = np.asarray(z["packed"], np.uint32)
        rows = packed.shape[0]
        if rows < self.shard_rows:
            packed = np.pad(packed, ((0, self.shard_rows - rows), (0, 0)))
        return packed
