"""Out-of-core GBM training over bit-packed shard stores.

The resident ``hist="stream"`` tier (ops/tree.py ``_fit_forest_streamed``)
already computes each tree level as a ``lax.scan`` over row chunks of the
binned feature matrix — but the matrix itself lives on device.  This
module replaces that scan with a SHARD SWEEP: the packed bin matrix
stays on disk (data/shards.py), an async prefetcher (data/prefetch.py)
streams one shard ahead of the device, and each sweep step runs one
cached per-level program whose body is literally the same
``stream_level_step`` the resident scan folds — same contractions, same
precisions, same sequential accumulation order.  That is the whole
bit-identity argument: a streaming fit and a resident ``hist="stream"``
fit with ``stream_chunk_rows == shard_rows`` execute the same f32 ops on
the same operands in the same order (XLA does not reassociate f32 across
kernel boundaries), so the fitted params are EQUAL, not close
(tests/test_streaming.py pins it per family).

Program inventory per fit is fixed and small (~``2*max_depth + 5``
cached programs), independent of shard count and round count: per-shard
state (``node_all [S, R, M]`` ids, ``vals_all [S, R, M, C]`` value
channels) stays resident and programs address the current shard with a
TRACED index (``lax.dynamic_index_in_dim``) — no per-shard or per-round
retraces, which the graftlint program-contract checker budgets
(analysis/contracts.json).

Only the packed bin matrix is out of core.  Labels, weights, carried
predictions, per-shard node ids and value channels are ``O(n)`` vectors
and stay resident — the budget targets the dominant ``n*d``-scale
operand the round loop re-reads every level.

The round loop itself routes through the SAME ``_drive_rounds`` /
``RoundExecutor`` machinery as the resident fits (execution.py): chunked
dispatch, patience early-stop, checkpoint cadence, numeric-guard
recovery and chaos semantics are shared, and checkpoints are
INTERCHANGEABLE with resident ones (same fingerprint shape parts, and
the states are bit-identical anyway) — a fit killed mid-shard resumes
from the last round boundary like any other fit.
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_ensemble_tpu.models.base import (
    as_f32,
    cached_program,
    infer_num_classes,
    resolve_weights,
)
from spark_ensemble_tpu.models.dummy import DummyClassifier
from spark_ensemble_tpu.models.tree import DecisionTreeRegressor
from spark_ensemble_tpu.ops import losses as losses_mod
from spark_ensemble_tpu.ops.binning import CompressedBins, unpack_bins
from spark_ensemble_tpu.ops.linesearch import (
    brent_minimize,
    projected_newton_box,
)
from spark_ensemble_tpu.ops.tree import (
    _HIST_PRECISION,
    _routing_precision,
    Tree,
    predict_chunked_rows,
    stream_leaf_step,
    stream_leaf_values,
    stream_level_step,
    stream_level_update,
    stream_vals_prep,
)
from spark_ensemble_tpu.telemetry.events import FitTelemetry
from spark_ensemble_tpu.utils.instrumentation import Instrumentation
from spark_ensemble_tpu.utils.quantile import weighted_quantile

from spark_ensemble_tpu.data.prefetch import ShardPrefetcher

logger = logging.getLogger(__name__)

_PRECISION_LH = (jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST)


# ---------------------------------------------------------------------------
# per-level shard programs (family-agnostic: M, C ride in on shapes)
# ---------------------------------------------------------------------------


def _shard_level_prog(level: int, B: int, bits: int, d: int, prec: str):
    """One shard's contribution to level ``level``'s histograms:
    unpack in-program, route through the previous level's tables
    (``level > 0``), matmul-accumulate — the resident scan body
    (``stream_level_step``) addressed by a traced shard index."""
    stat_prec = _HIST_PRECISION[prec]
    route_prec = _routing_precision(B)
    n_nodes = 2 ** level

    def build():
        def step(acc, packed, node_all, vals_all, s, tables):
            xb = unpack_bins(
                CompressedBins(packed=packed, bits=bits, num_features=d)
            )
            nd = jax.lax.dynamic_index_in_dim(
                node_all, s, axis=0, keepdims=False
            )
            vl = jax.lax.dynamic_index_in_dim(
                vals_all, s, axis=0, keepdims=False
            )
            acc, nd = stream_level_step(
                acc, xb, nd, vl, n_nodes=n_nodes, tables=tables,
                max_bins=B, stat_prec=stat_prec, route_prec=route_prec,
            )
            node_all = jax.lax.dynamic_update_index_in_dim(
                node_all, nd, s, axis=0
            )
            return acc, node_all

        if level == 0:
            run = lambda acc, packed, node_all, vals_all, s: step(
                acc, packed, node_all, vals_all, s, None
            )
        else:
            run = lambda acc, packed, node_all, vals_all, s, bf, bt: step(
                acc, packed, node_all, vals_all, s, (bf, bt)
            )
        return jax.jit(run)

    return cached_program(
        ("stream_shard_level", level, B, bits, d, prec), build
    )


def _level_finish_prog(level: int, B: int, d: int, prec: str,
                       min_gain: float):
    """Score one level's swept histograms and write its heap rows —
    the resident path's ``stream_level_update`` behind a cached call."""
    stat_prec = _HIST_PRECISION[prec]

    def build():
        def run(H, mask, thresholds, parent_value, sf, sb, st, sg):
            fm = jnp.broadcast_to(mask[None, :], (H.shape[0], d))
            tables, parent_value, sf, sb, st, sg = stream_level_update(
                H, fm, min_gain, thresholds, B, stat_prec, level,
                parent_value, sf, sb, st, sg,
            )
            return tables[0], tables[1], parent_value, sf, sb, st, sg

        return jax.jit(run)

    return cached_program(
        ("stream_level_finish", level, B, d, prec, min_gain), build
    )


def _shard_leaf_prog(max_depth: int, B: int, bits: int, d: int, prec: str):
    """One shard's contribution to the leaf sums (``stream_leaf_step``),
    updating the resident per-shard node ids in place."""
    stat_prec = _HIST_PRECISION[prec]
    route_prec = _routing_precision(B)
    num_leaves = 2 ** max_depth

    def build():
        def run(acc, packed, node_all, vals_all, s, bf, bt):
            xb = unpack_bins(
                CompressedBins(packed=packed, bits=bits, num_features=d)
            )
            nd = jax.lax.dynamic_index_in_dim(
                node_all, s, axis=0, keepdims=False
            )
            vl = jax.lax.dynamic_index_in_dim(
                vals_all, s, axis=0, keepdims=False
            )
            acc, nd = stream_leaf_step(
                acc, xb, nd, vl, num_leaves=num_leaves, tables=(bf, bt),
                stat_prec=stat_prec, route_prec=route_prec,
            )
            node_all = jax.lax.dynamic_update_index_in_dim(
                node_all, nd, s, axis=0
            )
            return acc, node_all

        return jax.jit(run)

    return cached_program(
        ("stream_shard_leaf", max_depth, B, bits, d, prec), build
    )


def _leaf_finish_prog():
    def build():
        def run(L, parent_value, y_mean):
            return stream_leaf_values(
                L[:, :, 0], L[:, :, 1:], parent_value, y_mean
            )

        return jax.jit(run)

    return cached_program(("stream_leaf_finish",), build)


def _sweep_forest(prefetch, ctl, site, vals_p, y_mean, mask, thresholds, *,
                  max_depth, B, bits, d, prec, min_gain, dist=None):
    """Fit M trees over the shard store: ``max_depth + 1`` shard sweeps
    (one histogram sweep per level, one leaf sweep) -> ``(Tree [M, ...],
    node_all [S, R, M])``.  Mirrors ``_fit_forest_streamed`` exactly,
    with the ``lax.scan`` replaced by the prefetched shard loop.

    With ``dist`` (a ``parallel/elastic.py`` ``DistributedSweep``), the
    sweeps run mesh-wide instead — each row position folds only its
    manifest slice and positions reduce before split selection — with
    the same return contract and, under ``reduce="ordered"``,
    bit-identical outputs."""
    if dist is not None:
        return dist.sweep_forest(
            prefetch, ctl, site, vals_p, y_mean, mask, thresholds,
            max_depth=max_depth, B=B, bits=bits, d=d, prec=prec,
            min_gain=min_gain,
        )
    S, R, M, C = vals_p.shape
    num_internal = 2 ** max_depth - 1
    sf = jnp.zeros((M, num_internal), jnp.int32)
    sb = jnp.zeros((M, num_internal), jnp.int32)
    stt = jnp.zeros((M, num_internal), jnp.float32)
    sg = jnp.zeros((M, num_internal), jnp.float32)
    parent_value = y_mean[:, None, :]
    node_all = jnp.zeros((S, R, M), jnp.int32)
    best_f = best_t = None
    for level in range(max_depth):
        prog = _shard_level_prog(level, B, bits, d, prec)
        acc = jnp.zeros((M, 2 ** level, C, d, B), jnp.float32)
        for s, packed in prefetch.sweep():
            # chaos: a mid-shard kill lands between two accumulation
            # programs — resume must replay the round from its last
            # checkpoint without double-counting any shard
            ctl.preempt(f"{site}:level:{level}:shard:{s}")
            if level == 0:
                acc, node_all = prog(
                    acc, packed, node_all, vals_p, np.int32(s)
                )
            else:
                acc, node_all = prog(
                    acc, packed, node_all, vals_p, np.int32(s),
                    best_f, best_t,
                )
        fin = _level_finish_prog(level, B, d, prec, min_gain)
        best_f, best_t, parent_value, sf, sb, stt, sg = fin(
            acc, mask, thresholds, parent_value, sf, sb, stt, sg
        )
    leaf = _shard_leaf_prog(max_depth, B, bits, d, prec)
    acc = jnp.zeros((M, 2 ** max_depth, C), jnp.float32)
    for s, packed in prefetch.sweep():
        ctl.preempt(f"{site}:leaf:shard:{s}")
        acc, node_all = leaf(
            acc, packed, node_all, vals_p, np.int32(s), best_f, best_t
        )
    leaf_value = _leaf_finish_prog()(acc, parent_value, y_mean)
    tree = Tree(
        split_feature=sf, split_bin=sb, split_threshold=stt,
        leaf_value=leaf_value, split_gain=sg,
    )
    return tree, node_all


def _dir_reg_prog(n: int):
    """Per-row direction from the swept leaf ids — the single-tree
    leaf-id contraction of ``models/tree.py:_fit_and_leaf_pred``."""

    def build():
        def run(node_all, leaf_value):  # [S, R, 1], [1, L, k]
            node = node_all.reshape(-1, 1)[:n]
            lv = leaf_value[0]
            L = lv.shape[0]

            def rows(nd):
                oh = jax.nn.one_hot(nd[:, 0], L, dtype=jnp.float32)
                return jax.lax.dot_general(
                    oh, lv, (((1,), (0,)), ((), ())),
                    precision=_PRECISION_LH,
                )

            return predict_chunked_rows(rows, node, 1, L)[..., 0]

        return jax.jit(run)

    return cached_program(("stream_dir_reg", n), build)


def _dir_cls_prog(n: int):
    """Per-row, per-class-dim directions — the fused-member leaf-id
    contraction of ``models/tree.py:fit_many_and_directions``."""

    def build():
        def run(node_all, leaf_value):  # [S, R, M], [M, L, k]
            M, L = leaf_value.shape[:2]
            node = node_all.reshape(-1, M)[:n]

            def rows(nd):
                oh = jax.nn.one_hot(nd, L, dtype=jnp.float32)
                return jnp.einsum(
                    "nml,mlk->nmk", oh, leaf_value,
                    precision=_PRECISION_LH,
                )

            return predict_chunked_rows(rows, node, M, L)[..., 0]

        return jax.jit(run)

    return cached_program(("stream_dir_cls", n), build)


# ---------------------------------------------------------------------------
# shared setup
# ---------------------------------------------------------------------------


def _check_store(est, store, y):
    base = est._base().copy()
    if not isinstance(base, DecisionTreeRegressor):
        raise ValueError(
            "fit_streaming supports histogram DecisionTreeRegressor base "
            f"learners; got {type(base).__name__}"
        )
    if int(base.max_bins) != store.max_bins:
        raise ValueError(
            f"base learner max_bins={base.max_bins} does not match the "
            f"shard store's max_bins={store.max_bins}; the store's "
            "thresholds were computed at write_shards time"
        )
    if y.shape[0] != store.n:
        raise ValueError(
            f"y has {y.shape[0]} rows, shard store has {store.n}"
        )
    return base


def _emit_shard_io(telem, prefetch):
    """Per-round shard-I/O events through the fit's telemetry stream
    (tools/telemetry_report.py folds them into the shard-I/O share)."""
    if telem is None or not telem.enabled:
        prefetch.take_stats()
        return
    st = prefetch.take_stats()
    if not st["loads"]:
        return
    telem.emit(
        "shard_load", count=st["loads"], bytes=st["bytes"],
        duration_us=int(st["load_s"] * 1e6),
    )
    telem.emit(
        "shard_prefetch_hit", hits=st["hits"], misses=st["misses"],
    )
    telem.emit("shard_wait_us", wait_us=int(st["wait_s"] * 1e6))


# ---------------------------------------------------------------------------
# regressor
# ---------------------------------------------------------------------------


def fit_streaming_regressor(est, store, y, sample_weight=None, X_val=None,
                            y_val=None, mesh=None, reduce="ordered"):
    """Out-of-core ``GBMRegressor`` fit over a ``ShardStore`` — the
    streaming twin of ``GBMRegressor.fit`` (models/gbm.py), bit-identical
    to a resident ``hist="stream"`` fit with matched chunk rows.  The
    validation split (if any) stays resident (raw features).

    With ``mesh``, the shard sweeps distribute over the mesh's row
    positions (parallel/elastic.py): each position prefetches only its
    round-robin manifest slice and contributions are reduced across
    ``{dcn_data, data}`` before split selection — still bit-identical
    under ``reduce="ordered"``, allclose under ``reduce="psum"``."""
    from spark_ensemble_tpu.models.gbm import (
        GBMRegressionModel,
        _pseudo_residuals_and_weights,
        _round_cost,
        concat_pytrees,
        slice_pytree,
    )
    from spark_ensemble_tpu.robustness.chaos import controller

    y = as_f32(y)
    base = _check_store(est, store, y)
    if est.init_strategy.lower() == "base":
        raise ValueError(
            "init_strategy='base' needs resident features; use "
            "'constant' or 'zero' for streaming fits"
        )
    w = resolve_weights(y, sample_weight)
    n, d = store.n, store.d
    S, R = store.num_shards, store.shard_rows
    B, bits = store.max_bins, store.bits
    max_depth = int(base.max_depth)
    prec = str(base.hist_precision).lower()
    min_gain = float(base.min_info_gain)

    instr = Instrumentation("GBMRegressor.fit_streaming")
    instr.log_params(est.get_params())
    instr.log_dataset(n, d)
    telem = FitTelemetry.start(est, n=n, d=d)
    telem.emit(
        "streaming_config", shards=S, shard_rows=R, bits=bits,
        packed_bytes=store.packed_nbytes,
    )
    dist = None
    if mesh is not None:
        from spark_ensemble_tpu.parallel.elastic import DistributedSweep

        dist = DistributedSweep(mesh, store, reduce=reduce, telem=telem)
        dist.check_agreement()
    bag_keys, masks = est._sampling_plan(n, d)
    bag_many = est._make_bag_many_fn(n, n)
    ctl = controller()

    # placeholder features: every supported init strategy is a Dummy fit
    # that reads only (y, w) and predicts a broadcast constant
    X_ph = jnp.zeros((n, 1), jnp.float32)
    init_model = est._fit_init(X_ph, y, w)
    huber = est.loss.lower() == "huber"
    if huber:
        full_y = (
            jnp.concatenate([y, as_f32(y_val)]) if y_val is not None else y
        )
        delta = weighted_quantile(full_y, est.alpha)
    else:
        delta = jnp.asarray(0.0, jnp.float32)
    pred = init_model.predict(X_ph)
    valid_w = jnp.ones((n,), jnp.float32)
    y = jnp.asarray(y)
    w = jnp.asarray(w)
    thresholds = jnp.asarray(store.thresholds)

    updates = est.updates.lower()
    optimized = bool(est.optimized_weights)
    lr = float(est.learning_rate)
    goss = (
        (float(est.top_rate), float(est.other_rate))
        if est.sample_method.lower() == "goss"
        else None
    )
    tol = float(est.tol)
    max_iter = int(est.max_iter)
    alpha_q = float(est.alpha)
    loss_name = est.loss.lower()
    base_key = base.config_key()
    with_validation = X_val is not None

    def make_loss(delta):
        if loss_name == "huber":
            return losses_mod.HuberLoss(delta)
        return losses_mod.get_regression_loss(
            loss_name, alpha=alpha_q, quantile=alpha_q
        )

    stream_key = (
        "gbm_reg_stream", loss_name, alpha_q, updates, optimized, lr,
        goss, float(est.subsample_ratio), bool(est.replacement), tol,
        max_iter, base_key,
    )

    def build_prep():
        def run(y, w, valid_w, pred, delta, bag_w, key):
            if huber:
                delta = weighted_quantile(
                    jnp.abs(y - pred), alpha_q, weights=valid_w
                )
            loss = make_loss(delta)
            y_enc = loss.encode_label(y)
            labels, fit_w, bag_w = _pseudo_residuals_and_weights(
                loss, updates, y_enc, pred[:, None], bag_w, w,
                goss=goss, goss_key=jax.random.fold_in(key, 7),
            )
            Y = labels[:, 0][:, None, None]  # [n, 1, 1]
            wf = fit_w[:, 0][:, None]  # [n, 1]
            _, y_mean, vals = stream_vals_prep(Y, wf)
            vals_p = jnp.pad(
                vals, ((0, S * R - n), (0, 0), (0, 0))
            ).reshape(S, R, 1, 2)
            return vals_p, y_mean, bag_w, delta

        return jax.jit(run)

    def build_update():
        def run(y, pred, direction, bag_w, delta, scale):
            loss = make_loss(delta)
            y_enc = loss.encode_label(y)
            if optimized and loss_name == "squared":
                res = y - pred
                num = jnp.sum(bag_w * direction * res)
                den = jnp.sum(bag_w * direction * direction)
                alpha_opt = jnp.where(
                    den > 1e-30,
                    jnp.clip(num / jnp.maximum(den, 1e-30), 0.0, 100.0),
                    jnp.asarray(1.0, jnp.float32),
                )
            elif optimized:
                def phi(a):
                    return jnp.sum(
                        bag_w
                        * loss.loss(y_enc, (pred + a * direction)[:, None])
                    )

                alpha_opt = brent_minimize(
                    phi, 0.0, 100.0, tol=tol, max_iter=max_iter
                )
            else:
                alpha_opt = jnp.asarray(1.0, jnp.float32)
            weight = jnp.where(scale > 0, lr * alpha_opt * scale, 0.0)
            new_pred = pred + jnp.where(scale > 0, weight * direction, 0.0)
            return weight, new_pred

        return jax.jit(run)

    def build_val():
        def run(params, X_val, pred_val, weight, delta, y_val, scale):
            dir_val = base.predict_fn(params, X_val)
            new_pred_val = pred_val + jnp.where(
                scale > 0, weight * dir_val, 0.0
            )
            l = make_loss(delta)
            err = jnp.mean(
                l.loss(l.encode_label(y_val), new_pred_val[:, None])
            )
            return err, new_pred_val

        return jax.jit(run)

    prep = cached_program(stream_key + ("prep", huber, n, R), build_prep)
    upd = cached_program(stream_key + ("update",), build_update)
    valp = cached_program(stream_key + ("val",), build_val)
    dirp = _dir_reg_prog(n)
    eval_loss = cached_program(
        ("gbm_reg_eval", loss_name, alpha_q),
        lambda: jax.jit(
            lambda pred_v, delta, y_v: jnp.mean(
                make_loss(delta).loss(
                    make_loss(delta).encode_label(y_v), pred_v[:, None]
                )
            )
        ),
    )

    best = 0.0
    pred_val = None
    nv_pad = 0
    if with_validation:
        X_val = as_f32(X_val)
        y_val = as_f32(y_val)
        pred_val = init_model.predict(X_val)
        best = float(eval_loss(pred_val, delta, y_val))
        nv_pad = X_val.shape[0]

    members_chunks: List[Any] = []
    weights_chunks: List[Any] = []
    val_history: List[float] = []
    i, v = 0, 0

    # same fingerprint shape parts as the resident fit (n_pad == n): the
    # two paths produce bit-identical state, so their checkpoints are
    # interchangeable by construction
    ckpt = est._checkpointer(n, d, n, nv_pad, telem=telem)
    resumed = ckpt.load_latest()
    if resumed is not None:
        last_round, st = resumed
        detail = ckpt.last_load_detail or {}
        telem.emit(
            "resume_from_checkpoint",
            round=last_round + 1,
            source=detail.get("source", "latest"),
            fallback=bool(detail.get("fallback", False)),
        )
        i, v, best = last_round + 1, int(st["v"]), float(st["best"])
        val_history[:] = [
            float(x) for x in np.asarray(st.get("val_hist", []))
        ]
        pred = jnp.asarray(st["pred"])
        pred_val = st.get("pred_val")
        if pred_val is not None:
            pred_val = jnp.asarray(pred_val)
        members_chunks, weights_chunks = est._resume_chunks(st)
        delta = jnp.asarray(st["delta"])
        logger.info("GBMRegressor streaming resume from round %d", i)

    def save_state(round_idx, v, best):
        if not ckpt.should_save(round_idx):
            return
        ckpt.save(
            round_idx,
            {
                "v": v,
                "best": best,
                "val_hist": jnp.asarray(val_history, jnp.float32),
                "pred": pred,
                "pred_val": pred_val,
                "members_layout": est.MEMBERS_LAYOUT,
                "members": concat_pytrees(members_chunks),
                "weights": concat_pytrees(weights_chunks),
                "delta": delta,
            },
        )

    # distributed: each host prefetches only its manifest slice, as raw
    # numpy blocks (the sweep re-places them per mesh row position)
    prefetch = ShardPrefetcher(
        dist.reader() if dist is not None else store,
        telem=telem, to_device=dist is None,
    )
    try:
        def run_chunk(sl, step_scale=1.0):
            nonlocal pred, pred_val, delta
            c = sl.stop - sl.start
            bag_c = bag_many(bag_keys[sl])
            keys_c, masks_c = bag_keys[sl], masks[sl]
            params_l, weights_l, errs_l = [], [], []
            for j in range(c):
                r = sl.start + j
                scale = np.float32(step_scale)
                vals_p, y_mean, bag_w, delta = prep(
                    y, w, valid_w, pred, delta, bag_c[j], keys_c[j]
                )
                forest, node_all = _sweep_forest(
                    prefetch, ctl, f"GBMRegressor:stream_round:{r}",
                    vals_p, y_mean, masks_c[j], thresholds,
                    max_depth=max_depth, B=B, bits=bits, d=d, prec=prec,
                    min_gain=min_gain, dist=dist,
                )
                direction = dirp(node_all, forest.leaf_value)
                # unbatch M=1 — the member layout the resident fit stores
                tree = jax.tree_util.tree_map(lambda a: a[0], forest)
                weight, pred = upd(y, pred, direction, bag_w, delta, scale)
                if with_validation:
                    err, pred_val = valp(
                        tree, X_val, pred_val, weight, delta, y_val, scale
                    )
                    errs_l.append(err)
                params_l.append(tree)
                weights_l.append(weight)
                _emit_shard_io(telem, prefetch)
            params_c = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *params_l
            )
            weights_c = jnp.stack(weights_l)
            errs = jnp.stack(errs_l) if with_validation else None
            return params_c, weights_c, errs

        def snapshot():
            return pred, pred_val, delta

        def restore(snap):
            nonlocal pred, pred_val, delta
            pred, pred_val, delta = snap

        telem.phase_mark("setup")
        i, v, best = est._drive_rounds(
            ckpt, members_chunks, weights_chunks,
            run_chunk, save_state, "GBMRegressor", i, v, best,
            val_history=val_history, telem=telem,
            guard=est._numeric_guard(telem),
            snapshot=snapshot, restore=restore, n_rows=n,
            round_cost=_round_cost(base, n, d, 1),
        )
    finally:
        prefetch.close()
        if dist is not None:
            from spark_ensemble_tpu.parallel.elastic import (
                _record_fit_stats,
            )

            _record_fit_stats(dist)
    ckpt.delete()

    keep = i - v
    instr.log_outcome(rounds=i, kept_members=keep)
    all_members = concat_pytrees(members_chunks) if members_chunks else None
    all_weights = (
        jnp.concatenate(weights_chunks) if weights_chunks else None
    )
    model = GBMRegressionModel(
        params={
            "members": slice_pytree(all_members, keep) if keep > 0 else None,
            "weights": all_weights[:keep] if keep > 0 else jnp.zeros((0,)),
            "masks": masks[:keep],
            "init": init_model.params,
            "val_hist": jnp.asarray(val_history, jnp.float32)
            if with_validation
            else None,
        },
        num_features=d,
        init_model=init_model,
        num_members=keep,
        **est.get_params(),
    )
    telem.finish(model=model, rounds=i, kept_members=keep)
    return model


# ---------------------------------------------------------------------------
# classifier
# ---------------------------------------------------------------------------


def fit_streaming_classifier(est, store, y, sample_weight=None, X_val=None,
                             y_val=None, num_classes=None, mesh=None,
                             reduce="ordered"):
    """Out-of-core ``GBMClassifier`` fit over a ``ShardStore`` — the
    streaming twin of ``GBMClassifier.fit`` (single-chip path; the class
    dims fold into the shard programs' M axis like the resident fused
    forest).  ``mesh``/``reduce`` distribute the shard sweeps exactly as
    in :func:`fit_streaming_regressor`."""
    from spark_ensemble_tpu.models.gbm import (
        GBMClassificationModel,
        _pseudo_residuals_and_weights,
        _round_cost,
        concat_pytrees,
        slice_pytree,
    )
    from spark_ensemble_tpu.robustness.chaos import controller

    y = as_f32(y)
    base = _check_store(est, store, y)
    w = resolve_weights(y, sample_weight)
    n, d = store.n, store.d
    S, R = store.num_shards, store.shard_rows
    B, bits = store.max_bins, store.bits
    max_depth = int(base.max_depth)
    prec = str(base.hist_precision).lower()
    min_gain = float(base.min_info_gain)
    num_classes = infer_num_classes(y, num_classes)

    instr = Instrumentation("GBMClassifier.fit_streaming")
    instr.log_params(est.get_params())
    instr.log_dataset(n, d, num_classes)
    telem = FitTelemetry.start(
        est, n=n, d=d, num_classes=int(num_classes)
    )
    telem.emit(
        "streaming_config", shards=S, shard_rows=R, bits=bits,
        packed_bytes=store.packed_nbytes,
    )
    dist = None
    if mesh is not None:
        from spark_ensemble_tpu.parallel.elastic import DistributedSweep

        dist = DistributedSweep(mesh, store, reduce=reduce, telem=telem)
        dist.check_agreement()
    bag_keys, masks = est._sampling_plan(n, d)
    bag_many = est._make_bag_many_fn(n, n)
    ctl = controller()
    loss = est._make_loss(num_classes)
    dim = loss.dim
    y_enc = loss.encode_label(y)

    X_ph = jnp.zeros((n, 1), jnp.float32)
    init_dummy = DummyClassifier(strategy=est.init_strategy)
    init_model = init_dummy.fit(
        X_ph, y, sample_weight=w, num_classes=num_classes
    )
    if dim == 1 and num_classes == 2 and est.init_strategy.lower() == "prior":
        p1 = init_model.params["proba"][1]
        logodds = jnp.log(
            jnp.maximum(p1, 1e-30) / jnp.maximum(1.0 - p1, 1e-30)
        )
        init_raw = logodds[None]
    elif dim == 1:
        init_raw = jnp.zeros((1,), jnp.float32)
    else:
        init_raw = init_model.params["raw"]
    pred = jnp.broadcast_to(init_raw[None, :], (n, dim)).astype(jnp.float32)
    w = jnp.asarray(w)
    thresholds = jnp.asarray(store.thresholds)

    updates = est.updates.lower()
    optimized = bool(est.optimized_weights)
    lr = float(est.learning_rate)
    goss = (
        (float(est.top_rate), float(est.other_rate))
        if est.sample_method.lower() == "goss"
        else None
    )
    tol = float(est.tol)
    max_iter = int(est.max_iter)
    loss_name = est.loss.lower()
    base_key = base.config_key()
    with_validation = X_val is not None
    if with_validation:
        X_val = as_f32(X_val)
        y_enc_val = loss.encode_label(as_f32(y_val))

    stream_key = (
        "gbm_cls_stream", loss_name, num_classes, updates, optimized, lr,
        goss, float(est.subsample_ratio), bool(est.replacement), tol,
        max_iter, base_key,
    )

    def build_prep():
        def run(y_enc, w, pred, bag_w, key):
            labels, fit_w, bag_w = _pseudo_residuals_and_weights(
                loss, updates, y_enc, pred, bag_w, w,
                goss=goss, goss_key=jax.random.fold_in(key, 7),
            )
            Y = labels[:, :, None]  # [n, dim, 1]
            _, y_mean, vals = stream_vals_prep(Y, fit_w)
            vals_p = jnp.pad(
                vals, ((0, S * R - n), (0, 0), (0, 0))
            ).reshape(S, R, dim, 2)
            return vals_p, y_mean, bag_w

        return jax.jit(run)

    def build_update():
        def run(y_enc, pred, directions, bag_w, alpha_ws, scale):
            if optimized:
                def phi(a):
                    return jnp.sum(
                        bag_w
                        * loss.loss(y_enc, pred + a[None, :] * directions)
                    )

                if loss.has_hessian:
                    gh = lambda a: loss.linesearch_grad_hess(
                        y_enc, pred + a[None, :] * directions, directions,
                        bag_w,
                    )
                else:
                    gh = None
                alpha_opt = projected_newton_box(
                    phi, alpha_ws, max_iter=min(max_iter, 25), tol=tol,
                    grad_hess=gh,
                )
            else:
                alpha_opt = jnp.ones((dim,), jnp.float32)
            weight = jnp.where(scale > 0, lr * alpha_opt * scale, 0.0)
            new_pred = pred + jnp.where(
                scale > 0, weight[None, :] * directions, 0.0
            )
            alpha_carry = jnp.where(
                jnp.isfinite(alpha_opt), alpha_opt,
                jnp.ones_like(alpha_opt),
            )
            return weight, new_pred, alpha_carry

        return jax.jit(run)

    def build_val():
        def run(params, X_val, pred_val, y_enc_val, weight, scale):
            dirs_val = jax.vmap(
                lambda p: base.predict_fn(p, X_val)
            )(params).T
            new_pred_val = pred_val + jnp.where(
                scale > 0, weight[None, :] * dirs_val, 0.0
            )
            err = jnp.mean(loss.loss(y_enc_val, new_pred_val))
            return err, new_pred_val

        return jax.jit(run)

    prep = cached_program(stream_key + ("prep", n, R), build_prep)
    upd = cached_program(stream_key + ("update",), build_update)
    valp = cached_program(stream_key + ("val",), build_val)
    dirp = _dir_cls_prog(n)
    eval_loss = cached_program(
        ("gbm_cls_eval", loss_name, num_classes),
        lambda: jax.jit(
            lambda pred_v, y_enc_v: jnp.mean(loss.loss(y_enc_v, pred_v))
        ),
    )

    best = 0.0
    pred_val = None
    nv_pad = 0
    if with_validation:
        pred_val = jnp.broadcast_to(
            init_raw[None, :], (X_val.shape[0], dim)
        ).astype(jnp.float32)
        best = float(eval_loss(pred_val, y_enc_val))
        nv_pad = X_val.shape[0]

    members_chunks: List[Any] = []
    weights_chunks: List[Any] = []
    val_history: List[float] = []
    i, v = 0, 0
    alpha_ws = jnp.ones((dim,), jnp.float32)

    ckpt = est._checkpointer(n, d, num_classes, n, nv_pad, telem=telem)
    resumed = ckpt.load_latest()
    if resumed is not None:
        last_round, st = resumed
        detail = ckpt.last_load_detail or {}
        telem.emit(
            "resume_from_checkpoint",
            round=last_round + 1,
            source=detail.get("source", "latest"),
            fallback=bool(detail.get("fallback", False)),
        )
        i, v, best = last_round + 1, int(st["v"]), float(st["best"])
        val_history[:] = [
            float(x) for x in np.asarray(st.get("val_hist", []))
        ]
        if "alpha_ws" in st:
            alpha_ws = jnp.asarray(st["alpha_ws"])
        pred = jnp.asarray(st["pred"])
        pred_val = st.get("pred_val")
        if pred_val is not None:
            pred_val = jnp.asarray(pred_val)
        members_chunks, weights_chunks = est._resume_chunks(st)
        logger.info("GBMClassifier streaming resume from round %d", i)

    def save_state(round_idx, v, best):
        if not ckpt.should_save(round_idx):
            return
        ckpt.save(
            round_idx,
            {
                "v": v,
                "best": best,
                "val_hist": jnp.asarray(val_history, jnp.float32),
                "pred": pred,
                "pred_val": pred_val,
                "alpha_ws": alpha_ws,
                "members_layout": est.MEMBERS_LAYOUT,
                "members": concat_pytrees(members_chunks),
                "weights": concat_pytrees(weights_chunks),
            },
        )

    # distributed: each host prefetches only its manifest slice, as raw
    # numpy blocks (the sweep re-places them per mesh row position)
    prefetch = ShardPrefetcher(
        dist.reader() if dist is not None else store,
        telem=telem, to_device=dist is None,
    )
    try:
        def run_chunk(sl, step_scale=1.0):
            nonlocal pred, pred_val, alpha_ws
            c = sl.stop - sl.start
            bag_c = bag_many(bag_keys[sl])
            keys_c, masks_c = bag_keys[sl], masks[sl]
            params_l, weights_l, errs_l = [], [], []
            for j in range(c):
                r = sl.start + j
                scale = np.float32(step_scale)
                vals_p, y_mean, bag_w = prep(
                    y_enc, w, pred, bag_c[j], keys_c[j]
                )
                forest, node_all = _sweep_forest(
                    prefetch, ctl, f"GBMClassifier:stream_round:{r}",
                    vals_p, y_mean, masks_c[j], thresholds,
                    max_depth=max_depth, B=B, bits=bits, d=d, prec=prec,
                    min_gain=min_gain, dist=dist,
                )
                directions = dirp(node_all, forest.leaf_value)
                weight, pred, alpha_ws = upd(
                    y_enc, pred, directions, bag_w, alpha_ws, scale
                )
                if with_validation:
                    err, pred_val = valp(
                        forest, X_val, pred_val, y_enc_val, weight, scale
                    )
                    errs_l.append(err)
                params_l.append(forest)
                weights_l.append(weight)
                _emit_shard_io(telem, prefetch)
            params_c = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *params_l
            )
            weights_c = jnp.stack(weights_l)
            errs = jnp.stack(errs_l) if with_validation else None
            return params_c, weights_c, errs

        def snapshot():
            return pred, pred_val, alpha_ws

        def restore(snap):
            nonlocal pred, pred_val, alpha_ws
            pred, pred_val, alpha_ws = snap

        telem.phase_mark("setup")
        i, v, best = est._drive_rounds(
            ckpt, members_chunks, weights_chunks,
            run_chunk, save_state, "GBMClassifier", i, v, best,
            val_history=val_history, telem=telem,
            guard=est._numeric_guard(telem),
            snapshot=snapshot, restore=restore, n_rows=n,
            round_cost=_round_cost(base, n, d, dim),
        )
    finally:
        prefetch.close()
        if dist is not None:
            from spark_ensemble_tpu.parallel.elastic import (
                _record_fit_stats,
            )

            _record_fit_stats(dist)
    ckpt.delete()

    keep = i - v
    instr.log_outcome(rounds=i, kept_members=keep)
    all_members = concat_pytrees(members_chunks) if members_chunks else None
    all_weights = (
        jnp.concatenate(weights_chunks) if weights_chunks else None
    )
    model = GBMClassificationModel(
        params={
            "members": slice_pytree(all_members, keep) if keep > 0 else None,
            "weights": all_weights[:keep]
            if keep > 0
            else jnp.zeros((0, dim)),
            "masks": masks[:keep],
            "init_raw": init_raw,
            "val_hist": jnp.asarray(val_history, jnp.float32)
            if with_validation
            else None,
        },
        num_features=d,
        num_classes=num_classes,
        num_members=keep,
        dim=dim,
        **est.get_params(),
    )
    telem.finish(model=model, rounds=i, kept_members=keep)
    return model
