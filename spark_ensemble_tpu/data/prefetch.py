"""Async double-buffered shard prefetch for the streaming fit.

One worker thread reads shard files from disk while the device chews on
the current shard's histogram program — the round loop's host I/O hides
behind device compute instead of serializing with it.  The schedule is
the consumer's by construction: every sweep walks shards ``0..S-1`` in
order and sweeps repeat back-to-back (``max_depth + 1`` sweeps per
round), so the prefetcher simply keeps the next ``prefetch_depth``
indices of the cyclic order in flight.

Threading contract: the WORKER thread only touches numpy + file IO; all
JAX calls (``device_put``) and all telemetry run on the consumer thread
inside ``sweep()``.  Consumer-side waits on a not-yet-finished shard are
measured with a ``perf_counter`` fence and charged to the fit's
``host_blocked_us`` accounting (telemetry/events.py) — the sanctioned
fenced-wait shape the graftlint unfenced-blocking-read rule recognizes.

Abandon-safety: a sweep generator may die mid-round (chaos preemption,
a transient retry unwinding the dispatch).  In-flight futures are keyed
by shard INDEX, not by queue position, so the next sweep reconciles
against whatever is already loading — shard content is immutable, a
loaded shard is valid whenever it arrives.
"""

from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Iterator, Optional, Tuple

import jax
import numpy as np

from spark_ensemble_tpu.autotune.resolve import resolve as _tuned
from spark_ensemble_tpu.telemetry.events import global_metrics
from spark_ensemble_tpu.telemetry.trace import new_flow_id

#: default lookahead (shards in flight past the one being consumed) —
#: the "prefetch_depth" tunable's default (autotune/space.py)
DEFAULT_PREFETCH_DEPTH = 2


def _mirror_shard_metrics(hit: bool, nbytes: int, load_s: float,
                          wait_s: float) -> None:
    """Mirror one shard's I/O into the process-global registry
    (``telemetry.global_metrics()``) so ``MetricsRegistry.snapshot()``
    is a one-stop process view — the per-fit ``take_stats()`` ledger
    resets on read, these accumulate for the process lifetime."""
    g = global_metrics()
    g.counter("data/shard_loads").inc()
    g.counter("data/shard_bytes").inc(nbytes)
    g.counter(
        "data/shard_prefetch_hits" if hit else "data/shard_prefetch_misses"
    ).inc()
    g.histogram("data/shard_load_s").record(load_s)
    g.histogram("data/shard_wait_s").record(wait_s)


class ShardLoadError(RuntimeError):
    """A shard read failed on the prefetch worker thread.

    Worker exceptions only surface when the consumer awaits the future —
    potentially several shards after the one that broke.  This wrapper
    pins the failure to its shard index (``.shard``) and keeps the
    original exception as ``__cause__``, so a streaming-fit abort names
    the file that failed, not the shard that happened to be awaited.  A
    ``RuntimeError`` so the retry layer treats a flaky read like any
    other transient fault."""

    def __init__(self, shard: int, cause: BaseException):
        super().__init__(f"shard {shard} failed to load: {cause!r}")
        self.shard = int(shard)


class ShardPrefetcher:
    """Cyclic single-worker shard prefetcher over a ``ShardStore``."""

    def __init__(self, store, depth: Optional[int] = None, telem=None,
                 to_device: bool = True):
        self.store = store
        if depth is None:
            depth = int(_tuned("prefetch_depth", DEFAULT_PREFETCH_DEPTH,
                               n=store.n))
        self.depth = max(1, int(depth))
        self.telem = telem
        self.to_device = to_device
        self._ex = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="se-tpu-shard"
        )
        self._pending: Dict[int, Future] = {}
        self._closed = False
        self._stats = self._zero_stats()

    @staticmethod
    def _zero_stats():
        return {
            "loads": 0, "hits": 0, "misses": 0, "bytes": 0,
            "load_s": 0.0, "wait_s": 0.0,
            "errors": 0, "last_error": None,
        }

    def _read(self, s: int) -> Tuple[np.ndarray, float, float]:
        # worker thread: numpy + file IO only (no JAX, no telemetry).
        # The wall-clock start rides back so the CONSUMER can reconstruct
        # the worker's load as a span on the "se-tpu-shard" track without
        # the worker ever touching telemetry (telemetry/trace.py).
        wall0 = time.time()
        t0 = time.perf_counter()
        arr = self.store.load_shard(s)
        return arr, time.perf_counter() - t0, wall0

    def _schedule_from(self, pos: int) -> None:
        S = self.store.num_shards
        for j in range(self.depth + 1):
            if len(self._pending) > self.depth:
                break
            s = (pos + j) % S
            if s not in self._pending:
                self._pending[s] = self._ex.submit(self._read, s)

    def sweep(self) -> Iterator[Tuple[int, jax.Array]]:
        """Yield ``(shard_index, packed_words)`` for shards ``0..S-1``."""
        if self._closed:
            raise RuntimeError("prefetcher is closed")
        S = self.store.num_shards
        for pos in range(S):
            self._schedule_from(pos)
            fut = self._pending.pop(pos, None)
            if fut is None:  # pragma: no cover - reconcile safety net
                fut = self._ex.submit(self._read, pos)
            hit = fut.done()
            wait_wall0 = time.time()
            t0 = time.perf_counter()
            try:
                arr, load_s, load_wall0 = fut.result()
            except Exception as e:
                # attribute the abort to the shard that broke: the wait is
                # still charged, the failure lands in take_stats(), and the
                # consumer sees the index (not just whichever await lost)
                st = self._stats
                st["wait_s"] += time.perf_counter() - t0
                st["errors"] += 1
                st["last_error"] = f"shard {pos}: {type(e).__name__}: {e}"
                global_metrics().counter("data/shard_errors").inc()
                raise ShardLoadError(pos, e) from e
            wait_s = time.perf_counter() - t0
            st = self._stats
            st["loads"] += 1
            st["bytes"] += arr.nbytes
            st["load_s"] += load_s
            st["hits" if hit else "misses"] += 1
            st["wait_s"] += wait_s
            _mirror_shard_metrics(hit, arr.nbytes, load_s, wait_s)
            if self.telem is not None and self.telem.enabled:
                # the overlap miss the prefetcher exists to hide, charged
                # to the same host-blocked ledger as device-read fences
                self.telem.host_blocked(wait_s)
                # causal spans (docs/tracing.md): the worker's load,
                # reconstructed from its measured wall window onto the
                # worker track, and the consumer's wait — with a flow
                # arrow between them when the wait was CAUSED by the
                # load still running (a prefetch miss)
                flow = None if hit else new_flow_id()
                self.telem.emit_span(
                    "shard_load", load_wall0, load_s,
                    thread="se-tpu-shard", shard=pos, bytes=arr.nbytes,
                    flow_out=None if flow is None else [flow],
                )
                self.telem.emit_span(
                    "shard_wait", wait_wall0, wait_s,
                    shard=pos, hit=hit, flow_in=flow,
                )
            # keep the worker busy while the device consumes this shard
            self._schedule_from(pos + 1)
            if self.to_device:
                arr = jax.device_put(arr)
            yield pos, arr

    def take_stats(self) -> Dict[str, float]:
        """Counters accumulated since the last take (loads / hits /
        misses / bytes / load_s / wait_s / errors / last_error), then
        reset — the per-round shard-I/O telemetry reads this after each
        round."""
        out, self._stats = self._stats, self._zero_stats()
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            fut.cancel()
        self._pending.clear()
        self._ex.shutdown(wait=True)

    def __enter__(self) -> "ShardPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
