"""Out-of-core data plane: bit-packed shard stores + streaming fits.

``write_shards`` seals a binned, bit-packed dataset into a sha256-
manifested shard directory; ``ShardStore`` is the verified read handle;
``ShardPrefetcher`` streams shards one step ahead of the device; the
``fit_streaming`` methods on ``GBMRegressor`` / ``GBMClassifier``
(models/gbm.py) train over a store without ever materializing the
packed matrix on device — bit-identically to a resident
``hist="stream"`` fit.
"""

from spark_ensemble_tpu.data.partition import (
    PartitionedShardReader,
    ShardPartition,
    manifest_digest,
    partition_shards,
)
from spark_ensemble_tpu.data.prefetch import (
    DEFAULT_PREFETCH_DEPTH,
    ShardLoadError,
    ShardPrefetcher,
)
from spark_ensemble_tpu.data.shards import (
    DEFAULT_SHARD_ROWS,
    SHARD_FORMAT,
    ShardStore,
    write_shards,
)

__all__ = [
    "DEFAULT_PREFETCH_DEPTH",
    "DEFAULT_SHARD_ROWS",
    "PartitionedShardReader",
    "SHARD_FORMAT",
    "ShardLoadError",
    "ShardPartition",
    "ShardPrefetcher",
    "ShardStore",
    "manifest_digest",
    "partition_shards",
    "write_shards",
]
