"""Deterministic manifest partitioning: the multi-host shard layout.

A pod-scale streaming fit assigns every shard of a sealed ``ShardStore``
to exactly one *row position* of the training mesh (one slot along the
flattened ``{dcn_data, data}`` axes).  The assignment is round-robin —
shard ``s`` belongs to position ``s % W`` at local step ``s // W`` — and
is a pure function of ``(num_shards, W)``, so every host derives the
same global layout from the manifest alone, with no coordination
traffic.  ``manifest_digest`` seals the agreement: hosts exchange the
digest once per fit (parallel/elastic.py) and refuse to train against
diverging manifests.

The round-robin layout is what makes the distributed sweep *ordered*:
at step ``k`` the mesh holds shards ``k*W .. k*W+W-1``, one per
position, and the reduce program folds their contributions in position
order — i.e. in exactly the global shard order ``0..S-1`` that the
single-host sweep uses.  Because the fold order never depends on which
host owns which position, repartitioning after a preemption is
bit-invisible (see elastic.py for the full argument).

``PartitionedShardReader`` adapts a host's slice of the layout to the
``ShardPrefetcher`` duck-type (``num_shards`` / ``load_shard`` / ``n``),
yielding blocks in step-major order.  Steps past the end of the manifest
read as all-zero blocks: zero words unpack to bin-0 rows that every
consumer pairs with all-zero value channels, so ragged tails contribute
exactly ``0.0`` — the same padding rule as ``ShardStore.load_shard``.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np


def partition_shards(num_shards: int, num_parts: int, part: int) -> Tuple[int, ...]:
    """Shard indices owned by ``part`` of ``num_parts`` (round-robin).

    Deterministic and total: every shard in ``range(num_shards)`` lands
    in exactly one part.  A part may be empty when ``num_shards <
    num_parts`` — its positions then sweep only zero blocks.
    """
    if num_parts <= 0:
        raise ValueError(f"num_parts must be positive, got {num_parts}")
    if not 0 <= part < num_parts:
        raise ValueError(f"part {part} out of range for {num_parts} parts")
    return tuple(range(part, int(num_shards), num_parts))


def partition_steps(num_shards: int, num_parts: int) -> int:
    """Number of sweep steps ``K = ceil(num_shards / num_parts)`` — the
    global step count every position executes, full or not."""
    if num_parts <= 0:
        raise ValueError(f"num_parts must be positive, got {num_parts}")
    return max(1, -(-int(num_shards) // num_parts))


def manifest_digest(store) -> str:
    """sha256 hex digest of the store's canonical manifest.

    Covers the full geometry (``n``, ``d``, ``max_bins``, ``bits``,
    ``shard_rows``) plus every shard's and the thresholds file's own
    sha256 — two stores share a digest iff they describe the same binned
    dataset byte-for-byte.  This is what hosts compare before a
    distributed fit: digest agreement implies agreement on the global
    row count and bin thresholds.
    """
    canon = json.dumps(store._manifest, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def digest_words(digest: str) -> np.ndarray:
    """A sha256 hex digest as ``u32[8]`` — the wire form the agreement
    check all-gathers across the mesh (collectives move arrays, not
    strings)."""
    return np.frombuffer(bytes.fromhex(digest), dtype=np.uint32).copy()


@dataclass(frozen=True)
class ShardPartition:
    """One part's view of a partitioned manifest (pure metadata)."""

    part: int
    num_parts: int
    shards: Tuple[int, ...]
    total_shards: int
    n: int
    digest: str

    @classmethod
    def from_store(cls, store, num_parts: int, part: int) -> "ShardPartition":
        return cls(
            part=part,
            num_parts=num_parts,
            shards=partition_shards(store.num_shards, num_parts, part),
            total_shards=store.num_shards,
            n=store.n,
            digest=manifest_digest(store),
        )

    @property
    def steps(self) -> int:
        return partition_steps(self.total_shards, self.num_parts)


class PartitionedShardReader:
    """A host's slice of a partitioned store, as a prefetchable store.

    Duck-types the ``ShardStore`` surface ``ShardPrefetcher`` consumes
    (``num_shards``, ``load_shard``, ``n``).  ``positions`` are the mesh
    row positions this process owns (each one a part of the ``W``-way
    round-robin layout); blocks come out in step-major order — local
    index ``j`` maps to step ``k = j // P``, position ``positions[j % P]``
    and thus global shard ``k * W + positions[j % P]`` — which is exactly
    the order the distributed sweep feeds positions each step.  Global
    indices past the manifest end read as zero blocks (exact ``+0.0``
    contributions, see module docstring).
    """

    def __init__(self, store, positions: Sequence[int], num_parts: int):
        positions = tuple(int(p) for p in positions)
        if not positions:
            raise ValueError("PartitionedShardReader needs >= 1 position")
        for p in positions:
            if not 0 <= p < num_parts:
                raise ValueError(f"position {p} out of range for W={num_parts}")
        if len(set(positions)) != len(positions):
            raise ValueError(f"duplicate positions: {positions}")
        self.store = store
        self.positions = positions
        self.num_parts = int(num_parts)
        self.steps = partition_steps(store.num_shards, num_parts)
        #: local block count — K steps x P owned positions
        self.num_shards = self.steps * len(positions)
        #: resident-vector length: prefetch depth heuristics key on it
        self.n = store.n
        self.shard_rows = store.shard_rows
        self.words_per_row = store.words_per_row

    def global_index(self, j: int) -> int:
        """Local block ``j`` -> global shard index (may be >= the
        manifest's shard count for ragged-tail steps)."""
        k, i = divmod(int(j), len(self.positions))
        return k * self.num_parts + self.positions[i]

    def load_shard(self, j: int) -> np.ndarray:
        s = self.global_index(j)
        if s < self.store.num_shards:
            return self.store.load_shard(s)
        return np.zeros(
            (self.store.shard_rows, self.store.words_per_row), np.uint32
        )

    def local_partitions(self) -> List[ShardPartition]:
        """One ``ShardPartition`` per owned position — the metadata the
        elastic plane logs when slices move between hosts."""
        return [
            ShardPartition.from_store(self.store, self.num_parts, p)
            for p in self.positions
        ]
