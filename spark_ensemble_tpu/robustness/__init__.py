"""Fault-tolerant training runtime: numeric guards, retry/backoff,
crash-consistent resume, and a deterministic chaos-injection harness.

At production scale fits die mid-round — preemptions, transient device
errors, NaN gradients from a bad step size.  The reference has no story for
any of these (training is not even resumable there, SURVEY.md §5); XGBoost-
class systems treat recoverability as a first-class feature (arXiv
1806.11248).  This package is that feature for the four ensemble families:

- ``guards``: a fused non-finite check over each round chunk's outputs
  (member params, step sizes, losses) with a configurable ``on_nonfinite``
  policy — ``raise`` | ``skip_round`` | ``halve_step`` | ``stop_early``
  (``off`` disables the check entirely);
- ``retry``: exponential backoff + deterministic jitter around round
  dispatch and checkpoint I/O for transient ``RuntimeError``/XLA device
  errors, with ``retry`` events on the telemetry stream;
- ``validate``: fail-fast NaN/Inf input validation at ``fit()`` entry
  (``allow_nan=True`` is the escape hatch);
- ``chaos``: a deterministic fault injector (``SE_TPU_CHAOS``) for NaN
  gradients, mid-round preemption, transient errors, checkpoint corruption,
  and serving-replica faults (stall / crash / slow reply) — how all of the
  above is exercised in CI (docs/robustness.md).
"""

from spark_ensemble_tpu.robustness.chaos import (
    ChaosController,
    ChaosHostPreemption,
    ChaosPreemption,
    ChaosReplicaCrash,
    ChaosTransientError,
)
from spark_ensemble_tpu.robustness.guards import (
    NONFINITE_POLICIES,
    NonFiniteError,
    NumericGuard,
)
from spark_ensemble_tpu.robustness.retry import RetryPolicy, retry_call
from spark_ensemble_tpu.robustness.validate import validate_fit_inputs

__all__ = [
    "ChaosController",
    "ChaosHostPreemption",
    "ChaosPreemption",
    "ChaosReplicaCrash",
    "ChaosTransientError",
    "NONFINITE_POLICIES",
    "NonFiniteError",
    "NumericGuard",
    "RetryPolicy",
    "retry_call",
    "validate_fit_inputs",
]
