"""Fail-fast input validation at ``fit()`` entry.

A NaN/Inf feature or label does not crash a fit — it flows through every
round and produces a silently-NaN model, the worst possible failure mode.
The reference inherits Spark ML's behaviour (no finiteness check either);
scikit-learn's ``check_array(force_all_finite=True)`` is the precedent this
follows.  One fused jitted all-reduce over X (and y) costs a single pass
at fit entry; ``allow_nan=True`` is the escape hatch for callers who
deliberately feed NaN (e.g. future missing-value support carried them
through masks).
"""

from __future__ import annotations

_allfinite_fn = None


def _all_finite(arrs) -> bool:
    global _allfinite_fn
    if _allfinite_fn is None:
        import jax
        import jax.numpy as jnp

        def _ok(ls):
            return jnp.all(
                jnp.stack([jnp.all(jnp.isfinite(x)) for x in ls])
            )

        _allfinite_fn = jax.jit(_ok)
    return bool(_allfinite_fn(arrs))


def validate_fit_inputs(
    X,
    y=None,
    allow_nan: bool = False,
    family: str = "",
) -> None:
    """Raise ``ValueError`` when X (or y) contains NaN/Inf, unless
    ``allow_nan=True``.  Non-float inputs pass through untouched."""
    if allow_nan:
        return
    import jax.numpy as jnp

    arrs = []
    names = []
    for name, arr in (("X", X), ("y", y)):
        if arr is None:
            continue
        a = jnp.asarray(arr)
        if jnp.issubdtype(a.dtype, jnp.inexact):
            arrs.append(a)
            names.append(name)
    if not arrs:
        return
    # one fused check first (the common clean path costs a single reduce);
    # only on failure re-check per-array to name the culprit
    if _all_finite(arrs):
        return
    bad = [n for n, a in zip(names, arrs) if not _all_finite([a])]
    who = " and ".join(bad) or "input"
    prefix = f"{family}: " if family else ""
    raise ValueError(
        f"{prefix}{who} contains NaN or Inf values; ensemble fits would "
        "silently produce a non-finite model. Clean the inputs, or pass "
        "allow_nan=True to skip this check (see docs/robustness.md)."
    )
