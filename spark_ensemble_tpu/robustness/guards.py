"""Numeric guards: fused non-finite detection over per-round outputs.

A single non-finite gradient (from an overflowing line-search step, a bad
learning rate, or an injected chaos fault) silently poisons every later
round — boosting's residuals, GBM's running prediction, bagging's stacked
members.  The guard catches it the round it happens: one jitted reduction
over the chunk's outputs (member params, step sizes, losses — all carrying
a leading round axis) produces a per-round ``bool`` vector, and only that
tiny vector crosses to the host.  Cost is O(bytes already produced) fused
elementwise work per chunk — measured as ``robustness_overhead_pct`` in
bench.py and budgeted < 2%.

Recovery is policy-driven (``on_nonfinite`` estimator param):

- ``raise``    — fail fast with :class:`NonFiniteError` (default);
- ``skip_round``  — drop the poisoned round's contribution, keep going;
- ``halve_step``  — re-run the round with a halved line-search step until
  finite (GBM; families without a scalable step degrade to skip);
- ``stop_early``  — truncate the ensemble to the last good round;
- ``off``      — no check at all (opt out of the guard's cost).
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

logger = logging.getLogger("spark_ensemble_tpu")

NONFINITE_POLICIES = ("off", "raise", "skip_round", "halve_step",
                      "stop_early")


class NonFiniteError(FloatingPointError):
    """A non-finite value surfaced in a round's outputs under the
    ``on_nonfinite="raise"`` policy.  Carries ``family`` and ``round_index``
    so the failure is attributable without re-running."""

    def __init__(self, message: str, family: str = "",
                 round_index: Optional[int] = None):
        super().__init__(message)
        self.family = family
        self.round_index = round_index


def _inexact_leaves(trees):
    import jax
    import jax.numpy as jnp

    leaves = []
    for tree in trees:
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "dtype") and jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.inexact
            ):
                leaves.append(jnp.asarray(leaf))
    return leaves


_flags_fn = None


def round_nonfinite_flags(nan_leaves, strict_leaves):
    """``bool[c]`` — per-round badness over leaves that all share leading
    round axis ``c``.  ``nan_leaves`` are checked for NaN only (member
    params legitimately carry ±Inf — tree split thresholds use Inf
    sentinels for leaves/unused levels); ``strict_leaves`` (step sizes,
    losses) must be fully finite.  One fused jitted reduction; retraces
    only per distinct (length, shape) combination, which the chunk-program
    cache already bounds."""
    global _flags_fn
    if _flags_fn is None:
        import jax
        import jax.numpy as jnp

        def _flags(nan_ls, strict_ls):
            out = None
            for x in nan_ls:
                bad = jnp.any(jnp.isnan(x.reshape(x.shape[0], -1)), axis=1)
                out = bad if out is None else out | bad
            for x in strict_ls:
                bad = jnp.any(
                    ~jnp.isfinite(x.reshape(x.shape[0], -1)), axis=1
                )
                out = bad if out is None else out | bad
            return out

        _flags_fn = jax.jit(_flags)
    return _flags_fn(nan_leaves, strict_leaves)


def tree_any_nan(*trees) -> bool:
    """Host bool: any NaN anywhere in the given pytrees (whole-model check
    for families without a round axis; NaN-only for the same Inf-sentinel
    reason as :func:`round_nonfinite_flags`)."""
    leaves = _inexact_leaves(trees)
    if not leaves:
        return False
    import jax
    import jax.numpy as jnp

    bad = jax.jit(
        lambda ls: jnp.any(jnp.stack([jnp.any(jnp.isnan(x)) for x in ls]))
    )(leaves)
    return bool(bad)


class NumericGuard:
    """Per-fit guard instance: detection + policy + telemetry.

    The drivers own *recovery* (they hold the carried state to snapshot and
    replay); the guard owns detection (:meth:`first_nonfinite`,
    :meth:`member_flags`), policy validation, and the ``guard_nonfinite``
    event record.
    """

    def __init__(self, policy: str, family: str = "", telem=None,
                 max_halvings: int = 4):
        if policy not in NONFINITE_POLICIES:
            raise ValueError(
                f"on_nonfinite must be one of {NONFINITE_POLICIES}, "
                f"got {policy!r}"
            )
        self.policy = policy
        self.family = family
        self.telem = telem
        self.max_halvings = max_halvings

    @property
    def active(self) -> bool:
        return self.policy != "off"

    def first_nonfinite(self, params, *arrays) -> Optional[int]:
        """Index of the first bad round in a chunk whose trees all carry a
        leading round axis, or ``None`` when the chunk is clean.

        ``params`` (the member-params pytree) is checked for NaN only —
        tree encodings legitimately carry ±Inf split-threshold sentinels;
        ``arrays`` (step sizes, losses) must be fully finite."""
        nan_leaves = _inexact_leaves((params,))
        strict_leaves = _inexact_leaves(arrays)
        if not nan_leaves and not strict_leaves:
            return None
        flags = np.asarray(round_nonfinite_flags(nan_leaves, strict_leaves))
        idx = np.flatnonzero(flags)
        return int(idx[0]) if idx.size else None

    def member_flags(self, params, *arrays) -> Optional[np.ndarray]:
        """``bool[m]`` per-member badness flags for stacked members
        (bagging), or ``None`` when nothing to check.  Same NaN-only
        semantics for ``params`` as :meth:`first_nonfinite`."""
        nan_leaves = _inexact_leaves((params,))
        strict_leaves = _inexact_leaves(arrays)
        if not nan_leaves and not strict_leaves:
            return None
        return np.asarray(round_nonfinite_flags(nan_leaves, strict_leaves))

    def record(self, round_index: int, action: str, **extra) -> None:
        """Log + emit a ``guard_nonfinite`` telemetry event describing what
        the policy did about a detection."""
        logger.warning(
            "[%s] non-finite round output at round %d -> %s",
            self.family, round_index, action,
        )
        if self.telem is not None:
            self.telem.emit(
                "guard_nonfinite",
                round=round_index,
                policy=self.policy,
                action=action,
                **extra,
            )

    def raise_error(self, round_index: int, what: str = "round outputs"):
        self.record(round_index, "raise")
        raise NonFiniteError(
            f"non-finite {what} at round {round_index} in "
            f"{self.family or 'fit'} (on_nonfinite='raise'; see "
            "docs/robustness.md for recovery policies)",
            family=self.family,
            round_index=round_index,
        )
