"""Deterministic chaos-injection harness for the training runtime.

Fault tolerance that is never exercised is fault tolerance that does not
work.  This module injects the four failure modes the runtime defends
against — NaN gradients, mid-round preemption, transient device errors,
checkpoint corruption — at well-defined *sites* inside the fit loops, with
fully deterministic draws so a failing CI run reproduces locally from the
seed alone.

Environment contract (read once, cached):

- ``SE_TPU_CHAOS``: enables injection; an integer seed (non-numeric values
  are hashed to one).  Unset/empty → no-op controller.
- ``SE_TPU_CHAOS_FAULTS``: comma list restricting the active fault kinds
  (subset of ``nan_grad,preempt,transient,ckpt_corrupt,replica_stall,
  replica_crash,slow_reply,host_preempt,host_stall,swap_crash,scale_crash,
  refresh_crash``; default all).
- ``SE_TPU_CHAOS_RATE``: per-site firing probability (default 0.05).
- ``SE_TPU_CHAOS_LOG``: JSONL path appending one record per injected fault
  (uploaded as a CI artifact next to the telemetry stream).

Every fault fires **at most once per site** so retried/replayed work
succeeds deterministically on the second attempt, and ``preempt`` carries a
global budget (default 1) so a high rate kills a fit once, not forever.
Tests bypass the environment entirely via :func:`install`.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Dict, Iterable, Optional, Set, Tuple

logger = logging.getLogger("spark_ensemble_tpu")

FAULT_KINDS = (
    "nan_grad", "preempt", "transient", "ckpt_corrupt",
    # serving-fleet faults (fired from FleetRouter replica workers only)
    "replica_stall", "replica_crash", "slow_reply",
    # elastic-training faults (fired from the distributed sweep only):
    # host_preempt kills one whole host mid-round (survivors repartition
    # and resume); host_stall makes one host drag a sweep step — the
    # straggler the pod skew report must attribute (telemetry/podview.py)
    "host_preempt",
    "host_stall",
    # fleet control-loop faults (docs/autopilot.md): swap_crash kills a
    # replica mid-rebind during a rolling hot swap, scale_crash kills a
    # just-added replica during its warm-in, refresh_crash kills a
    # background warm-start refresh fit mid-round (the serving model must
    # stay untouched and the refresh retryable)
    "swap_crash",
    "scale_crash",
    "refresh_crash",
)


class ChaosPreemption(Exception):
    """Injected mid-round kill.  Deliberately **not** a ``RuntimeError`` so
    the retry layer never swallows it — a preemption must propagate and be
    recovered via checkpoint resume, exactly like a real SIGTERM."""


class ChaosTransientError(RuntimeError):
    """Injected transient device error; a ``RuntimeError`` on purpose so
    the retry/backoff layer treats it like a real XLA hiccup."""


class ChaosHostPreemption(Exception):
    """Injected whole-host kill during a distributed sweep.  Raised only
    on the *victim* process (survivors get ``elastic.HostLostError``
    instead); not a ``RuntimeError`` so no retry layer can swallow it —
    the victim must actually leave the mesh, exactly like a real pod
    preemption notice."""


class ChaosReplicaCrash(Exception):
    """Injected serving-replica death.  Not a ``RuntimeError`` so nothing
    between the replica worker and the fleet router can swallow it: the
    router must observe the crash, eject the replica, and replay its queue
    on a healthy one — exactly like a real worker-process kill."""


class ChaosController:
    """Deterministic per-site fault injector.

    ``seed`` fixes every draw; ``rate`` is the per-site firing probability;
    ``faults`` restricts the active kinds; ``budgets`` optionally caps the
    total firings per kind (``preempt`` defaults to 1).  A draw for a given
    ``(fault, site)`` pair is a pure function of the seed, so two runs that
    visit the same sites inject the same faults.
    """

    enabled = True

    def __init__(
        self,
        seed: int = 0,
        rate: float = 1.0,
        faults: Optional[Iterable[str]] = None,
        budgets: Optional[Dict[str, Optional[int]]] = None,
        log_path: Optional[str] = None,
    ):
        kinds = tuple(faults) if faults is not None else FAULT_KINDS
        unknown = set(kinds) - set(FAULT_KINDS)
        if unknown:
            raise ValueError(
                f"unknown chaos fault kinds {sorted(unknown)}; "
                f"expected a subset of {FAULT_KINDS}"
            )
        self.seed = int(seed)
        self.rate = float(rate)
        self.faults: Set[str] = set(kinds)
        self.budgets: Dict[str, Optional[int]] = {
            "preempt": 1,
            # one replica death per run by default: the fleet should absorb
            # a single kill; unbounded kills is a different experiment
            "replica_crash": 1,
            # likewise one host loss per run: survivors must prove one
            # clean repartition+resume, not survive a dying pod
            "host_preempt": 1,
            # one kill per control-loop experiment: the swap/scale/refresh
            # machinery must absorb a single mid-flight death and converge
            "swap_crash": 1,
            "scale_crash": 1,
            "refresh_crash": 1,
        }
        if budgets:
            self.budgets.update(budgets)
        self.log_path = log_path
        self.fired: list = []  # (fault, site) in firing order
        self._counts: Dict[str, int] = {}
        self._seen: Set[Tuple[str, str]] = set()
        self._lock = threading.Lock()

    # -- draw machinery ----------------------------------------------------

    def _draw(self, fault: str, site: str) -> float:
        """Uniform [0,1) draw, a pure function of (seed, fault, site)."""
        h = zlib.crc32(f"{self.seed}:{fault}:{site}".encode())
        return (h & 0xFFFFFFFF) / 2**32

    def _fire(self, fault: str, site: str) -> bool:
        if fault not in self.faults:
            return False
        with self._lock:
            key = (fault, site)
            if key in self._seen:
                return False  # at-most-once per site: retries succeed
            budget = self.budgets.get(fault)
            if budget is not None and self._counts.get(fault, 0) >= budget:
                return False
            if self._draw(fault, site) >= self.rate:
                return False
            self._seen.add(key)
            self._counts[fault] = self._counts.get(fault, 0) + 1
            self.fired.append(key)
        self._log(fault, site)
        return True

    def _log(self, fault: str, site: str) -> None:
        logger.warning("chaos: injecting %s at %s", fault, site)
        if not self.log_path:
            return
        rec = {"ts": time.time(), "fault": fault, "site": site,
               "seed": self.seed}
        try:
            with open(self.log_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            logger.exception("chaos: could not append to %s", self.log_path)

    def pick(self, fault: str, site: str, n: int) -> int:
        """Deterministic index in [0, n) — which round/member to poison."""
        h = zlib.crc32(f"{self.seed}:{fault}:{site}:pick".encode())
        return int(h % max(n, 1))

    # -- injection hooks (called from the runtime) -------------------------

    def transient(self, site: str) -> None:
        """Raise a retryable :class:`ChaosTransientError` (at most once per
        site, so the retry layer's second attempt succeeds)."""
        if self._fire("transient", site):
            raise ChaosTransientError(f"chaos: transient fault at {site}")

    def preempt(self, site: str) -> None:
        """Raise a :class:`ChaosPreemption` (globally budgeted; default 1)."""
        if self._fire("preempt", site):
            raise ChaosPreemption(f"chaos: preempted at {site}")

    def poison_array(self, site: str, arr):
        """Return ``arr`` with one leading-axis slice set to NaN (or ``arr``
        unchanged when the site does not fire)."""
        if arr is None or not self._fire("nan_grad", site):
            return arr
        import jax.numpy as jnp

        j = self.pick("nan_grad", site, arr.shape[0])
        return arr.at[j].set(jnp.nan)

    def poison_member_stack(self, site: str, tree):
        """Poison one stacked member: NaN the picked leading-axis index of
        the first floating leaf in ``tree``."""
        if not self._fire("nan_grad", site):
            return tree
        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        for i, leaf in enumerate(leaves):
            if hasattr(leaf, "dtype") and jnp.issubdtype(
                leaf.dtype, jnp.inexact
            ):
                j = self.pick("nan_grad", site, leaf.shape[0])
                leaves[i] = leaf.at[j].set(jnp.nan)
                break
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def poison_tree(self, site: str, tree):
        """NaN a single element of the first floating leaf of ``tree``
        (used for unstacked per-member models, e.g. stacking bases)."""
        if not self._fire("nan_grad", site):
            return tree
        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        for i, leaf in enumerate(leaves):
            if hasattr(leaf, "dtype") and jnp.issubdtype(
                leaf.dtype, jnp.inexact
            ):
                flat = jnp.ravel(leaf).at[0].set(jnp.nan)
                leaves[i] = flat.reshape(leaf.shape)
                break
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def corrupt_checkpoint(self, site: str, state_path: str) -> None:
        """Truncate a just-written ``state.json`` mid-byte, simulating a
        crash during the (non-atomic on some filesystems) write."""
        if not self._fire("ckpt_corrupt", site):
            return
        try:
            with open(state_path, "r+b") as f:
                f.truncate(max(f.seek(0, 2) // 2, 1))
        except OSError:
            logger.exception("chaos: could not corrupt %s", state_path)

    def host_preempt(self, site: str) -> bool:
        """Whether a host preemption fires at this site (globally
        budgeted; default 1).  Unlike :meth:`preempt` this returns a
        verdict instead of raising: the caller (the distributed sweep)
        must first drain in-flight collectives and resolve the victim
        via :meth:`pick` — and then raises ``ChaosHostPreemption`` on
        the victim, ``HostLostError`` on survivors.  The draw is a pure
        function of ``(seed, fault, site)``, so every host reaches the
        same verdict at the same site without communicating."""
        return self._fire("host_preempt", site)

    def host_stall_s(self, site: str, seconds: float = 0.25) -> float:
        """Seconds one host should drag a distributed sweep step —
        enough to dominate the per-round sweep wall so the pod skew
        report names the straggler deterministically, without tripping
        anything fatal.  Like :meth:`host_preempt` the verdict is
        symmetric (pure function of seed/fault/site); the caller
        resolves WHICH host sleeps via :meth:`pick` and only the victim
        does.  0.0 when the site does not fire."""
        return float(seconds) if self._fire("host_stall", site) else 0.0

    # -- serving-fleet hooks (called from FleetRouter replica workers) -----

    def stall_s(self, site: str, seconds: float = 0.25) -> float:
        """Seconds a replica worker should sleep before serving — long
        enough to trip the router's hedge timer and the breaker's slow
        streak, without killing the replica.  0.0 when the site does not
        fire (the caller skips the sleep entirely)."""
        return float(seconds) if self._fire("replica_stall", site) else 0.0

    def crash(self, site: str) -> None:
        """Raise :class:`ChaosReplicaCrash` (globally budgeted; default 1)."""
        if self._fire("replica_crash", site):
            raise ChaosReplicaCrash(f"chaos: replica crashed at {site}")

    def slow_s(self, site: str, seconds: float = 0.05) -> float:
        """Seconds of added reply latency — a degraded-but-alive replica
        (slow NIC, noisy neighbor) that should push the router toward
        hedging and prefix degradation rather than ejection."""
        return float(seconds) if self._fire("slow_reply", site) else 0.0

    # -- fleet control-loop hooks (swap / scale / refresh) -----------------

    def swap_crash(self, site: str) -> None:
        """Raise :class:`ChaosReplicaCrash` mid-rebind during a rolling
        hot swap (globally budgeted; default 1).  The router must treat it
        exactly like a replica death: eject, replay the drained queue on a
        healthy replica, and finish the swap on the survivors — every
        response still computed by exactly one model version."""
        if self._fire("swap_crash", site):
            raise ChaosReplicaCrash(f"chaos: replica crashed mid-swap at {site}")

    def scale_crash(self, site: str) -> None:
        """Raise :class:`ChaosReplicaCrash` during a scale-up warm-in
        (globally budgeted; default 1).  A replica that dies before
        admission must never have owned a request, so the fleet drops
        nothing — it just ends up one replica narrower than asked."""
        if self._fire("scale_crash", site):
            raise ChaosReplicaCrash(f"chaos: replica crashed at warm-in {site}")

    def refresh_crash(self, site: str) -> None:
        """Raise :class:`ChaosPreemption` mid-round inside a background
        warm-start refresh fit (globally budgeted; default 1).  Not a
        ``RuntimeError`` so no retry layer swallows it: the refresh dies,
        the serving model stays byte-identical, and the next refresh
        attempt succeeds (the site fires at most once)."""
        if self._fire("refresh_crash", site):
            raise ChaosPreemption(f"chaos: refresh fit killed at {site}")


class _NoopController:
    """Injection disabled: every hook is a cheap no-op/identity."""

    enabled = False
    fired: tuple = ()

    def transient(self, site: str) -> None:
        pass

    def preempt(self, site: str) -> None:
        pass

    def poison_array(self, site: str, arr):
        return arr

    def poison_member_stack(self, site: str, tree):
        return tree

    def poison_tree(self, site: str, tree):
        return tree

    def corrupt_checkpoint(self, site: str, state_path: str) -> None:
        pass

    def stall_s(self, site: str, seconds: float = 0.25) -> float:
        return 0.0

    def host_preempt(self, site: str) -> bool:
        return False

    def host_stall_s(self, site: str, seconds: float = 0.25) -> float:
        return 0.0

    def crash(self, site: str) -> None:
        pass

    def slow_s(self, site: str, seconds: float = 0.05) -> float:
        return 0.0

    def swap_crash(self, site: str) -> None:
        pass

    def scale_crash(self, site: str) -> None:
        pass

    def refresh_crash(self, site: str) -> None:
        pass


_NOOP = _NoopController()
_installed: Optional[object] = None
_env_cache: Optional[Tuple[tuple, object]] = None
_cache_lock = threading.Lock()


def install(ctrl) -> None:
    """Override the process controller (tests); ``install(None)`` reverts
    to the environment-configured one."""
    global _installed
    _installed = ctrl


def _from_env():
    raw = os.environ.get("SE_TPU_CHAOS", "").strip()
    if not raw:
        return None
    seed = int(raw) if raw.lstrip("+-").isdigit() else zlib.crc32(raw.encode())
    faults_raw = os.environ.get("SE_TPU_CHAOS_FAULTS", "").strip()
    faults = (
        tuple(p.strip() for p in faults_raw.split(",") if p.strip())
        if faults_raw
        else None
    )
    rate = float(os.environ.get("SE_TPU_CHAOS_RATE", "0.05"))
    log_path = os.environ.get("SE_TPU_CHAOS_LOG") or None
    return seed, faults, rate, log_path


def controller():
    """The active controller: an installed one, else env-configured
    (cached until the relevant env vars change), else a no-op."""
    global _env_cache
    if _installed is not None:
        return _installed
    cfg = _from_env()
    if cfg is None:
        return _NOOP
    with _cache_lock:
        if _env_cache is not None and _env_cache[0] == cfg:
            return _env_cache[1]
        seed, faults, rate, log_path = cfg
        ctrl = ChaosController(
            seed=seed, rate=rate, faults=faults, log_path=log_path
        )
        _env_cache = (cfg, ctrl)
        return ctrl
