"""Retry with exponential backoff + deterministic jitter.

Transient ``RuntimeError``/XLA device errors (a flaky DMA, a preempted
all-reduce, an interconnect blip) are recoverable by simply re-running the
dispatched round program — jax dispatch is functional, so a retried chunk
recomputes from the same carried state.  This wraps round execution and
checkpoint I/O in a bounded retry loop; each retry emits a ``retry`` event
on the telemetry stream (docs/telemetry.md) so recovery is observable, not
silent.

Jitter is derived deterministically from the operation name + attempt
number (not ``random.random()``): backoff schedules reproduce exactly under
the chaos harness, and concurrent member fits (stacking's threaded pool)
still decorrelate because their op names differ.
"""

from __future__ import annotations

import dataclasses
import logging
import time
import zlib
from typing import Callable, Optional, Tuple, Type

logger = logging.getLogger("spark_ensemble_tpu")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff: delay ``base_delay * 2**(attempt-1)``
    capped at ``max_delay``, plus up to ``jitter`` fraction of itself.

    ``max_retries`` counts *re*-attempts: 2 means up to 3 calls total; 0
    disables retry entirely.  Only ``retry_on`` exception types are retried
    — anything else (including :class:`ChaosPreemption`, ``KeyboardInterrupt``)
    propagates immediately.
    """

    max_retries: int = 2
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.25
    retry_on: Tuple[Type[BaseException], ...] = (RuntimeError, OSError)

    def delay(self, op: str, attempt: int) -> float:
        """Backoff before re-attempt ``attempt`` (1-based), deterministic
        in ``(op, attempt)``."""
        raw = min(self.base_delay * (2.0 ** (attempt - 1)), self.max_delay)
        h = zlib.crc32(f"{op}:{attempt}".encode()) & 0xFFFFFFFF
        return raw * (1.0 + self.jitter * (h / 2**32))


def retry_call(
    fn: Callable,
    policy: Optional[RetryPolicy] = None,
    op: str = "",
    telem=None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` under ``policy``; returns its result.

    On a retryable failure, emits a ``retry`` telemetry event (operation,
    attempt, backoff delay, error type) and re-raises once ``max_retries``
    is exhausted.  ``telem=None`` (or a disabled telemetry) just logs.
    """
    policy = policy or RetryPolicy()
    attempt = 0
    while True:
        try:
            return fn()
        except policy.retry_on as e:
            attempt += 1
            if attempt > policy.max_retries:
                raise
            delay = policy.delay(op, attempt)
            logger.warning(
                "retrying %s after %s (attempt %d/%d, backoff %.3fs): %s",
                op or "operation", type(e).__name__, attempt,
                policy.max_retries, delay, e,
            )
            if telem is not None:
                telem.emit(
                    "retry",
                    op=op,
                    attempt=attempt,
                    max_retries=policy.max_retries,
                    delay_s=round(delay, 6),
                    error_type=type(e).__name__,
                    error=str(e)[:500],
                )
            sleep(delay)
