"""Round execution: the family-agnostic RoundExecutor, lookahead depth
resolution, and the opt-in on-device patience recurrence.

:class:`RoundExecutor` is the ONE speculative round-loop driver.  Both
round drivers (``models/gbm.py:_drive_rounds``,
``models/boosting.py:_drive_boosting_rounds``) and the out-of-core
streaming fit (``data/streaming.py``) plug into it through
:class:`RoundAdapter`; the executor owns window fill, in-order commit
and in-flight invalidation, while each family keeps its own chunk math,
guard recovery and checkpoint payloads behind the adapter hooks.

The round drivers historically read every
chunk's outputs back to the host *before* dispatching the next chunk, so
the device idled during patience stepping, guard scans, telemetry fences
and checkpoint bookkeeping — the dispatch-bound regime the only on-chip
capture measured at 0.51% MFU.  JAX dispatch is asynchronous: a jitted
call returns future arrays immediately and the host only blocks when it
*reads* them.  The pipeline exploits exactly that: with depth ``k`` the
driver keeps up to ``k`` speculative chunks enqueued past the chunk whose
bookkeeping is being committed, so the device computes chunk ``j+1``
while the host reads chunk ``j``.

Exactness is preserved because member keys/masks derive from **absolute
round indices**: a mid-chunk validation stop or a guard recovery simply
discards the speculative in-flight chunks and rewinds the carry — replay
(when needed) re-dispatches the same pure program over the same keys and
is bit-identical.  ``SE_TPU_PIPELINE=0`` pins today's fully synchronous
path (test-pinned bit-identity); unset, the depth comes from the
autotuned ``pipeline_depth`` tunable (autotune/space.py).

``SE_TPU_DEVICE_PATIENCE=1`` additionally moves the patience recurrence
on-device: the chunk's per-round validation losses are folded through a
``lax.scan`` inside one cached program and the host reads back four
scalars (best, patience, stopped, kept) instead of stepping the loop in
Python.  The device recurrence runs in float32 while the host reference
steps in float64, so decisions can diverge at tolerance boundaries —
that is why it is opt-in and OFF by default (docs/pipeline.md).
"""

from __future__ import annotations

import os
from collections import deque
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_ensemble_tpu.telemetry.trace import NULL_SPAN, new_flow_id

PIPELINE_ENV = "SE_TPU_PIPELINE"
DEVICE_PATIENCE_ENV = "SE_TPU_DEVICE_PATIENCE"

#: deepest supported lookahead window; beyond 2 the host is never the
#: bottleneck and speculative work wasted on a stop grows linearly
MAX_PIPELINE_DEPTH = 2

#: default depth — must mirror the ``pipeline_depth`` tunable's default
#: (autotune/space.py bit-identity contract)
DEFAULT_PIPELINE_DEPTH = 1


def resolve_pipeline_depth(n_rows: Optional[int] = None) -> int:
    """Lookahead depth for a fit over ``n_rows`` training rows.

    Resolution order: ``SE_TPU_PIPELINE`` (clamped to
    ``[0, MAX_PIPELINE_DEPTH]``; non-integer values are ignored) wins
    over the autotuned ``pipeline_depth`` tunable, which falls back to
    :data:`DEFAULT_PIPELINE_DEPTH`.  Read per fit, not at import, so a
    test or bench leg can flip the env between fits.
    """
    raw = os.environ.get(PIPELINE_ENV)
    if raw is not None and raw.strip():
        try:
            return max(0, min(MAX_PIPELINE_DEPTH, int(raw)))
        except ValueError:
            pass  # unparsable env degrades to the tunable, not a crash
    from spark_ensemble_tpu.autotune.resolve import resolve

    depth = resolve("pipeline_depth", DEFAULT_PIPELINE_DEPTH, n=n_rows)
    try:
        return max(0, min(MAX_PIPELINE_DEPTH, int(depth)))
    except (TypeError, ValueError):
        return DEFAULT_PIPELINE_DEPTH


def device_patience_enabled() -> bool:
    """Whether the opt-in on-device patience recurrence is active."""
    return os.environ.get(DEVICE_PATIENCE_ENV, "") not in ("", "0")


def _patience_scan_program():
    """One cached program folding a chunk's validation losses through the
    patience recurrence (the device twin of
    ``_GBMParams._patience_step``).  Scalar inputs are traced, so a
    single program serves every estimator; the errs length retraces per
    chunk size (bounded by the handful of distinct chunk tails)."""
    from spark_ensemble_tpu.models.base import cached_program

    def build():
        def run(errs, best0, v0, tol, limit):
            def step(carry, err):
                best, v, done, kept = carry
                no_improve = (best - err) < tol * jnp.maximum(err, 0.01)
                new_v = jnp.where(no_improve, v + 1, 0)
                new_best = jnp.where(no_improve, best, err)
                stop_now = jnp.logical_and(
                    jnp.logical_not(done), new_v >= limit
                )
                best = jnp.where(done, best, new_best)
                v = jnp.where(done, v, new_v)
                kept = jnp.where(done, kept, kept + 1)
                done = jnp.logical_or(done, stop_now)
                return (best, v, done, kept), None

            init = (
                jnp.float32(best0),
                jnp.int32(v0),
                jnp.bool_(False),
                jnp.int32(0),
            )
            (best, v, done, kept), _ = jax.lax.scan(
                step, init, jnp.asarray(errs, jnp.float32)
            )
            return best, v, done, kept

        return jax.jit(run)

    return cached_program(("device_patience_scan",), build)


def device_patience_step(
    errs, best: float, v: int, tol: float, limit: int, telem=None
) -> Tuple[float, int, bool, int]:
    """Fold a chunk's per-round validation losses on-device and read back
    four scalars: ``(best, v, stopped, kept)`` where ``kept`` counts the
    rounds up to AND INCLUDING the stopping round.  ``best`` comes back
    as float32 — callers carrying it across chunks stay in the device's
    precision by construction.

    The four-scalar readback is a blocking host read inside the dispatch
    window; with ``telem`` it is charged to the fit's ``host_blocked_us``
    accounting like every other sanctioned fence (graftlint
    unfenced-blocking-read)."""
    prog = _patience_scan_program()
    b0 = np.float32(np.inf) if not np.isfinite(best) else np.float32(best)
    out = prog(errs, b0, np.int32(v), np.float32(tol), np.int32(limit))
    if telem is not None:
        telem.blocking_read(out)
    best_h, v_h, done_h, kept_h = jax.device_get(out)
    return float(best_h), int(v_h), bool(done_h), int(kept_h)


# ---------------------------------------------------------------------------
# the family-agnostic round executor
# ---------------------------------------------------------------------------


class RoundAdapter:
    """One ensemble family's view of its round loop, as seen by
    :class:`RoundExecutor`.

    The executor owns ONLY the speculation machinery — window fill,
    in-order commit, invalidation of in-flight chunks — which is the part
    `gbm._drive_rounds` and `boosting._drive_boosting_rounds` used to
    duplicate.  Everything family-specific (what a chunk dispatch returns,
    patience vs abort-replay bookkeeping, guard recovery, checkpoint
    payloads) lives behind these hooks:

    - ``should_continue()``: loop predicate over COMMITTED state (round
      count, patience, abort/halt flags).
    - ``can_launch()``: whether the dispatch frontier has rounds left to
      speculate on.
    - ``window()``: in-flight chunk cap for the next fill — normally
      ``depth + 1``; families with a probe chunk (boosting's abort ramp)
      return 1 until the probe commits.
    - ``launch() -> entry``: plan one chunk at the frontier (remaining
      rounds, checkpoint-boundary clamp), dispatch it asynchronously, and
      advance the frontier.  The returned entry is opaque to the executor.
    - ``commit(entry, speculated) -> bool``: read the chunk's outputs and
      run the family's bookkeeping.  ``speculated`` is True when further
      chunks are still in flight (the family must then commit under the
      entry's own carry snapshot, not the speculative frontier).  Return
      True to INVALIDATE everything still in flight — a mid-chunk stop,
      an abort, or a guard rewind dispatched those chunks for rounds that
      no longer exist; the executor discards them unread and calls
      ``reset_frontier()``.  Replay stays bit-identical because member
      keys/masks derive from absolute round indices.
    - ``reset_frontier()``: rewind the dispatch frontier (and any carried
      frontier state, e.g. boosting's weight future) to committed state.
    - ``finish()``: post-loop join (the drivers' ``ckpt.wait()``); runs
      only on a clean exit so a ``raise`` guard policy propagates.
    """

    #: lookahead depth (chunks in flight past the committing one); 0 pins
    #: the fully synchronous pre-pipeline path
    depth: int = 0

    #: the fit's FitTelemetry, when the family wires one through — the
    #: executor traces each chunk's dispatch→commit life as a span with
    #: its commit/invalidate fate (telemetry/trace.py); None (the
    #: default, kept by bare test adapters) traces nothing
    telem = None

    #: extra fields merged into every round_chunk span the executor opens
    #: (a dict, e.g. the GBM sampling stage's ``{"sampling": "goss",
    #: "sample_bucket": 256}``) so per-chunk trace rows carry the
    #: adapter-level configuration that shaped the dispatch; None adds
    #: nothing
    span_fields = None

    def should_continue(self) -> bool:
        raise NotImplementedError

    def can_launch(self) -> bool:
        raise NotImplementedError

    def window(self) -> int:
        return self.depth + 1

    def launch(self) -> Any:
        raise NotImplementedError

    def commit(self, entry: Any, speculated: bool) -> bool:
        raise NotImplementedError

    def reset_frontier(self) -> None:
        raise NotImplementedError

    def finish(self) -> None:  # pragma: no cover - trivial default
        pass


class RoundExecutor:
    """The single round-loop driver every family routes through.

    Fills the adapter's lookahead window with asynchronously dispatched
    chunks, commits them strictly in dispatch order, and on invalidation
    discards the speculative tail unread.  With ``depth == 0`` the fill
    never exceeds one chunk, which reproduces the historical synchronous
    drivers exactly (pinned by tests/test_pipeline_exec.py); with
    ``depth > 0`` the device computes chunk ``j+1`` while the host reads
    chunk ``j`` (docs/pipeline.md)."""

    def __init__(self, adapter: RoundAdapter):
        self.adapter = adapter

    def run(self) -> RoundAdapter:
        a = self.adapter
        telem = a.telem
        pending: deque = deque()
        seq = 0
        try:
            while a.should_continue():
                while a.can_launch() and len(pending) < max(1, a.window()):
                    # span first, then launch: the chunk span covers the
                    # dispatch and stays open until its commit resolves
                    # its fate (committed / invalidated / abandoned)
                    pending.append((
                        NULL_SPAN if telem is None else telem.begin_span(
                            "round_chunk", chunk_seq=seq,
                            speculative=bool(pending),
                            **(getattr(a, "span_fields", None) or {}),
                        ),
                        a.launch(),
                    ))
                    seq += 1
                if not pending:
                    # frontier exhausted with nothing in flight: only an
                    # adapter whose committed state lags its own frontier
                    # can get here, and committing is impossible — stop
                    break
                sp, entry = pending.popleft()
                invalidate = False
                fate = "aborted"
                flow = None
                try:
                    invalidate = a.commit(entry, speculated=bool(pending))
                    fate = "committed"
                    if invalidate and pending and sp:
                        # the commit decision kills the speculative tail:
                        # a flow arrow from this span to each invalidated
                        # chunk renders the causality in the trace viewer
                        flow = new_flow_id()
                        sp.add(flow_out=[flow])
                finally:
                    sp.end(fate=fate)
                if invalidate:
                    while pending:
                        psp, _ = pending.popleft()
                        if flow is None:
                            psp.end(fate="invalidated")
                        else:
                            psp.end(fate="invalidated", flow_in=flow)
                    a.reset_frontier()
        finally:
            # a raise mid-loop (guard policy, chaos fault) discards the
            # in-flight tail unread — their spans still close
            while pending:
                psp, _ = pending.popleft()
                psp.end(fate="abandoned")
        a.finish()
        return a
