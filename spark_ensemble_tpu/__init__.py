"""TPU-native ensemble-learning framework.

A from-scratch JAX/XLA re-design of the capabilities of
pierrenodet/spark-ensemble (Scala/Spark meta-estimators): Bagging (SubBag),
Boosting (AdaBoost SAMME / SAMME.R / Drucker R2), Gradient Boosting Machines
(gradient & Newton updates, line-searched step sizes, early stopping,
stochastic subbagging) and Stacking, for classification and regression, over
pluggable base learners.

Where the reference runs inner loops as Spark RDD jobs on JVM executors
(reference: `core/src/main/scala/org/apache/spark/ml/...`), this framework
compiles them to XLA: base-learner fits are vmapped across ensemble members
and class dims, rows are sharded over a `jax.sharding.Mesh`, and reductions
use `psum` over ICI instead of Spark `treeAggregate`.
"""

from spark_ensemble_tpu.models.bagging import (
    BaggingClassificationModel,
    BaggingClassifier,
    BaggingRegressionModel,
    BaggingRegressor,
)
from spark_ensemble_tpu.models.boosting import (
    BoostingClassificationModel,
    BoostingClassifier,
    BoostingRegressionModel,
    BoostingRegressor,
)
from spark_ensemble_tpu.models.dummy import (
    DummyClassificationModel,
    DummyClassifier,
    DummyRegressionModel,
    DummyRegressor,
)
from spark_ensemble_tpu.models.gbm import (
    GBMClassificationModel,
    GBMClassifier,
    GBMRegressionModel,
    GBMRegressor,
)
from spark_ensemble_tpu.models.linear import (
    LinearRegression,
    LinearRegressionModel,
    LogisticRegression,
    LogisticRegressionModel,
)
from spark_ensemble_tpu.models.linear_tree import (
    LinearTreeRegressionModel,
    LinearTreeRegressor,
)
from spark_ensemble_tpu.models.mlp import (
    MLPClassificationModel,
    MLPClassifier,
    MLPRegressionModel,
    MLPRegressor,
)
from spark_ensemble_tpu.models.naive_bayes import (
    GaussianNaiveBayes,
    GaussianNaiveBayesModel,
)
from spark_ensemble_tpu.models.stacking import (
    StackingClassificationModel,
    StackingClassifier,
    StackingRegressionModel,
    StackingRegressor,
)
from spark_ensemble_tpu.models.tree import (
    DecisionTreeClassificationModel,
    DecisionTreeClassifier,
    DecisionTreeRegressionModel,
    DecisionTreeRegressor,
)
from spark_ensemble_tpu.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_ensemble_tpu.pipeline import (
    MinMaxScaler,
    MinMaxScalerModel,
    Pipeline,
    PipelineModel,
    StandardScaler,
    StandardScalerModel,
)
from spark_ensemble_tpu.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)
from spark_ensemble_tpu import telemetry
from spark_ensemble_tpu.telemetry import (
    DriftMonitor,
    FitTelemetry,
    FlightRecorder,
    HbmSampler,
    MetricsRegistry,
    OperatorPlane,
    OperatorServer,
    ProgramInventory,
    ProgramRecord,
    ShadowScorer,
    Span,
    TelemetryRecorder,
    TraceContext,
    Tracer,
    Watchdog,
    dump_flight,
    global_inventory,
    record_fits,
    render_openmetrics,
    skew_report,
    staged_attribution,
    start_operator_plane,
    stitch_files,
    trace_annotations_enabled,
    validate_openmetrics,
)
from spark_ensemble_tpu import robustness
from spark_ensemble_tpu.robustness import (
    ChaosController,
    ChaosPreemption,
    ChaosTransientError,
    NonFiniteError,
    NumericGuard,
    RetryPolicy,
    retry_call,
    validate_fit_inputs,
)
from spark_ensemble_tpu import serving
from spark_ensemble_tpu.serving import (
    Autopilot,
    FleetOverloadError,
    FleetResponse,
    FleetRouter,
    InferenceEngine,
    ModelRegistry,
    PackedModel,
    fit_resume,
    load_packed,
    pack,
)
from spark_ensemble_tpu import autotune
from spark_ensemble_tpu.autotune import (
    TUNABLES,
    TuningCache,
    autotune_fit,
    enable_compilation_cache,
    run_search,
)
from spark_ensemble_tpu import analysis
from spark_ensemble_tpu.analysis import (
    ContractReport,
    check_contracts,
    lint_paths,
    trace_contracts,
)
from spark_ensemble_tpu.execution import (
    RoundExecutor,
    device_patience_enabled,
    resolve_pipeline_depth,
)
from spark_ensemble_tpu import data
from spark_ensemble_tpu.data import (
    PartitionedShardReader,
    ShardPartition,
    ShardPrefetcher,
    ShardStore,
    manifest_digest,
    partition_shards,
    write_shards,
)
from spark_ensemble_tpu import parallel
from spark_ensemble_tpu.parallel import (
    DistributedSweep,
    ElasticCoordinator,
    HostLostError,
    slice_count,
    survivor_mesh,
)
from spark_ensemble_tpu.models.base import shared_fit_context
from spark_ensemble_tpu.utils.persist import load

__version__ = "0.1.0"

__all__ = [
    "BaggingClassifier",
    "BaggingClassificationModel",
    "BaggingRegressor",
    "BaggingRegressionModel",
    "BoostingClassifier",
    "BoostingClassificationModel",
    "BoostingRegressor",
    "BoostingRegressionModel",
    "GBMClassifier",
    "GBMClassificationModel",
    "GBMRegressor",
    "GBMRegressionModel",
    "StackingClassifier",
    "StackingClassificationModel",
    "StackingRegressor",
    "StackingRegressionModel",
    "DummyClassifier",
    "DummyClassificationModel",
    "DummyRegressor",
    "DummyRegressionModel",
    "DecisionTreeClassifier",
    "DecisionTreeClassificationModel",
    "DecisionTreeRegressor",
    "DecisionTreeRegressionModel",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "GaussianNaiveBayes",
    "GaussianNaiveBayesModel",
    "LinearTreeRegressor",
    "LinearTreeRegressionModel",
    "MLPClassifier",
    "MLPClassificationModel",
    "MLPRegressor",
    "MLPRegressionModel",
    "RegressionEvaluator",
    "MulticlassClassificationEvaluator",
    "BinaryClassificationEvaluator",
    "ParamGridBuilder",
    "CrossValidator",
    "CrossValidatorModel",
    "TrainValidationSplit",
    "TrainValidationSplitModel",
    "Pipeline",
    "PipelineModel",
    "StandardScaler",
    "StandardScalerModel",
    "MinMaxScaler",
    "MinMaxScalerModel",
    "FitTelemetry",
    "FlightRecorder",
    "MetricsRegistry",
    "TelemetryRecorder",
    "dump_flight",
    "record_fits",
    "skew_report",
    "stitch_files",
    "Span",
    "TraceContext",
    "Tracer",
    "trace_annotations_enabled",
    "ProgramInventory",
    "ProgramRecord",
    "HbmSampler",
    "global_inventory",
    "OperatorPlane",
    "OperatorServer",
    "Watchdog",
    "render_openmetrics",
    "start_operator_plane",
    "validate_openmetrics",
    "DriftMonitor",
    "ShadowScorer",
    "staged_attribution",
    "ChaosController",
    "ChaosPreemption",
    "ChaosTransientError",
    "NonFiniteError",
    "NumericGuard",
    "RetryPolicy",
    "retry_call",
    "validate_fit_inputs",
    "PackedModel",
    "pack",
    "fit_resume",
    "load_packed",
    "InferenceEngine",
    "ModelRegistry",
    "FleetRouter",
    "FleetResponse",
    "FleetOverloadError",
    "Autopilot",
    "TUNABLES",
    "TuningCache",
    "autotune_fit",
    "enable_compilation_cache",
    "run_search",
    "resolve_pipeline_depth",
    "device_patience_enabled",
    "RoundExecutor",
    "ShardStore",
    "ShardPrefetcher",
    "write_shards",
    "PartitionedShardReader",
    "ShardPartition",
    "manifest_digest",
    "partition_shards",
    "DistributedSweep",
    "ElasticCoordinator",
    "HostLostError",
    "slice_count",
    "survivor_mesh",
    "shared_fit_context",
    "lint_paths",
    "ContractReport",
    "check_contracts",
    "trace_contracts",
    "load",
]
