"""Evaluators: jitted metric kernels with the Spark ML evaluator surface.

The reference's docs and test suites evaluate models with Spark's
``MulticlassClassificationEvaluator`` / ``RegressionEvaluator`` /
``BinaryClassificationEvaluator`` (reference `docs/example.md`,
`GBMClassifierSuite.scala:51-87`, `BaggingRegressorSuite.scala:48-75`).
This module supplies the TPU-native equivalents so the ensemble estimators
compose with model selection (:mod:`spark_ensemble_tpu.tuning`) the way the
reference composes with ``CrossValidator``.

Each evaluator exposes:
- ``evaluate(model, X, y, sample_weight=None) -> float`` — fetches whatever
  the metric needs from the model (predictions / probabilities);
- a pure, jit-compiled metric kernel on device arrays (``_metric_fn``), so
  evaluation inside a tuning sweep adds one fused XLA program, not a
  per-row UDF pass like Spark's evaluator DataFrame scans;
- ``is_larger_better`` — drives the argbest direction in model selection,
  mirroring ``Evaluator.isLargerBetter``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from spark_ensemble_tpu.models.base import infer_num_classes, resolve_weights
from spark_ensemble_tpu.params import Param, Params, gt_eq, in_array


class Evaluator(Params):
    """Base evaluator (reference: Spark ``ml.evaluation.Evaluator``)."""

    is_larger_better = True

    def evaluate(self, model, X, y, sample_weight=None) -> float:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Regression
# ---------------------------------------------------------------------------


@jax.jit
def _regression_metrics(pred, y, w):
    pred = jnp.asarray(pred, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    sw = jnp.maximum(jnp.sum(w), 1e-30)
    err = pred - y
    mse = jnp.sum(w * err * err) / sw
    mae = jnp.sum(w * jnp.abs(err)) / sw
    y_mean = jnp.sum(w * y) / sw
    ss_tot = jnp.sum(w * (y - y_mean) ** 2) / sw
    r2 = 1.0 - mse / jnp.maximum(ss_tot, 1e-30)
    # Spark's 'var' is EXPLAINED variance (SSreg / weightSum), larger-better
    # (SPARK RegressionMetrics.explainedVariance), not residual variance
    var = jnp.sum(w * (pred - y_mean) ** 2) / sw
    return {"mse": mse, "rmse": jnp.sqrt(mse), "mae": mae, "r2": r2, "var": var}


class RegressionEvaluator(Evaluator):
    """Metrics rmse|mse|mae|r2|var (Spark ``RegressionEvaluator`` set)."""

    metric = Param(
        "rmse", in_array(["rmse", "mse", "mae", "r2", "var"]),
        doc="regression metric (Spark RegressionEvaluator names)",
    )

    @property
    def is_larger_better(self):
        return self.metric.lower() in ("r2", "var")

    def evaluate(self, model, X, y, sample_weight=None) -> float:
        y = jnp.asarray(y, jnp.float32)
        w = resolve_weights(y, sample_weight)
        pred = model.predict(X)
        return float(_regression_metrics(pred, y, w)[self.metric.lower()])


# ---------------------------------------------------------------------------
# Multiclass classification
# ---------------------------------------------------------------------------


def _confusion_stats(pred, y, w, num_classes: int):
    """Per-class (tp, predicted-positive, actual-positive) weighted counts."""
    p = jax.nn.one_hot(pred.astype(jnp.int32), num_classes)
    t = jax.nn.one_hot(y.astype(jnp.int32), num_classes)
    tp = jnp.sum(w[:, None] * p * t, axis=0)
    pp = jnp.sum(w[:, None] * p, axis=0)
    ap = jnp.sum(w[:, None] * t, axis=0)
    return tp, pp, ap


class MulticlassClassificationEvaluator(Evaluator):
    """accuracy|f1|weightedPrecision|weightedRecall|logLoss|hammingLoss
    (Spark ``MulticlassClassificationEvaluator`` set).  f1 is the
    actual-frequency-weighted mean of per-class F1, matching Spark."""

    metric = Param(
        "f1",
        in_array(
            [
                "f1",
                "accuracy",
                "weightedprecision",
                "weightedrecall",
                "logloss",
                "hammingloss",
            ]
        ),
        doc="multiclass metric (Spark MulticlassClassificationEvaluator "
        "names); f1 is the actual-frequency-weighted mean of per-class F1",
    )
    eps = Param(1e-15, gt_eq(0.0), doc="probability clamp for logLoss (Spark default)")

    @property
    def is_larger_better(self):
        return self.metric.lower() not in ("logloss", "hammingloss")

    def evaluate(self, model, X, y, sample_weight=None) -> float:
        y = jnp.asarray(y, jnp.float32)
        w = resolve_weights(y, sample_weight)
        metric = self.metric.lower()
        if metric == "logloss":
            proba = jnp.asarray(model.predict_proba(X))
            return float(_metric_logloss(proba.shape[1], float(self.eps))(proba, y, w))
        pred = jnp.asarray(model.predict(X))
        num_classes = int(getattr(model, "num_classes", None) or infer_num_classes(y))
        return float(_multiclass_metric(metric, num_classes)(pred, y, w))


@functools.lru_cache(maxsize=None)
def _metric_logloss(num_classes: int, eps: float):
    @jax.jit
    def f(proba, y, w):
        p = jnp.clip(proba, eps, 1.0 - eps)
        t = jax.nn.one_hot(y.astype(jnp.int32), num_classes)
        ll = -jnp.sum(t * jnp.log(p), axis=-1)
        return jnp.sum(w * ll) / jnp.maximum(jnp.sum(w), 1e-30)

    return f


@functools.lru_cache(maxsize=None)
def _multiclass_metric(metric: str, num_classes: int):
    @jax.jit
    def f(pred, y, w):
        sw = jnp.maximum(jnp.sum(w), 1e-30)
        if metric == "accuracy":
            return jnp.sum(w * (pred == y)) / sw
        if metric == "hammingloss":
            return jnp.sum(w * (pred != y)) / sw
        tp, pp, ap = _confusion_stats(pred, y, w, num_classes)
        precision = tp / jnp.maximum(pp, 1e-30)
        recall = tp / jnp.maximum(ap, 1e-30)
        if metric == "weightedprecision":
            return jnp.sum(ap * precision) / sw
        if metric == "weightedrecall":
            return jnp.sum(ap * recall) / sw
        f1 = 2.0 * precision * recall / jnp.maximum(precision + recall, 1e-30)
        return jnp.sum(ap * f1) / sw

    return f


# ---------------------------------------------------------------------------
# Binary classification (ranking metrics)
# ---------------------------------------------------------------------------


@jax.jit
def _binary_curves(score, y, w):
    """Weighted ROC/PR points from scores for the positive class.

    Sort-by-score (descending) + cumulative sums — the XLA replacement for
    Spark's ``BinaryClassificationMetrics`` shuffle-and-scan.  Tied scores
    must yield ONE curve point per distinct threshold (otherwise a constant
    scorer walks a lucky staircase instead of the chance diagonal), so each
    row takes the (tp, fp) of the LAST row in its tie group: intermediate
    tied rows then duplicate the group-end point and contribute zero width
    to the trapezoid, and the segment across the group is the correct
    straight line.
    """
    n = score.shape[0]
    order = jnp.argsort(-score)
    ss = score[order]
    ys = y[order]
    ws = w[order]
    pos = jnp.sum(w * y)
    neg = jnp.sum(w * (1.0 - y))
    tp = jnp.cumsum(ws * ys)
    fp = jnp.cumsum(ws * (1.0 - ys))
    # tie-group ids: a group starts where the sorted score changes
    start = jnp.concatenate([jnp.ones((1,), bool), ss[1:] != ss[:-1]])
    sid = jnp.cumsum(start.astype(jnp.int32)) - 1
    # group-end cumulative counts (tp/fp are monotone, so max == group end)
    tp = jax.ops.segment_max(tp, sid, num_segments=n)[sid]
    fp = jax.ops.segment_max(fp, sid, num_segments=n)[sid]
    tpr = tp / jnp.maximum(pos, 1e-30)
    fpr = fp / jnp.maximum(neg, 1e-30)
    precision = tp / jnp.maximum(tp + fp, 1e-30)
    return tpr, fpr, precision


class BinaryClassificationEvaluator(Evaluator):
    """areaUnderROC | areaUnderPR via trapezoidal integration over the
    weighted score-ranked curves (Spark ``BinaryClassificationEvaluator``)."""

    metric = Param(
        "areaunderroc", in_array(["areaunderroc", "areaunderpr"]),
        doc="threshold-free binary metric over raw scores/probabilities",
    )

    is_larger_better = True

    def evaluate(self, model, X, y, sample_weight=None) -> float:
        y = jnp.asarray(y, jnp.float32)
        w = resolve_weights(y, sample_weight)
        proba = jnp.asarray(model.predict_proba(X))
        score = proba[:, 1]
        tpr, fpr, precision = _binary_curves(score, y, w)
        if self.metric.lower() == "areaunderpr":
            # anchor at (recall=0, firstPrecision) like Spark (SPARK-21806):
            # the (0, 1) anchor inflates AUPR when thresholds are few — a
            # constant scorer would score (1 + baseRate)/2 instead of baseRate
            recall = jnp.concatenate([jnp.zeros((1,)), tpr])
            prec = jnp.concatenate([precision[:1], precision])
            return float(jnp.trapezoid(prec, recall))
        tpr = jnp.concatenate([jnp.zeros((1,)), tpr])
        fpr = jnp.concatenate([jnp.zeros((1,)), fpr])
        return float(jnp.trapezoid(tpr, fpr))
