"""Deterministic measured search over the tunable space.

The search times REAL jitted dispatches — a letter-shaped GBM fit, its
full-batch predict, the stream histogram tier, a mixed-size predict
request stream — under each candidate config, fenced through the
telemetry ``RoundTimer`` so async dispatch cannot fake a win.  Winners
land in the on-disk :class:`~spark_ensemble_tpu.autotune.cache.TuningCache`
keyed by ``(platform, device_kind, shape_class)`` and are consulted
transparently at fit/serve time (autotune.resolve).

Determinism: fixed-seed synthetic data, a fixed candidate order, and a
winner rule of "min median time, but only if it beats the default by
more than the noise floor" — so re-running the search on the same
machine converges instead of flapping.  Tests inject a fake ``measure``
callable for bit-deterministic winner selection.

Entry points: :func:`run_search` (the ``tools/autotune.py`` CLI body)
and :func:`autotune_fit` (the in-process fast path: tune for an actual
estimator + dataset, short-circuiting when the cache already covers
this device and shape class).
"""

from __future__ import annotations

import logging
import statistics
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from spark_ensemble_tpu.autotune.cache import TuningCache
from spark_ensemble_tpu.autotune.resolve import (
    _device_identity,
    override,
    reset,
)
from spark_ensemble_tpu.autotune.space import TUNABLES, shape_class

logger = logging.getLogger("spark_ensemble_tpu")

# only winners beating the default config by more than this fraction are
# recorded — below it the measured spread is timing noise, and recording
# it would make back-to-back searches flap between near-ties
NOISE_FLOOR = 0.02

# (n, d, k, rounds, repeats, max_depth, max_bins) per budget; "full" is
# letter-shaped (same shape class the bench headline leg resolves)
BUDGETS: Dict[str, Dict[str, int]] = {
    "smoke": dict(n=2048, d=8, k=4, rounds=6, repeats=1, depth=4, bins=32),
    "fast": dict(n=8192, d=16, k=8, rounds=16, repeats=2, depth=5, bins=64),
    "full": dict(n=15000, d=16, k=26, rounds=24, repeats=3, depth=5, bins=64),
}

_GROUPS = ("fit", "predict", "stream", "bucket", "pallas")


def clear_program_caches() -> None:
    """Drop every jitted/compiled program so the next dispatch re-traces
    under the CURRENT tuned config.  Trace-time tunables (stream chunk,
    fused-cell budgets, the hist tier) are latched into programs at
    trace time; candidate sweeps and tuned-vs-default comparisons must
    clear between configs or they time a stale program."""
    import jax

    from spark_ensemble_tpu.models import base as model_base

    with model_base._PROGRAM_CACHE_LOCK:
        model_base._PROGRAM_CACHE.clear()
    jax.clear_caches()


def _measure_real(tag: Dict[str, Any], thunk: Callable[[], Any],
                  repeats: int) -> float:
    """Median fenced wall time of ``thunk`` over ``repeats`` runs, after
    one untimed warmup (compiles excluded — steady-state cost is what
    the tuned constants control)."""
    from spark_ensemble_tpu.telemetry.events import global_metrics
    from spark_ensemble_tpu.telemetry.registry import RoundTimer

    timer = RoundTimer(
        "autotune/measure", global_metrics().histogram("autotune/measure_s")
    )
    thunk()  # warmup: compile + first dispatch
    times = []
    for _ in range(max(repeats, 1)):
        timer.start()
        out = thunk()
        times.append(timer.stop(out))
    return statistics.median(times)


def _synth_classification(n: int, d: int, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable k-class data (fixed seed: the search must
    measure the same programs every run)."""
    rng = np.random.default_rng(0)
    X = rng.standard_normal((n, d)).astype(np.float32)
    W = rng.standard_normal((d, k)).astype(np.float32)
    logits = X @ W + 0.5 * rng.standard_normal((n, k)).astype(np.float32)
    y = np.argmax(logits, axis=1).astype(np.int32)
    return X, y


def _candidate_rows(values, default) -> List[Any]:
    """Default first (its time is the comparison floor), then the rest in
    declared order."""
    rest = [v for v in values if v != default]
    return [default] + rest


def _sweep(
    name: str,
    candidates: List[Any],
    make_thunk: Callable[[], Callable[[], Any]],
    measure: Callable,
    repeats: int,
    timings: Dict[str, Dict[str, float]],
    extra_override: Optional[Dict[str, Any]] = None,
    real: bool = True,
) -> Tuple[Any, float, float]:
    """Time every candidate for one tunable; returns (winner, win_time,
    default_time).  ``make_thunk`` builds a fresh workload closure per
    candidate (program caches are cleared under the candidate override
    when measuring for real)."""
    default = candidates[0]
    results: Dict[Any, float] = {}
    for cand in candidates:
        ov = dict(extra_override or {})
        ov[name] = cand
        with override(mode="cache", **ov):
            if real:
                clear_program_caches()
            thunk = make_thunk()
            t = measure({"tunable": name, "candidate": cand}, thunk, repeats)
        results[cand] = t
        logger.info("autotune %s=%r: %.4fs", name, cand, t)
    timings[name] = {str(c): results[c] for c in candidates}
    best = min(results, key=lambda c: (results[c], str(c) != str(default)))
    if results[best] >= results[default] * (1.0 - NOISE_FLOOR):
        best = default  # not convincingly better than shipped default
    return best, results[best], results[default]


def run_search(
    budget: str = "smoke",
    *,
    groups: Optional[Tuple[str, ...]] = None,
    measure: Optional[Callable] = None,
    save: bool = True,
    directory: Optional[str] = None,
) -> Dict[str, Any]:
    """Run the measured search; returns ``{"winners", "timings",
    "platform", "device_kind", "shape_class", "budget"}`` and (when
    ``save``) publishes winners to the on-disk cache under both the
    tuned shape class and ``"*"``.

    ``measure(tag, thunk, repeats) -> seconds`` is injectable for
    deterministic tests; the default times real fenced dispatches.
    """
    if budget not in BUDGETS:
        raise ValueError(f"budget must be one of {sorted(BUDGETS)}; got {budget!r}")
    cfg = BUDGETS[budget]
    groups = tuple(groups or _GROUPS)
    bad = [g for g in groups if g not in _GROUPS]
    if bad:
        raise ValueError(f"unknown search groups: {bad}")
    real = measure is None
    measure = measure or _measure_real
    repeats = cfg["repeats"]

    import jax

    from spark_ensemble_tpu import DecisionTreeRegressor, GBMClassifier

    platform, device_kind = _device_identity()
    n, d, k = cfg["n"], cfg["d"], cfg["k"]
    X, y = _synth_classification(n, d, k)
    sc = shape_class(n)

    winners: Dict[str, Any] = {}
    timings: Dict[str, Dict[str, float]] = {}

    def fresh_estimator(**extra):
        return GBMClassifier(
            num_base_learners=cfg["rounds"],
            loss="logloss",
            updates="newton",
            learning_rate=0.3,
            base_learner=DecisionTreeRegressor(
                max_depth=cfg["depth"], max_bins=cfg["bins"]
            ),
            **extra,
        )

    # -- fit group: hist tier, then scan_chunk at the winning tier ----------
    model = None
    if "fit" in groups:
        def fit_thunk():
            est = fresh_estimator()

            def run():
                return est.fit(X, y).params

            return run

        tiers = ["auto", "scatter", "matmul"]
        if n > 200_000:
            tiers.append("stream")  # stream only wins at HBM scale
        tier, _, _ = _sweep(
            "hist_tier", tiers, fit_thunk, measure, repeats, timings,
            real=real,
        )
        if tier != "auto":
            winners["hist_tier"] = tier
        tier_ov = {"hist_tier": tier} if tier != "auto" else {}

        chunks = _candidate_rows(
            [c for c in TUNABLES["scan_chunk"].candidates
             if c <= cfg["rounds"] * 2],
            TUNABLES["scan_chunk"].default,
        )
        chunk, _, _ = _sweep(
            "scan_chunk", chunks, fit_thunk, measure, repeats, timings,
            extra_override=tier_ov, real=real,
        )
        if chunk != TUNABLES["scan_chunk"].default:
            winners["scan_chunk"] = chunk
        if real:
            with override(mode="cache", **{**tier_ov,
                                           "scan_chunk": chunk}):
                clear_program_caches()
                model = fresh_estimator().fit(X, y)

    # -- predict group: the fused-predict cell budget -----------------------
    if "predict" in groups:
        if model is None and real:
            model = fresh_estimator().fit(X, y)
        Xd = jax.numpy.asarray(X)

        def predict_thunk():
            if not real:
                return lambda: None
            m = model

            def run():
                return m.predict(Xd)

            return run

        cells = _candidate_rows(
            list(TUNABLES["predict_fused_max_cells"].candidates),
            TUNABLES["predict_fused_max_cells"].default,
        )
        cell, _, _ = _sweep(
            "predict_fused_max_cells", cells, predict_thunk, measure,
            max(repeats * 3, 3), timings, real=real,
        )
        if cell != TUNABLES["predict_fused_max_cells"].default:
            winners["predict_fused_max_cells"] = cell

    # -- stream group: rows per scan step of the stream hist tier -----------
    if "stream" in groups:
        def stream_thunk():
            if not real:
                return lambda: None
            from spark_ensemble_tpu.models.tree import DecisionTreeRegressor as DT

            est = DT(
                max_depth=cfg["depth"], max_bins=cfg["bins"], hist="stream"
            )
            yr = (np.asarray(y, np.float32) - float(np.mean(y)))

            def run():
                return est.fit(X, yr).params

            return run

        rows = _candidate_rows(
            [c for c in TUNABLES["stream_chunk_rows"].candidates if c <= 4 * n],
            TUNABLES["stream_chunk_rows"].default,
        )
        row, _, _ = _sweep(
            "stream_chunk_rows", rows, stream_thunk, measure, repeats,
            timings, real=real,
        )
        if row != TUNABLES["stream_chunk_rows"].default:
            winners["stream_chunk_rows"] = row

    # -- bucket group: the predict bucket ladder over mixed request sizes --
    if "bucket" in groups:
        if model is None and real:
            model = fresh_estimator().fit(X, y)
        rng = np.random.default_rng(1)
        sizes = [int(s) for s in rng.integers(1, max(n // 4, 2), size=24)]
        reqs = [X[:s] for s in sizes]

        def bucket_thunk():
            if not real:
                return lambda: None
            m = model

            def run():
                out = None
                for r in reqs:
                    out = m.predict(r)
                return out

            return run

        for name in ("predict_bucket_pow2_exact",
                     "predict_bucket_octave_steps"):
            cands = _candidate_rows(
                list(TUNABLES[name].candidates), TUNABLES[name].default
            )
            won, _, _ = _sweep(
                name, cands, bucket_thunk, measure, repeats, timings,
                real=real,
            )
            if won != TUNABLES[name].default:
                winners[name] = won

    # -- pallas group: kernel tiling (TPU only — interpret mode timings
    # are meaningless) ------------------------------------------------------
    if "pallas" in groups:
        if platform == "tpu" or not real:
            def pallas_thunk():
                if not real:
                    return lambda: None
                from spark_ensemble_tpu.ops.pallas_hist import hist_level_pallas

                rng = np.random.default_rng(2)
                Xb = jax.numpy.asarray(
                    rng.integers(0, cfg["bins"], size=(n, d), dtype=np.int32)
                )
                node = jax.numpy.asarray(
                    rng.integers(0, 8, size=(n, 4), dtype=np.int32)
                )
                vals = jax.numpy.asarray(
                    rng.standard_normal((n, 4, 3)).astype(np.float32)
                )

                def run():
                    return hist_level_pallas(
                        Xb, node, vals, n_nodes=8, max_bins=cfg["bins"]
                    )

                return run

            cands = _candidate_rows(
                list(TUNABLES["pallas_block_rows"].candidates),
                TUNABLES["pallas_block_rows"].default,
            )
            br, _, _ = _sweep(
                "pallas_block_rows", cands, pallas_thunk, measure,
                repeats, timings, real=real,
            )
            if br != TUNABLES["pallas_block_rows"].default:
                winners["pallas_block_rows"] = br

            # fused round kernel tiling: one routed level over bit-packed
            # bins, the shape the fused tier runs every round
            def fused_thunk():
                if not real:
                    return lambda: None
                from spark_ensemble_tpu.ops.binning import pack_bins, pack_width
                from spark_ensemble_tpu.ops.pallas_hist import fused_round_level

                bins = min(cfg["bins"], 256)  # fused packs B <= 256 only
                bits = pack_width(bins)
                rng = np.random.default_rng(3)
                cb = pack_bins(
                    jax.numpy.asarray(
                        rng.integers(0, bins, size=(n, d), dtype=np.int32)
                    ),
                    bins, bits,
                )
                node = jax.numpy.asarray(
                    rng.integers(0, 4, size=(n, 4), dtype=np.int32)
                )
                vals = jax.numpy.asarray(
                    rng.standard_normal((n, 4, 3)).astype(np.float32)
                )
                bf = jax.numpy.asarray(
                    rng.integers(0, d, size=(4, 4), dtype=np.int32)
                )
                bt = jax.numpy.asarray(
                    rng.integers(0, bins, size=(4, 4), dtype=np.int32)
                )

                def run():
                    return fused_round_level(
                        cb.packed, node, vals, bf, bt, n_nodes=8,
                        max_bins=bins, bits=bits, num_features=d,
                    )

                return run

            cands = _candidate_rows(
                list(TUNABLES["fused_block_rows"].candidates),
                TUNABLES["fused_block_rows"].default,
            )
            fbr, _, _ = _sweep(
                "fused_block_rows", cands, fused_thunk, measure,
                repeats, timings, real=real,
            )
            if fbr != TUNABLES["fused_block_rows"].default:
                winners["fused_block_rows"] = fbr
        else:
            logger.info("pallas group skipped: platform=%s (TPU only)", platform)

    result = {
        "winners": winners,
        "timings": timings,
        "platform": platform,
        "device_kind": device_kind,
        "shape_class": sc,
        "budget": budget,
        "shape": {"n": n, "d": d, "k": k, "rounds": cfg["rounds"]},
    }
    if save:
        cache = TuningCache.load(directory)
        meta = {
            "budget": budget,
            "shape": result["shape"],
            "cache_format": "autotune.search",
        }
        cache.put(platform, device_kind, sc, winners, meta)
        cache.put(platform, device_kind, "*", winners, meta)
        result["cache_path"] = cache.save(directory)
        reset()  # published generation supersedes the memoized view
    if real:
        clear_program_caches()
    return result


def autotune_fit(
    estimator,
    X,
    y=None,
    *,
    budget: str = "smoke",
    measure: Optional[Callable] = None,
    save: bool = True,
    directory: Optional[str] = None,
    force: bool = False,
) -> Dict[str, Any]:
    """In-process fast path: make sure tuned winners exist for THIS
    device and this dataset's shape class, searching only on a miss.

    A cache hit short-circuits the search entirely (zero measurements) —
    call with ``force=True`` to re-measure.  Returns the ``run_search``
    result dict, or ``{"cached": True, "params": {...}}`` on a hit.
    The estimator's own hand-set params are never overridden: resolution
    consults the cache only for params the user left at their defaults.
    """
    platform, device_kind = _device_identity()
    n = int(np.shape(X)[0])
    sc = shape_class(n)
    cache = TuningCache.load(directory)
    if not force:
        params = cache.lookup(platform, device_kind, sc)
        if params:
            return {
                "cached": True,
                "params": params,
                "platform": platform,
                "device_kind": device_kind,
                "shape_class": sc,
            }
    # size the search budget off the actual data when smaller than the
    # budget's nominal shape (tuning must stay cheap next to the fit)
    cfg = dict(BUDGETS[budget])
    cfg["n"] = min(cfg["n"], max(n, 256))
    saved = BUDGETS[budget]
    BUDGETS[budget] = cfg
    try:
        return run_search(
            budget, measure=measure, save=save, directory=directory
        )
    finally:
        BUDGETS[budget] = saved
