"""JAX persistent compilation cache wiring.

Every fresh process re-traces and re-compiles the same XLA programs —
the serving engine's warmup and the CI jobs were cold on every run.
JAX ships a persistent on-disk compilation cache; this module turns it
on with thresholds suited to this package (many sub-second CPU
compiles, which the stock 1-second minimum would refuse to persist).

Activation is transparent: ``cached_program`` (every fit/predict
program build) and ``InferenceEngine.warmup`` call
:func:`ensure_compilation_cache`, which is a no-op unless
``SE_TPU_COMPILE_CACHE=<dir>`` is set — or code calls
:func:`enable_compilation_cache` with an explicit path.  CI exports the
env var and persists the directory as an actions cache, so the second
run of any job loads compiled executables instead of re-compiling
(verified by the serving job's zero-warmup-compile assertion).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

logger = logging.getLogger("spark_ensemble_tpu")

COMPILE_CACHE_ENV = "SE_TPU_COMPILE_CACHE"

_LOCK = threading.Lock()
_ENABLED_DIR: Optional[str] = None


def enable_compilation_cache(path: str) -> bool:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing) and lower the persistence thresholds so even fast CPU
    compiles are cached.  Idempotent; returns True when active."""
    global _ENABLED_DIR
    with _LOCK:
        if _ENABLED_DIR is not None:
            return True
        try:
            import jax

            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            # stock minimums (1s compile, 0-byte entries) skip most of
            # this package's programs on CPU; cache everything
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            # jax latches its cache state at the FIRST compile of the
            # process; if anything compiled before this call (e.g. data
            # prep ahead of the first program build), the backing store
            # latched to None and every later read/write is silently
            # skipped despite the config dir above.  Un-latch so the next
            # compile re-initializes against the configured directory.
            try:
                from jax._src import compilation_cache as _jcc

                if (
                    getattr(_jcc, "_cache_initialized", False)
                    and getattr(_jcc, "_cache", None) is None
                ):
                    _jcc.reset_cache()
            except Exception:  # noqa: BLE001 - private API moved
                pass
            _ENABLED_DIR = path
            logger.info("persistent compilation cache enabled at %s", path)
            return True
        except Exception:  # noqa: BLE001 - older jax / readonly fs
            logger.warning(
                "could not enable the persistent compilation cache at %s",
                path, exc_info=True,
            )
            return False


def ensure_compilation_cache() -> bool:
    """Enable the cache from ``SE_TPU_COMPILE_CACHE`` if set; cheap
    no-op otherwise.  Called on every program build and serving warmup."""
    if _ENABLED_DIR is not None:
        return True
    path = os.environ.get(COMPILE_CACHE_ENV, "").strip()
    if not path:
        return False
    return enable_compilation_cache(path)


def compilation_cache_dir() -> Optional[str]:
    """The active persistent-cache directory, or ``None``."""
    return _ENABLED_DIR
