"""Versioned on-disk cache of measured tuning winners.

Layout (same crash-consistency discipline as ``utils/checkpoint.py``: a
sha256 manifest written inside a temp directory, then published by
atomic rename with the displaced generation retained):

    <cache_dir>/latest/tuned.json      {"version", "entries": {...}}
    <cache_dir>/latest/manifest.json   {"version", "files": {name: {sha256, bytes}}}
    <cache_dir>/.cache-old/            previous good generation

Entries key winners by ``platform/device_kind/shape_class`` — e.g.
``"cpu/cpu/n14"`` — so a cache file carried across machines only ever
applies to the hardware it was measured on.  A corrupt or truncated
``latest`` (manifest checksum mismatch, undecodable JSON) falls back to
``.cache-old`` and then to an empty cache: tuning state can never make
the package fail to import or fit.

The cache directory defaults to ``~/.cache/spark_ensemble_tpu/autotune``
and is overridden by ``SE_TPU_AUTOTUNE_CACHE``.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
from typing import Any, Dict, Optional

from spark_ensemble_tpu.autotune.space import TUNABLES

logger = logging.getLogger("spark_ensemble_tpu")

CACHE_ENV = "SE_TPU_AUTOTUNE_CACHE"

# bumped when the entry schema changes incompatibly; a version-mismatched
# cache is ignored (defaults apply), never partially decoded
CACHE_VERSION = 1

_TUNED_FILE = "tuned.json"
_MANIFEST_FILE = "manifest.json"
_LATEST = "latest"
_OLD = ".cache-old"


def cache_dir() -> str:
    """The active cache directory (``SE_TPU_AUTOTUNE_CACHE`` or the
    user-level default)."""
    env = os.environ.get(CACHE_ENV, "").strip()
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "spark_ensemble_tpu", "autotune"
    )


def _file_sha256(path: str) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def entry_key(platform: str, device_kind: str, shape_cls: str) -> str:
    # device_kind strings ("TPU v5 lite") may contain spaces; the key is
    # a plain string, not a path — only "/" needs normalizing
    return "/".join(
        str(p).replace("/", "_") for p in (platform, device_kind, shape_cls)
    )


class TuningCache:
    """In-memory view of the on-disk winners, with load/save/lookup."""

    def __init__(self, entries: Optional[Dict[str, Dict[str, Any]]] = None):
        self.entries: Dict[str, Dict[str, Any]] = dict(entries or {})

    # -- lookup -------------------------------------------------------------
    def lookup(
        self, platform: str, device_kind: str, shape_cls: str
    ) -> Dict[str, Any]:
        """Merged tuned params for a resolution site: the platform-wide
        ``"*"`` entry overlaid by the exact shape-class entry.  Unknown
        names and invalid values are dropped (forward compat)."""
        merged: Dict[str, Any] = {}
        for cls in ("*", shape_cls):
            entry = self.entries.get(entry_key(platform, device_kind, cls))
            if entry:
                merged.update(entry.get("params", {}))
        return TUNABLES.validate_params(merged)

    def has_entry(
        self, platform: str, device_kind: str, shape_cls: str
    ) -> bool:
        return bool(self.lookup(platform, device_kind, shape_cls))

    def put(
        self,
        platform: str,
        device_kind: str,
        shape_cls: str,
        params: Dict[str, Any],
        meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        key = entry_key(platform, device_kind, shape_cls)
        entry = self.entries.setdefault(key, {"params": {}})
        entry["params"].update(TUNABLES.validate_params(params))
        if meta:
            entry.setdefault("meta", {}).update(meta)

    # -- disk ---------------------------------------------------------------
    @classmethod
    def load(cls, directory: Optional[str] = None) -> "TuningCache":
        """Load ``latest`` (manifest-verified), falling back to the
        retained ``.cache-old`` and then to empty."""
        directory = directory or cache_dir()
        for source in (_LATEST, _OLD):
            loaded = cls._load_dir(os.path.join(directory, source))
            if loaded is not None:
                if source == _OLD:
                    logger.warning(
                        "autotune cache 'latest' unreadable; using the "
                        "retained previous generation (%s)", directory,
                    )
                return loaded
        return cls()

    @classmethod
    def _load_dir(cls, path: str) -> Optional["TuningCache"]:
        tuned_path = os.path.join(path, _TUNED_FILE)
        manifest_path = os.path.join(path, _MANIFEST_FILE)
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
            if manifest.get("version") != CACHE_VERSION:
                logger.warning(
                    "autotune cache version %r != %d; ignoring %s",
                    manifest.get("version"), CACHE_VERSION, path,
                )
                return None
            want = manifest.get("files", {}).get(_TUNED_FILE, {})
            if _file_sha256(tuned_path) != want.get("sha256"):
                logger.warning(
                    "autotune cache checksum mismatch; ignoring %s", path
                )
                return None
            with open(tuned_path) as f:
                data = json.load(f)
            if data.get("version") != CACHE_VERSION:
                return None
            entries = data.get("entries", {})
            if not isinstance(entries, dict):
                return None
            return cls(entries)
        except (OSError, json.JSONDecodeError, ValueError):
            return None

    def save(self, directory: Optional[str] = None) -> str:
        """Atomically publish this cache as the new ``latest``; the
        displaced generation is retained as ``.cache-old``.  Returns the
        published directory."""
        directory = directory or cache_dir()
        os.makedirs(directory, exist_ok=True)
        tmp = tempfile.mkdtemp(dir=directory, prefix=".cache-tmp-")
        try:
            tuned_path = os.path.join(tmp, _TUNED_FILE)
            with open(tuned_path, "w") as f:
                json.dump(
                    {"version": CACHE_VERSION, "entries": self.entries},
                    f, indent=2, sort_keys=True,
                )
            manifest = {
                "version": CACHE_VERSION,
                "files": {
                    _TUNED_FILE: {
                        "sha256": _file_sha256(tuned_path),
                        "bytes": os.path.getsize(tuned_path),
                    }
                },
            }
            with open(os.path.join(tmp, _MANIFEST_FILE), "w") as f:
                json.dump(manifest, f, indent=2)
            final = os.path.join(directory, _LATEST)
            stale = os.path.join(directory, _OLD)
            if os.path.exists(final):
                if os.path.exists(stale):
                    shutil.rmtree(stale)
                os.rename(final, stale)
            os.rename(tmp, final)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return os.path.join(directory, _LATEST)


def manifest_signature(directory: Optional[str] = None):
    """Cheap change-detection token for the published cache: (mtime_ns,
    size) of ``latest/manifest.json``, or ``None`` when absent.  The
    resolution layer re-loads only when this changes, so per-call resolve
    cost is one ``stat``."""
    path = os.path.join(directory or cache_dir(), _LATEST, _MANIFEST_FILE)
    try:
        st = os.stat(path)
        return (st.st_mtime_ns, st.st_size)
    except OSError:
        return None
