"""The typed tunable space: every hand-guessed hot-path constant, named.

Three rounds of roofline work left performance-critical launch/tiling
parameters as hand-guessed literals — ``scan_chunk`` (models/gbm.py),
``_STREAM_CHUNK_ROWS`` / ``_PREDICT_FUSED_MAX_CELLS`` (ops/tree.py),
``_BLOCK_ROWS`` / ``_VMEM_BUDGET`` (ops/pallas_hist.py), the predict
bucket ladder (models/base.py) and the dense/stream/scatter histogram
tier itself.  GPU GBDT systems win precisely by tuning these to the
device (XGBoost GPU, arXiv:1806.11248); this module gives each knob a
name, its shipped default, the candidate grid a measured search sweeps,
and the source site the value feeds — so the search (autotune.search),
the on-disk cache (autotune.cache) and the resolution layer
(autotune.resolve) all speak one schema.

Defaults here MUST mirror the literals at the source sites: when
autotuning is off (``SE_TPU_AUTOTUNE=off``) or no cache entry exists,
``resolve`` returns the caller's live module constant and behavior is
bit-identical to a build without this package.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Tuple


@dataclass(frozen=True)
class Tunable:
    """One measured knob: name, shipped default, candidate grid, site."""

    name: str
    default: object
    candidates: Tuple[object, ...]
    doc: str
    site: str  # "<module>:<constant or param>" the value feeds
    kind: str = "int"  # "int" | "choice"

    def validate(self, value) -> bool:
        if self.kind == "choice":
            return value in self.candidates
        return isinstance(value, int) and not isinstance(value, bool) and value > 0


class TunableSpace:
    """Ordered, name-addressable collection of :class:`Tunable`."""

    def __init__(self, tunables: Tuple[Tunable, ...]):
        self._by_name: Dict[str, Tunable] = {}
        for t in tunables:
            if t.name in self._by_name:
                raise ValueError(f"duplicate tunable {t.name!r}")
            self._by_name[t.name] = t

    def __getitem__(self, name: str) -> Tunable:
        return self._by_name[name]

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __iter__(self) -> Iterator[Tunable]:
        return iter(self._by_name.values())

    def __len__(self) -> int:
        return len(self._by_name)

    def names(self) -> Tuple[str, ...]:
        return tuple(self._by_name)

    def defaults(self) -> Dict[str, object]:
        return {t.name: t.default for t in self}

    def validate_params(self, params: Dict[str, object]) -> Dict[str, object]:
        """Drop unknown names and type-invalid values (a forward-compat
        cache written by a newer build must degrade to defaults, not
        crash the hot path)."""
        out = {}
        for name, value in params.items():
            t = self._by_name.get(name)
            if t is not None and t.validate(value):
                out[name] = value
        return out


# the space: defaults == the literals at each site (bit-identity contract)
TUNABLES = TunableSpace((
    Tunable(
        "scan_chunk", 16, (4, 8, 16, 32, 64, 128),
        doc="boosting rounds fused into one lax.scan-ed dispatch "
        "(hand-set estimator param always wins)",
        site="models/gbm.py:scan_chunk",
    ),
    Tunable(
        "stream_chunk_rows", 32768,
        (8192, 16384, 32768, 65536, 131072),
        doc="rows per scan step of the STREAM histogram tier",
        site="ops/tree.py:_STREAM_CHUNK_ROWS",
    ),
    Tunable(
        "shard_rows", 32768,
        (8192, 16384, 32768, 65536, 131072),
        doc="rows per on-disk shard of the out-of-core data plane; keep "
            "equal to stream_chunk_rows for bit-identity with resident "
            "stream fits",
        site="data/shards.py:DEFAULT_SHARD_ROWS",
    ),
    Tunable(
        "prefetch_depth", 2,
        (1, 2, 3, 4),
        doc="shards kept in flight past the one being consumed by the "
            "streaming fit's prefetcher",
        site="data/prefetch.py:DEFAULT_PREFETCH_DEPTH",
    ),
    Tunable(
        "predict_fused_max_cells", 2**27,
        (2**24, 2**25, 2**26, 2**27, 2**28, 2**29, 2**30),
        doc="rows*members*leaves budget of the fused predict routing "
        "one-hot; past it predict lax.maps over row chunks",
        site="ops/tree.py:_PREDICT_FUSED_MAX_CELLS",
    ),
    Tunable(
        "hist_tier", "auto",
        ("auto", "scatter", "matmul", "stream", "fused"),
        doc="histogram accumulation backend consulted when the "
        "estimator's hist param is 'auto' (scatter=segment_sum, "
        "matmul=dense one-hot MXU path, stream=row-chunked, "
        "fused=bit-packed pallas round kernel)",
        site="ops/tree.py:_resolve_hist",
        kind="choice",
    ),
    Tunable(
        "pallas_block_rows", 256, (128, 256, 512, 1024),
        doc="rows per grid step of the pallas level-histogram kernel",
        site="ops/pallas_hist.py:_BLOCK_ROWS",
    ),
    Tunable(
        "pallas_vmem_budget", 12 * 2**20,
        (8 * 2**20, 12 * 2**20, 16 * 2**20, 24 * 2**20),
        doc="VMEM budget (bytes) for the pallas kernel's resident "
        "accumulator; configs over it fall back to the matmul path",
        site="ops/pallas_hist.py:_VMEM_BUDGET",
    ),
    Tunable(
        "pack_bits", 0, (0, 4, 8, 32),
        doc="lane width of the fused tier's bit-packed bin matrix "
        "(0 = auto: the narrowest width max_bins allows; a tuned "
        "value never narrows below that)",
        site="ops/binning.py:pack_width",
        kind="choice",
    ),
    Tunable(
        "fused_block_rows", 256, (128, 256, 512, 1024),
        doc="rows per grid step of the fused round kernel",
        site="ops/pallas_hist.py:_FUSED_BLOCK_ROWS",
    ),
    Tunable(
        "fused_vmem_budget", 12 * 2**20,
        (8 * 2**20, 12 * 2**20, 16 * 2**20, 24 * 2**20),
        doc="VMEM budget (bytes) for the fused round kernel's resident "
        "accumulator + routing tables; configs over it fall back to "
        "the matmul/stream tiers",
        site="ops/pallas_hist.py:_FUSED_VMEM_BUDGET",
    ),
    Tunable(
        "predict_bucket_pow2_exact", 512, (256, 512, 1024, 2048),
        doc="predict batches at or below this pad to the next power of "
        "two exactly (one trace per pow2 bucket)",
        site="models/base.py:_BUCKET_POW2_EXACT",
    ),
    Tunable(
        "predict_bucket_octave_steps", 8, (4, 8, 16),
        doc="buckets per octave above the exact-pow2 range (8 == "
        "<=12.5% padding; more buckets = less padding, more traces)",
        site="models/base.py:_BUCKET_OCTAVE_STEPS",
    ),
    Tunable(
        "pipeline_depth", 1, (0, 1, 2),
        doc="lookahead chunks kept in flight by the round drivers "
        "(0 = fully synchronous dispatch; SE_TPU_PIPELINE env wins)",
        site="execution.py:resolve_pipeline_depth",
        kind="choice",
    ),
    Tunable(
        "configs_per_dispatch", 32, (8, 16, 32, 64),
        doc="sweep candidates vmapped into one megabatch round dispatch "
        "(tuning.py megabatch; candidates beyond it run in further "
        "slabs of the same program shape)",
        site="models/gbm_sweep.py:_CONFIGS_PER_DISPATCH",
    ),
    Tunable(
        "sample_bucket_floor", 256, (64, 128, 256, 512, 1024),
        doc="smallest compacted row bucket the gradient-based sampling "
        "stage gathers into (GOSS/MVS); tiny sample targets round up to "
        "it so the pow2 bucket ladder, and with it the traced-program "
        "inventory, stays O(1) across sample ratios",
        site="models/gbm.py:_resolved_sampling",
    ),
    Tunable(
        "goss_top_rate", 0.2, (0.1, 0.2, 0.3),
        doc="fraction of rows kept deterministically by |grad| rank when "
        "sampling='goss' and the estimator's top_rate was left at its "
        "default (hand-set rates always win)",
        site="models/gbm.py:_resolved_sampling",
        kind="choice",
    ),
    Tunable(
        "goss_other_rate", 0.1, (0.05, 0.1, 0.2),
        doc="fraction of the remaining rows drawn uniformly (amplified by "
        "(1-a)/b) when sampling='goss' and other_rate was left at its "
        "default (hand-set rates always win)",
        site="models/gbm.py:_resolved_sampling",
        kind="choice",
    ),
))


def shape_class(n: Optional[int] = None) -> str:
    """Coarse workload key for the config cache: the log2 bucket of the
    row count (``"n14"`` for letter-scale ~16k rows), or ``"*"`` when no
    row count is known at the resolution site (e.g. the predict bucket
    ladder, which serves arbitrary request sizes).  Search results are
    stored under both the tuned shape's class and ``"*"``; lookup tries
    the exact class first."""
    if n is None or n <= 0:
        return "*"
    return f"n{round(math.log2(max(int(n), 1)))}"
