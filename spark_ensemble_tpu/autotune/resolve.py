"""Transparent resolution of tunables at fit/serve time.

Hot-path sites call ``resolve(name, default, n=...)`` with their live
module constant as the default.  Resolution order (first hit wins):

1. an active :func:`override` (bench/search in-process toggles);
2. ``SE_TPU_AUTOTUNE=off`` -> the default, always (bit-identity escape
   hatch; hand-set estimator params never reach resolve at all — the
   call sites skip it when the user set the param explicitly);
3. the on-disk cache entry for ``(platform, device_kind, shape_class)``
   (mode ``cache``, the default, and ``search``);
4. under mode ``search`` with no entry for this device: a one-shot
   in-process smoke search populates the cache first;
5. the default.

The loaded cache is memoized per directory and re-validated by a single
``stat`` of the published manifest per call, so resolve is cheap enough
for per-request sites (the predict bucket ladder).
"""

from __future__ import annotations

import logging
import os
import threading
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

from spark_ensemble_tpu.autotune.cache import (
    TuningCache,
    cache_dir,
    manifest_signature,
)
from spark_ensemble_tpu.autotune.space import TUNABLES, shape_class

logger = logging.getLogger("spark_ensemble_tpu")

MODE_ENV = "SE_TPU_AUTOTUNE"
_MODES = ("off", "cache", "search")

# override stack (bench tuned-vs-default legs, the search's candidate
# sweeps, tests).  Deliberately process-global, not thread-local: a
# worker thread dispatching a candidate program must see the candidate.
_OVERRIDES: list = []

# memoized cache view: {dir: (manifest_signature, TuningCache)}
_LOADED: Dict[str, Tuple[Any, TuningCache]] = {}
_LOAD_LOCK = threading.Lock()

# re-entrancy guard for mode="search" auto-tuning (the search itself
# fits models, whose hot paths call resolve)
_IN_SEARCH = threading.local()


def autotune_mode() -> str:
    """Active mode: the innermost ``override(mode=...)`` if any, else
    ``SE_TPU_AUTOTUNE`` (default ``cache``)."""
    for frame in reversed(_OVERRIDES):
        if frame.get("mode") is not None:
            return frame["mode"]
    raw = os.environ.get(MODE_ENV, "").strip().lower()
    if not raw:
        return "cache"
    if raw not in _MODES:
        logger.warning(
            "%s=%r is not one of %s; treating as 'off'", MODE_ENV, raw, _MODES
        )
        return "off"
    return raw


@contextmanager
def override(mode: Optional[str] = None, **params):
    """Force tunables (and/or the mode) for a scope — used by the search
    to dispatch candidate configs and by bench's tuned-vs-default leg.
    Overridden params win over the cache; unknown names raise."""
    unknown = [k for k in params if k not in TUNABLES]
    if unknown:
        raise ValueError(f"unknown tunables: {unknown}")
    if mode is not None and mode not in _MODES:
        raise ValueError(f"mode must be one of {_MODES}; got {mode!r}")
    frame = {"mode": mode, "params": params}
    _OVERRIDES.append(frame)
    try:
        yield
    finally:
        _OVERRIDES.remove(frame)


def reset() -> None:
    """Drop the memoized cache view (tests that swap cache dirs/content
    mid-process get a clean reload; normal use never needs this — the
    manifest stat re-validates automatically)."""
    with _LOAD_LOCK:
        _LOADED.clear()


def _device_identity() -> Tuple[str, str]:
    import jax

    try:
        dev = jax.devices()[0]
        return jax.default_backend(), getattr(dev, "device_kind", dev.platform)
    except Exception:  # noqa: BLE001 - no backend at all
        return "cpu", "cpu"


def _load() -> TuningCache:
    d = cache_dir()
    sig = manifest_signature(d)
    with _LOAD_LOCK:
        memo = _LOADED.get(d)
        if memo is not None and memo[0] == sig:
            return memo[1]
    cache = TuningCache.load(d) if sig is not None else TuningCache()
    with _LOAD_LOCK:
        _LOADED[d] = (sig, cache)
    return cache


def _maybe_search() -> None:
    """Mode ``search`` with an empty cache for this device: run the smoke
    search once, then serve from the cache like mode ``cache``."""
    if getattr(_IN_SEARCH, "active", False):
        return
    platform, kind = _device_identity()
    if _load().entries and any(
        k.startswith(f"{platform}/") for k in _load().entries
    ):
        return
    _IN_SEARCH.active = True
    try:
        from spark_ensemble_tpu.autotune.search import run_search

        logger.info(
            "SE_TPU_AUTOTUNE=search and no tuned entries for %s/%s: "
            "running the smoke search once", platform, kind,
        )
        run_search(budget="smoke")
        reset()
    except Exception:  # noqa: BLE001 - tuning must never break a fit
        logger.warning("in-process autotune search failed", exc_info=True)
    finally:
        _IN_SEARCH.active = False


def resolve(name: str, default, *, n: Optional[int] = None):
    """The tuned value for ``name`` at this site, or ``default``.

    ``default`` is the caller's LIVE module constant (read at call time,
    so test monkeypatching of the source literal keeps working); ``n``
    is the row count when the site knows one (selects the shape class).
    """
    for frame in reversed(_OVERRIDES):
        if name in frame["params"]:
            return frame["params"][name]
    mode = autotune_mode()
    if mode == "off":
        return default
    if mode == "search":
        _maybe_search()
    platform, kind = _device_identity()
    params = _load().lookup(platform, kind, shape_class(n))
    return params.get(name, default)


def fingerprint() -> tuple:
    """Tuning-state token appended to jitted-program cache keys: programs
    traced under different tuned configs (cache generations, override
    frames, modes) must never collide.  Cheap — one env read and one
    manifest stat."""
    mode = autotune_mode()
    if mode == "off" and not _OVERRIDES:
        return ("autotune-off",)
    over = tuple(
        (k, v) for frame in _OVERRIDES for k, v in frame["params"].items()
    )
    return (mode, manifest_signature(), over)


def resolved_snapshot(n: Optional[int] = None) -> Dict[str, Any]:
    """Every tunable's resolved value at this site plus the mode and
    whether any cache entry applied — bench records this in each leg."""
    mode = autotune_mode()
    platform, kind = _device_identity()
    if mode == "off":
        tuned: Dict[str, Any] = {}
    else:
        tuned = _load().lookup(platform, kind, shape_class(n))
    values = {
        t.name: resolve(t.name, t.default, n=n) for t in TUNABLES
    }
    return {
        "mode": mode,
        "cache_hit": bool(tuned),
        "cache_dir": cache_dir(),
        "values": values,
    }
