"""Autotuned execution engine: measured config selection for the
hot-path constants, plus the persistent compilation cache.

The package replaces three rounds of hand-guessed launch/tiling
literals with a measurement loop (docs/autotune.md):

- :mod:`~spark_ensemble_tpu.autotune.space` — the typed tunable space
  (``TUNABLES``) whose defaults mirror the shipped literals;
- :mod:`~spark_ensemble_tpu.autotune.search` — a deterministic search
  (``run_search`` / ``autotune_fit`` / the ``tools/autotune.py`` CLI)
  timing real jitted dispatches via the telemetry ``RoundTimer``;
- :mod:`~spark_ensemble_tpu.autotune.cache` — a versioned on-disk
  winner cache keyed by ``(platform, device_kind, shape_class)`` with
  sha256 manifest + atomic publish (``SE_TPU_AUTOTUNE_CACHE``);
- :mod:`~spark_ensemble_tpu.autotune.resolve` — transparent lookup at
  fit/serve time, gated by ``SE_TPU_AUTOTUNE=off|cache|search`` with
  hand-set estimator params always winning and bit-identical behavior
  when off or unpopulated;
- :mod:`~spark_ensemble_tpu.autotune.compilation_cache` — JAX
  persistent compilation cache wiring (``SE_TPU_COMPILE_CACHE``), so
  repeated processes (serving restarts, CI jobs) stop re-compiling.
"""

from spark_ensemble_tpu.autotune.cache import (
    CACHE_ENV,
    CACHE_VERSION,
    TuningCache,
    cache_dir,
)
from spark_ensemble_tpu.autotune.compilation_cache import (
    COMPILE_CACHE_ENV,
    compilation_cache_dir,
    enable_compilation_cache,
    ensure_compilation_cache,
)
from spark_ensemble_tpu.autotune.resolve import (
    MODE_ENV,
    autotune_mode,
    fingerprint,
    override,
    reset,
    resolve,
    resolved_snapshot,
)
from spark_ensemble_tpu.autotune.search import (
    autotune_fit,
    clear_program_caches,
    run_search,
)
from spark_ensemble_tpu.autotune.space import (
    TUNABLES,
    Tunable,
    TunableSpace,
    shape_class,
)

__all__ = [
    "CACHE_ENV",
    "CACHE_VERSION",
    "COMPILE_CACHE_ENV",
    "MODE_ENV",
    "TUNABLES",
    "Tunable",
    "TunableSpace",
    "TuningCache",
    "autotune_fit",
    "autotune_mode",
    "cache_dir",
    "clear_program_caches",
    "compilation_cache_dir",
    "enable_compilation_cache",
    "ensure_compilation_cache",
    "fingerprint",
    "override",
    "reset",
    "resolve",
    "resolved_snapshot",
    "run_search",
    "shape_class",
]
