"""Bucketed, AOT-warmed batch-inference engine over a packed model.

Serving traffic is many small requests of arbitrary row counts — the two
things jit-compiled inference is worst at (every novel shape retraces; every
tiny dispatch pays full launch overhead).  :class:`InferenceEngine` fixes
both:

- **Shape buckets**: requests are zero-padded into a fixed set of
  power-of-two row buckets, so the shape space the compiler ever sees is
  O(log max_batch) — and every bucket's program is AOT-compiled at startup
  (``jax.jit(...).lower().compile()``), so steady-state serving performs
  **zero** compiles (asserted in tests via the ``jax.monitoring`` compile
  counters).  Padding is done host-side in numpy, so not even a one-op pad
  program compiles per novel request size.
- **Donated request buffers**: the padded request array is donated to the
  compiled program (``donate_argnums``) on backends that support buffer
  donation (not CPU), so serving allocates no second copy of the request.
- **Micro-batching**: ``submit()`` returns a ``Future`` and a background
  worker coalesces queued requests into one device dispatch, up to
  ``max_batch_size`` rows or ``max_delay_ms`` of waiting — many small
  callers share one program execution.

Every request emits a ``request_served`` telemetry event (latency, rows,
bucket, padding utilization, queue depth) through the existing telemetry
sinks, and per-engine counters/histograms land in
``telemetry.global_metrics()``.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_ensemble_tpu.ops.binning import Bins, bin_occupancy
from spark_ensemble_tpu.serving.export import PackedModel, pack, rebuild_model
from spark_ensemble_tpu.telemetry.quality import DriftMonitor
from spark_ensemble_tpu.telemetry.events import (
    _ensure_compile_listener,
    compile_snapshot,
    emit_event,
    global_metrics,
    serving_stream_id,
)
from spark_ensemble_tpu.telemetry.trace import Tracer
from spark_ensemble_tpu.utils.instrumentation import block_on_arrays

__all__ = ["InferenceEngine"]

_SHUTDOWN = object()


def _pow2_buckets(min_bucket: int, max_bucket: int) -> Tuple[int, ...]:
    out = []
    b = 1 << max(0, int(min_bucket) - 1).bit_length()
    while b < max_bucket:
        out.append(b)
        b <<= 1
    out.append(1 << max(0, int(max_bucket) - 1).bit_length())
    return tuple(sorted(set(out)))


class _Request:
    __slots__ = ("X", "n", "single", "future", "t_submit")

    def __init__(self, X, n, single, future, t_submit):
        self.X = X
        self.n = n
        self.single = single
        self.future = future
        self.t_submit = t_submit


class InferenceEngine:
    """Serve a fitted or packed model through fixed power-of-two batch
    buckets with AOT-compiled programs and an optional micro-batching queue.

    Parameters
    ----------
    model:
        A fitted :class:`~spark_ensemble_tpu.models.base.Model` (packed
        automatically) or a :class:`PackedModel`.
    methods:
        Model entry points to serve (``"predict"``, ``"predict_proba"``,
        ``"predict_raw"``).  Every configured method is AOT-compiled for
        every bucket at :meth:`warmup`; calling an unconfigured method
        raises rather than silently compiling mid-serve.
    min_bucket / max_batch_size:
        Smallest and largest bucket row counts; buckets are the powers of
        two spanning them.  Requests larger than the top bucket are served
        in top-bucket chunks.
    max_delay_ms:
        Micro-batching window: how long the queue worker waits to coalesce
        more requests once one is pending.
    prefix_tiers:
        Ensemble-prefix member counts to AOT-compile as degraded tiers
        (see :meth:`PackedModel.take`): ``predict(..., tier=k)`` serves the
        first-k-member prefix through its own pre-warmed programs, so a
        fleet under deadline pressure can shed compute without shedding
        requests — and without a single mid-serve compile.
    donate:
        Donate the padded request buffer to the compiled program; default
        on for backends with real donation support (not CPU).
    warm:
        AOT-compile + execute every (method, bucket) program at
        construction; pass ``False`` to warm explicitly later.
    drift / drift_window / drift_monitor:
        On-device feature-drift sketching (telemetry/quality.py,
        docs/quality.md).  When the packed model carries its fit-time bin
        reference (``PackedModel.quality``), the full-model predict
        programs ALSO emit a per-feature bin-count histogram of the served
        rows — fused into the same cached program, so steady-state serving
        still performs zero compiles and zero extra dispatches — and a
        :class:`DriftMonitor` scores tumbling ``drift_window``-row windows
        as PSI/KL against the training occupancy.  ``drift=None`` enables
        this exactly when the reference is present; ``drift_monitor``
        injects a shared monitor (fleet replicas aggregate into one).
    """

    def __init__(
        self,
        model,
        *,
        methods: Tuple[str, ...] = ("predict",),
        min_bucket: int = 8,
        max_batch_size: int = 4096,
        max_delay_ms: float = 2.0,
        donate: Optional[bool] = None,
        warm: bool = True,
        label: str = "engine",
        telemetry_path: Optional[str] = None,
        prefix_tiers: Tuple[int, ...] = (),
        drift: Optional[bool] = None,
        drift_window: int = 2048,
        drift_monitor: Optional[DriftMonitor] = None,
    ):
        self._packed = model if isinstance(model, PackedModel) else pack(model)
        if self._packed.num_features <= 0:
            raise ValueError(
                "packed model reports no num_features; cannot size buckets"
            )
        self._methods = tuple(methods)
        for m in self._methods:
            if m not in ("predict", "predict_proba", "predict_raw"):
                raise ValueError(f"unknown serve method {m!r}")
        self._buckets = _pow2_buckets(min_bucket, max_batch_size)
        self._max_batch = self._buckets[-1]
        self._max_delay_s = float(max_delay_ms) / 1000.0
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self._donate = bool(donate)
        self._label = label
        self._telemetry_path = telemetry_path
        self._stream = serving_stream_id(label)
        self._tracer = Tracer(self._emit_trace, thread=label)
        self._lock = threading.Lock()
        self._compiled: Dict[Tuple[str, int], Any] = {}
        self._compile_s: Dict[Tuple[str, int], float] = {}
        # engine programs close over nothing: the packed arrays are passed
        # as arguments, snapshotted once here so the engine owns its device
        # references (registry eviction offloads the PackedModel without
        # yanking buffers out from under in-flight engines)
        self._arrays = self._packed.device_arrays()
        self._arrays_struct = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), self._arrays
        )
        # degraded tiers: each prefix is its own packed model with its own
        # (smaller) arrays; bit-identity to a k-round fit is PackedModel
        # .take()'s contract, the engine just pre-warms the programs
        self._prefix_tiers = tuple(sorted({int(k) for k in prefix_tiers}))
        self._tier_nodes: Dict[int, Dict[str, Any]] = {}
        self._tier_arrays: Dict[int, Dict[str, jax.Array]] = {}
        self._tier_structs: Dict[int, Any] = {}
        for k in self._prefix_tiers:
            sliced = self._packed.take(k)
            self._tier_nodes[k] = sliced.node
            self._tier_arrays[k] = sliced.device_arrays()
            self._tier_structs[k] = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                self._tier_arrays[k],
            )
        # on-device drift sketch: auto-on exactly when the packed model
        # ships its fit-time reference; the monitor is shared by clones so
        # a fleet's replicas aggregate into one window stream
        quality = self._packed.quality
        if drift is None:
            drift = quality is not None
        if drift and quality is None:
            raise ValueError(
                "drift=True but the packed model carries no fit-time drift "
                "reference (PackedModel.quality is None); re-pack from a "
                "fit that captured one, or pass drift=False"
            )
        self._drift_enabled = bool(drift)
        self._drift = drift_monitor
        self._drift_owner = False
        if self._drift_enabled and self._drift is None:
            self._drift = DriftMonitor(
                quality["thresholds"],
                quality["occupancy"],
                window_rows=drift_window,
                stream=self._stream,
                telemetry_path=telemetry_path,
            )
            self._drift_owner = True
        self._metrics = global_metrics()
        self._queue: "queue_mod.SimpleQueue" = queue_mod.SimpleQueue()
        self._worker: Optional[threading.Thread] = None
        self._stopped = False
        _ensure_compile_listener()
        self._warm_snapshot = compile_snapshot()
        if warm:
            self.warmup()

    # -- compilation -------------------------------------------------------

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    @property
    def prefix_tiers(self) -> Tuple[int, ...]:
        return self._prefix_tiers

    @property
    def packed(self) -> PackedModel:
        return self._packed

    @property
    def drift_monitor(self) -> Optional[DriftMonitor]:
        """The live drift monitor (shared across clones), or ``None`` when
        sketching is disabled."""
        return self._drift

    def clone(self, label: str) -> "InferenceEngine":
        """A fleet replica over the SAME compiled programs and device
        arrays: its own queue, worker thread, and telemetry stream, but the
        ``_compiled`` map is shared, so N replicas warm once — fleet warmup
        cost is O(methods x buckets x tiers), not x N.  (``setdefault``
        under the GIL keeps the rare concurrent-compile race benign.)"""
        eng = InferenceEngine.__new__(InferenceEngine)
        eng._packed = self._packed
        eng._methods = self._methods
        eng._buckets = self._buckets
        eng._max_batch = self._max_batch
        eng._max_delay_s = self._max_delay_s
        eng._donate = self._donate
        eng._label = label
        eng._telemetry_path = self._telemetry_path
        eng._stream = serving_stream_id(label)
        eng._tracer = Tracer(eng._emit_trace, thread=label)
        eng._lock = threading.Lock()
        eng._compiled = self._compiled
        eng._compile_s = self._compile_s
        eng._arrays = self._arrays
        eng._arrays_struct = self._arrays_struct
        eng._prefix_tiers = self._prefix_tiers
        eng._tier_nodes = self._tier_nodes
        eng._tier_arrays = self._tier_arrays
        eng._tier_structs = self._tier_structs
        eng._drift_enabled = self._drift_enabled
        eng._drift = self._drift  # shared: replicas fold into one stream
        eng._drift_owner = False
        eng._metrics = self._metrics
        eng._queue = queue_mod.SimpleQueue()
        eng._worker = None
        eng._stopped = False
        eng._warm_snapshot = compile_snapshot()
        return eng

    def bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._max_batch

    def _emit_trace(self, rec: Dict[str, Any]) -> None:
        # span chokepoint: span records ride the same standalone-event
        # sinks as engine_warmup/request_served, tagged with this
        # engine's stream id (telemetry/trace.py; docs/tracing.md)
        rec = dict(rec)
        emit_event(
            rec.pop("event"), path=self._telemetry_path,
            fit_id=self._stream, **rec,
        )

    def _tier_key(self, method: str, bucket: int, tier: int):
        # full-model programs keep the historical (method, bucket) key so
        # stats()/contract baselines stay stable; prefix tiers append k
        return (method, bucket) if not tier else (method, bucket, tier)

    def _arrays_for(self, tier: int):
        return self._arrays if not tier else self._tier_arrays[tier]

    def _compile(self, method: str, bucket: int, tier: int = 0):
        key = self._tier_key(method, bucket, tier)
        with self._lock:
            fn = self._compiled.get(key)
        if fn is not None:
            return fn
        node = self._packed.node if not tier else self._tier_nodes[tier]
        struct = self._arrays_struct if not tier else self._tier_structs[tier]
        d = self._packed.num_features
        # drift sketching rides ONLY the full-model programs: tier replays
        # (staged attribution) re-serve rows the tier-0 path already counted
        sketch = self._drift_enabled and not tier

        def run(arrays, X):
            # rebuild happens at trace time only: model construction is
            # pure pytree plumbing, so the whole model predict stages into
            # ONE program with the packed arrays as (non-donated) inputs
            out = getattr(rebuild_model(node, arrays), method)(X)
            if sketch:
                # per-feature bin histogram of the request rows, fused into
                # the SAME program: same compile count, same dispatch count
                hist = bin_occupancy(
                    X, Bins(thresholds=arrays["q.thresholds"])
                )
                return out, hist
            return out

        jitted = jax.jit(run, donate_argnums=(1,) if self._donate else ())
        wall0 = time.time()
        t0 = time.perf_counter()
        compiled = jitted.lower(
            struct,
            jax.ShapeDtypeStruct((bucket, d), jnp.float32),
        ).compile()
        compile_s = time.perf_counter() - t0
        with self._lock:
            won = self._compiled.setdefault(key, compiled)
            if won is compiled:
                self._compile_s[key] = compile_s
        if won is compiled:
            emit_event(
                "engine_warmup",
                path=self._telemetry_path,
                fit_id=self._stream,
                method=method,
                bucket=int(bucket),
                tier=int(tier),
                compile_s=compile_s,
            )
            # the same tier-warmup as a span on this engine's track, so
            # the trace shows warmup cost next to the requests it unblocks
            self._tracer.emit_span(
                "engine_warmup", wall0, compile_s,
                method=method, bucket=int(bucket), tier=int(tier),
            )
        return won

    def warmup(self, methods: Optional[Tuple[str, ...]] = None) -> "InferenceEngine":
        """AOT-compile every (method, bucket) program and execute each once
        on zeros (touches allocator paths), then snapshot the compile
        counters — ``stats()['compiles_since_warmup']`` counts from here."""
        from spark_ensemble_tpu.autotune import ensure_compilation_cache

        ensure_compilation_cache()
        d = self._packed.num_features
        for method in methods or self._methods:
            for b in self._buckets:
                for tier in (0,) + self._prefix_tiers:
                    compiled = self._compile(method, b, tier)
                    out = compiled(
                        self._arrays_for(tier), jnp.zeros((b, d), jnp.float32)
                    )
                    block_on_arrays(out)
        self._warm_snapshot = compile_snapshot()
        return self

    # -- synchronous serving ----------------------------------------------

    def _normalize(self, X) -> Tuple[np.ndarray, bool]:
        Xa = np.asarray(X, np.float32)
        single = Xa.ndim == 1
        if single:
            Xa = Xa[None, :]
        if Xa.ndim != 2 or Xa.shape[1] != self._packed.num_features:
            raise ValueError(
                f"request shape {np.shape(X)} does not match model "
                f"num_features={self._packed.num_features}"
            )
        return Xa, single

    def _run_padded(self, method: str, Xa: np.ndarray, tier: int = 0):
        """One compiled-program execution: host-side zero-pad to the bucket,
        run, fetch, slice the real rows back out in numpy.  Nothing here
        compiles on a warmed engine — pad AND slice stay on the host (even
        an eager ``out[:n]`` would compile a one-op program per novel size),
        which is what makes steady-state serving literally zero-compile."""
        n = Xa.shape[0]
        b = self.bucket_for(n)
        key = self._tier_key(method, b, tier)
        compiled = self._compiled.get(key) or self._compile(method, b, tier)
        if n < b:
            buf = np.zeros((b, Xa.shape[1]), np.float32)
            buf[:n] = Xa
            Xa = buf
        out = compiled(self._arrays_for(tier), jnp.asarray(Xa))
        if self._drift_enabled and not tier:
            out, hist = out
            res = np.asarray(out)[:n]
            if self._drift is not None:
                # one host transfer per dispatch, off the result's critical
                # section; pad rows are subtracted inside the monitor
                self._drift.observe(np.asarray(hist), pad_rows=b - n)
            return res, b
        return np.asarray(out)[:n], b

    def _serve_rows(self, method: str, Xa: np.ndarray, tier: int = 0):
        """Serve up to any row count: top-bucket chunks + one padded tail.
        Returns host arrays — the serving boundary hands results back to
        network/callers, so the device->host fetch happens exactly once."""
        n = Xa.shape[0]
        if n <= self._max_batch:
            return self._run_padded(method, Xa, tier)
        outs = []
        for i in range(0, n, self._max_batch):
            out, _ = self._run_padded(method, Xa[i : i + self._max_batch], tier)
            outs.append(out)
        return np.concatenate(outs, axis=0), self._max_batch

    def _check_method(self, method: str, tier: int = 0):
        if method not in self._methods:
            raise ValueError(
                f"engine was not configured to serve {method!r} "
                f"(methods={self._methods}); construct with "
                f"methods=(..., {method!r}) so it AOT-warms"
            )
        if tier and tier not in self._prefix_tiers:
            raise ValueError(
                f"engine has no prefix tier {tier} "
                f"(prefix_tiers={self._prefix_tiers}); construct with "
                f"prefix_tiers=(..., {tier}) so it AOT-warms"
            )

    def _record(self, method: str, rows: int, bucket: int, latency_s: float,
                queue_depth: int, batch_rows: int, source: str,
                tier: int = 0) -> None:
        util = batch_rows / bucket if bucket else 0.0
        emit_event(
            "request_served",
            path=self._telemetry_path,
            fit_id=self._stream,
            method=method,
            rows=int(rows),
            bucket=int(bucket),
            batch_rows=int(batch_rows),
            bucket_utilization=util,
            latency_ms=latency_s * 1e3,
            queue_depth=int(queue_depth),
            source=source,
            tier=int(tier),
        )
        self._metrics.counter("serving/requests").inc()
        self._metrics.counter("serving/rows").inc(int(rows))
        self._metrics.histogram("serving/latency_ms").record(latency_s * 1e3)
        self._metrics.histogram("serving/bucket_utilization").record(util)
        self._metrics.gauge("serving/queue_depth").set(queue_depth)

    def predict(self, X, method: str = "predict", tier: int = 0) -> np.ndarray:
        """Synchronous bucketed inference -> host array; the result is
        materialized before the latency is recorded, so
        ``request_served.latency_ms`` is honest under async dispatch.
        ``tier=k`` serves through the pre-warmed first-k-member prefix."""
        self._check_method(method, tier)
        t0 = time.perf_counter()
        Xa, single = self._normalize(X)
        out, bucket = self._serve_rows(method, Xa, tier)
        self._record(
            method, Xa.shape[0], bucket, time.perf_counter() - t0,
            queue_depth=0, batch_rows=Xa.shape[0], source="sync", tier=tier,
        )
        return out[0] if single else out

    def predict_proba(self, X) -> np.ndarray:
        return self.predict(X, method="predict_proba")

    def predict_raw(self, X) -> np.ndarray:
        return self.predict(X, method="predict_raw")

    # -- micro-batching queue ---------------------------------------------

    def submit(self, X, method: str = "predict", tier: int = 0) -> Future:
        """Queue a request; a background worker coalesces pending requests
        into one device dispatch (up to ``max_batch_size`` rows or
        ``max_delay_ms`` of waiting) and resolves each caller's Future with
        its own rows.  Requests only coalesce within a (method, tier)."""
        self._check_method(method, tier)
        if self._stopped:
            raise RuntimeError("engine is stopped")
        Xa, single = self._normalize(X)
        fut: Future = Future()
        req = _Request(Xa, Xa.shape[0], single, fut, time.perf_counter())
        self._ensure_worker()
        self._queue.put(((method, tier), req))
        return fut

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._worker_loop,
                    name=f"se-tpu-{self._label}",
                    daemon=True,
                )
                self._worker.start()

    def _worker_loop(self) -> None:
        while True:
            try:
                item = self._queue.get(timeout=0.2)
            except queue_mod.Empty:
                if self._stopped:
                    return
                continue
            if item is _SHUTDOWN:
                return
            key, first = item
            batch = [first]
            rows = first.n
            deadline = time.perf_counter() + self._max_delay_s
            while rows < self._max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    item = self._queue.get(timeout=remaining)
                except queue_mod.Empty:
                    break
                if item is _SHUTDOWN:
                    self._serve_batch(key, batch)
                    return
                nxt_key, req = item
                if nxt_key != key:
                    # (method, tier) switch flushes the coalesced batch
                    self._serve_batch(key, batch)
                    key, batch, rows = nxt_key, [req], req.n
                    deadline = time.perf_counter() + self._max_delay_s
                    continue
                batch.append(req)
                rows += req.n
            self._serve_batch(key, batch)

    def _serve_batch(self, key: Tuple[str, int], batch: List[_Request]) -> None:
        method, tier = key
        try:
            depth = len(batch)
            Xa = (
                batch[0].X
                if depth == 1
                else np.concatenate([r.X for r in batch], axis=0)
            )
            out, bucket = self._serve_rows(method, Xa, tier)
            now = time.perf_counter()
            offset = 0
            for r in batch:
                part = out[offset : offset + r.n]
                offset += r.n
                self._record(
                    method, r.n, bucket, now - r.t_submit,
                    queue_depth=depth, batch_rows=Xa.shape[0], source="queue",
                    tier=tier,
                )
                r.future.set_result(part[0] if r.single else part)
        except Exception as e:  # resolve every caller, never hang a Future
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)

    # -- lifecycle / introspection ----------------------------------------

    def stop(self) -> None:
        """Drain and stop the queue worker (idempotent)."""
        self._stopped = True
        if self._drift_owner and self._drift is not None:
            self._drift.close()
        worker = self._worker
        if worker is not None and worker.is_alive():
            self._queue.put(_SHUTDOWN)
            # a deferred registry offload can land on the worker itself
            # (future done-callbacks run on the resolving thread) — the
            # pill above still drains it, just don't self-join
            if worker is not threading.current_thread():
                worker.join(timeout=5.0)

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def stats(self) -> Dict[str, Any]:
        """Warmup + steady-state counters; ``compiles_since_warmup`` must
        stay 0 on a warmed engine (the acceptance criterion the serving
        tests and ``bench.py`` assert via ``jax.monitoring``)."""
        c, s = compile_snapshot()
        with self._lock:
            compiled = {
                (f"{k[0]}@{k[1]}" if len(k) == 2 else f"{k[0]}@{k[1]}~{k[2]}"):
                    self._compile_s.get(k)
                for k in sorted(self._compiled)
            }
        return {
            "buckets": self._buckets,
            "methods": self._methods,
            "prefix_tiers": self._prefix_tiers,
            "donate": self._donate,
            "compiled": compiled,
            "compiles_since_warmup": c - self._warm_snapshot[0],
            "compile_s_since_warmup": s - self._warm_snapshot[1],
            "packed_bytes": self._packed.nbytes,
            "drift_enabled": self._drift_enabled,
            "drift": (
                self._drift.snapshot() if self._drift is not None else None
            ),
        }
