"""Serving subsystem: packed export, bucketed AOT inference, model registry.

The training side of this package ends at ``est.fit(X, y) -> model``; this
subpackage is the inference side the ROADMAP's "serves heavy traffic" north
star needs (the reference library stops at ``model.transform(df)`` — no
export format, no batching, no warmup).  Three parts (docs/serving.md):

- :mod:`spark_ensemble_tpu.serving.export` — ``pack(model)`` compacts any
  fitted ensemble into a :class:`PackedModel` (flat dict of stacked device
  arrays + static JSON metadata) with a versioned sha256-manifested on-disk
  artifact and **bit-identical** predictions;
- :mod:`spark_ensemble_tpu.serving.engine` — :class:`InferenceEngine` pads
  requests into power-of-two batch buckets, AOT-compiles each bucket at
  startup (``jax.jit(...).lower().compile()``), and serves synchronously or
  through a micro-batching queue that coalesces many small callers into one
  device dispatch;
- :mod:`spark_ensemble_tpu.serving.registry` — :class:`ModelRegistry`, a
  thread-safe multi-model registry with LRU eviction of device buffers.

All three emit ``model_packed`` / ``engine_warmup`` / ``request_served``
events through :mod:`spark_ensemble_tpu.telemetry`, so
``tools/telemetry_report.py`` renders serving traces unchanged.
"""

from spark_ensemble_tpu.serving.export import (
    PACKED_FORMAT_VERSION,
    PackedModel,
    load_packed,
    pack,
)
from spark_ensemble_tpu.serving.engine import InferenceEngine
from spark_ensemble_tpu.serving.registry import ModelRegistry

__all__ = [
    "PACKED_FORMAT_VERSION",
    "PackedModel",
    "pack",
    "load_packed",
    "InferenceEngine",
    "ModelRegistry",
]
