"""Serving subsystem: packed export, bucketed AOT inference, model registry,
and a fault-tolerant replicated fleet.

The training side of this package ends at ``est.fit(X, y) -> model``; this
subpackage is the inference side the ROADMAP's "serves heavy traffic" north
star needs (the reference library stops at ``model.transform(df)`` — no
export format, no batching, no warmup).  Four parts (docs/serving.md,
docs/fleet.md):

- :mod:`spark_ensemble_tpu.serving.export` — ``pack(model)`` compacts any
  fitted ensemble into a :class:`PackedModel` (flat dict of stacked device
  arrays + static JSON metadata) with a versioned sha256-manifested on-disk
  artifact, **bit-identical** predictions, and ``take(k)`` ensemble-prefix
  slices (bit-identical to a k-round fit);
- :mod:`spark_ensemble_tpu.serving.engine` — :class:`InferenceEngine` pads
  requests into power-of-two batch buckets, AOT-compiles each bucket (and
  each configured prefix tier) at startup
  (``jax.jit(...).lower().compile()``), and serves synchronously or
  through a micro-batching queue that coalesces many small callers into one
  device dispatch;
- :mod:`spark_ensemble_tpu.serving.registry` — :class:`ModelRegistry`, a
  thread-safe multi-model registry with LRU eviction of device buffers and
  pin-until-reply leases (hot-swap can never free an in-flight version);
- :mod:`spark_ensemble_tpu.serving.fleet` — :class:`FleetRouter`, N
  replicated engines behind health-checked queue-depth routing, hedged
  retries under a deadline budget, per-replica circuit breakers, and
  graceful ensemble-prefix degradation.

All of it emits ``model_packed`` / ``engine_warmup`` / ``request_served`` /
``fleet_request`` / ``replica_state`` / ``fleet_slo`` events through
:mod:`spark_ensemble_tpu.telemetry`, so ``tools/telemetry_report.py``
renders serving traces unchanged.

:mod:`spark_ensemble_tpu.serving.autopilot` closes the loop
(docs/autopilot.md): :class:`Autopilot` turns watchdog verdicts into fleet
actions — elastic scaling, warm-start refresh fits (``fit_resume``), and
automatic rollback — each a torn-free rolling swap over the registry.

The model-quality plane rides on top (docs/quality.md): packed models
carry their fit-time bin reference (``PackedModel.quality``), engines fuse
a per-feature drift sketch into the cached predict programs, and the fleet
adds sampled staged attribution + shadow scoring
(:mod:`spark_ensemble_tpu.telemetry.quality`).
"""

from spark_ensemble_tpu.serving.autopilot import Autopilot
from spark_ensemble_tpu.serving.export import (
    PACKED_FORMAT_VERSION,
    PackedModel,
    fit_resume,
    load_packed,
    pack,
)
from spark_ensemble_tpu.serving.engine import InferenceEngine
from spark_ensemble_tpu.serving.fleet import (
    REPLICA_STATES,
    FleetDeadlineError,
    FleetOverloadError,
    FleetResponse,
    FleetRouter,
)
from spark_ensemble_tpu.serving.registry import ModelRegistry

__all__ = [
    "PACKED_FORMAT_VERSION",
    "Autopilot",
    "PackedModel",
    "pack",
    "fit_resume",
    "load_packed",
    "InferenceEngine",
    "ModelRegistry",
    "REPLICA_STATES",
    "FleetDeadlineError",
    "FleetOverloadError",
    "FleetResponse",
    "FleetRouter",
]
