"""Resilient serving fleet: replicated engines, health-checked routing,
hedged retries, and graceful ensemble-prefix degradation.

A single :class:`InferenceEngine` has no failure story: one stalled worker
or one slow reply stalls every caller behind it.  :class:`FleetRouter` puts
a fault-tolerance tier above the engine:

- **Replication without recompilation**: N replicas are
  :meth:`InferenceEngine.clone`\\ s of one warmed engine — each has its own
  request queue and worker thread, but all share the same AOT-compiled
  programs and device arrays, so fleet warmup costs O(methods x buckets x
  tiers), not x N, and steady-state serving stays zero-compile.
- **Health-checked routing**: requests go to the live replica with the
  shallowest queue.  Every replica runs a circuit breaker
  (``healthy -> degraded -> ejected -> half_open``): failures degrade it,
  a failure streak or an injected crash ejects it, and after a
  :class:`~spark_ensemble_tpu.robustness.retry.RetryPolicy` backoff a
  single half-open probe request decides re-admission.
- **Hedged retries under a deadline budget**: every request carries a
  deadline; if the first dispatch has not replied by the live p99 latency
  estimate, a second dispatch fires on another replica and the first
  completion wins (duplicate completions are dropped at the Future, never
  delivered twice).
- **Graceful ensemble-prefix degradation**: boosted ensembles are
  stagewise, so the first k rounds of a GBM ARE a valid (bit-identical to
  a k-round fit) cheaper model — :meth:`PackedModel.take`.  Under deadline
  pressure or queue buildup the router serves a pre-warmed prefix tier and
  marks the response ``degraded=True`` instead of shedding; a staged
  load-shedder (:class:`FleetOverloadError`) is the last resort.
- **Crash semantics**: a replica death (chaos ``replica_crash`` or
  :meth:`kill_replica`) drains that replica's queue and replays every
  unanswered request on a healthy replica — zero lost and zero duplicated
  responses, pinned by the chaos serving battery.
- **Torn-free hot swap + elastic width**: :meth:`swap_model` replaces the
  served model replica-by-replica under live traffic — each replica leaves
  rotation, drains, rebinds to a clone of the new (already warmed) engine,
  and re-admits — so every response is computed entirely by exactly one
  model version and a registry-leased swap adds ZERO compiles.
  :meth:`add_replica` / :meth:`remove_replica` resize the fleet the same
  way (clone in, drain out), and ``serving/autopilot.py`` closes the loop
  by driving all three from watchdog verdicts.

Per-replica SLO telemetry flows through the existing serving event stream
(``fleet_request`` / ``replica_state`` / ``hedge_fired`` / ``request_shed``
/ ``fleet_slo``; docs/telemetry.md), and the whole state machine is
deterministically drivable in CI via the chaos serving faults
(``replica_stall`` / ``replica_crash`` / ``slow_reply``; docs/fleet.md).
"""

from __future__ import annotations

import collections
import dataclasses
import queue as queue_mod
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from spark_ensemble_tpu.robustness.chaos import ChaosReplicaCrash, controller
from spark_ensemble_tpu.robustness.retry import RetryPolicy
from spark_ensemble_tpu.serving.engine import InferenceEngine
from spark_ensemble_tpu.telemetry.quality import staged_attribution
from spark_ensemble_tpu.telemetry.events import (
    compile_snapshot,
    emit_event,
    global_metrics,
    serving_stream_id,
    telemetry_sink_active,
)
from spark_ensemble_tpu.telemetry.trace import NULL_SPAN, Tracer, new_flow_id

__all__ = [
    "REPLICA_STATES",
    "FleetDeadlineError",
    "FleetOverloadError",
    "FleetResponse",
    "FleetRouter",
]

REPLICA_STATES = ("healthy", "degraded", "ejected", "half_open", "swapping")

_SHUTDOWN = object()
_KILL = object()


class FleetOverloadError(RuntimeError):
    """Staged shedding's last resort: every degradation lever (hedging,
    prefix tiers) is exhausted and queues are still past ``shed_depth`` —
    or no live replica exists to route to."""


class FleetDeadlineError(TimeoutError):
    """A synchronous :meth:`FleetRouter.predict` wait outlived its grace
    window (``deadline_ms x deadline_grace``) with no replica reply."""


@dataclasses.dataclass
class FleetResponse:
    """One served request: the prediction plus how it was served.

    ``degraded`` is the explicit contract flag: ``True`` iff the value was
    computed by an ensemble-prefix tier (``tier`` = member count) rather
    than the full model.

    The quality fields are populated only for attribution-sampled requests
    (``attribution_fraction``; telemetry/quality.py): ``staged_margins``
    maps each prefix-tier member count to its disagreement with the full
    model, ``uncertainty`` is the max disagreement (per-member
    disagreement score), and ``quality_flagged`` marks it crossing the
    router's ``uncertainty_threshold``."""

    value: np.ndarray
    tier: int
    degraded: bool
    replica: str
    hedged: bool
    replays: int
    latency_ms: float
    uncertainty: Optional[float] = None
    staged_margins: Optional[Dict[str, float]] = None
    quality_flagged: bool = False
    # model generation that computed this value (bumped by swap_model);
    # the torn-free contract: exactly ONE version per response, ever
    version: int = 0


class _FleetRequest:
    __slots__ = (
        "seq", "X", "method", "tier", "deadline_at", "t_submit",
        "future", "outstanding", "replays", "hedged", "hedge_timer",
        "primary", "span", "flow_in",
    )

    def __init__(self, seq, X, method, tier, deadline_at, t_submit):
        self.seq = seq
        self.X = X
        self.method = method
        self.tier = tier
        self.deadline_at = deadline_at
        self.t_submit = t_submit
        self.future: Future = Future()
        self.outstanding = 0   # dispatches not yet succeeded/failed
        self.replays = 0
        self.hedged = False
        self.hedge_timer: Optional[threading.Timer] = None
        self.primary: Optional[str] = None
        # causal tracing (telemetry/trace.py): the request's span on the
        # router track, and a (replica_name, flow_id) pair the NEXT serve
        # on that replica consumes as its incoming hedge/replay arrow
        self.span = NULL_SPAN
        self.flow_in: Optional[Tuple[str, int]] = None


class _Replica:
    __slots__ = (
        "name", "engine", "queue", "worker", "state", "inflight",
        "fail_streak", "slow_streak", "ok_streak", "ejections",
        "reopen_at", "probing", "served", "failed", "latencies",
        "transitions", "version",
    )

    def __init__(self, name: str, engine: InferenceEngine, version: int = 0):
        self.name = name
        self.engine = engine
        self.version = version
        self.queue: "queue_mod.SimpleQueue" = queue_mod.SimpleQueue()
        self.worker: Optional[threading.Thread] = None
        self.state = "healthy"
        self.inflight = 0          # dispatched to this replica, unanswered
        self.fail_streak = 0
        self.slow_streak = 0
        self.ok_streak = 0
        self.ejections = 0
        self.reopen_at = 0.0       # monotonic time the breaker half-opens
        self.probing = False
        self.served = 0
        self.failed = 0
        self.latencies: "collections.deque" = collections.deque(maxlen=512)
        self.transitions = 0


def _quantile_ms(window, q: float, default_ms: float) -> float:
    if not window:
        return default_ms
    xs = sorted(window)
    i = min(int(q * len(xs)), len(xs) - 1)
    return xs[i]


class FleetRouter:
    """Route requests across N replicated engines with breakers, hedging,
    and prefix degradation (see module docstring).

    Parameters
    ----------
    model:
        A fitted model, :class:`PackedModel`, or an already-warmed
        :class:`InferenceEngine` (e.g. from a shared
        :class:`~spark_ensemble_tpu.serving.registry.ModelRegistry` via
        :meth:`from_registry`).  Anything else is packed and warmed here.
    replicas:
        Replica count; each is a :meth:`clone` sharing the warm programs.
    prefix_tiers:
        Ensemble-prefix tiers to pre-warm for degradation (ignored when
        ``model`` is an engine — its tiers are used).  One or two tiers
        give the staged ladder: mild pressure serves the largest prefix,
        severe pressure the smallest.
    deadline_ms:
        Default per-request deadline budget: drives tier selection at
        dispatch, the hedge-timer clamp, and the sync-predict grace wait.
    hedge_init_ms:
        Hedge-timer seed before any latency history exists; afterwards the
        timer fires at the live p99 estimate.
    degrade_depth / shed_depth:
        Queue-depth stages: past ``degrade_depth`` requests serve prefix
        tiers; past ``shed_depth`` they shed (:class:`FleetOverloadError`).
    eject_after / recover_after / slow_ms / slow_streak_limit:
        Breaker tuning: consecutive failures to eject, consecutive
        successes to re-promote a degraded replica, and what counts as a
        slow serve (a streak of which degrades).
    breaker_backoff:
        :class:`RetryPolicy` whose deterministic ``delay(replica, n)``
        schedules the n-th ejection's half-open probe.
    drift / drift_window:
        Forwarded to the base :class:`InferenceEngine` when the fleet
        builds it: on-device feature-drift sketching over the packed
        model's fit-time bin reference (telemetry/quality.py).  All
        replicas share one :class:`DriftMonitor`, so the window stream is
        fleet-wide.  Ignored when ``model`` is already an engine.
    attribution_fraction / uncertainty_threshold:
        Staged attribution sampling: every ``1/fraction``-th full-model
        request is decomposed over the prefix tiers (deterministic
        ``seq``-based sampling, no RNG) and its ``FleetResponse`` carries
        ``staged_margins`` / ``uncertainty`` / ``quality_flagged``.
        ``0.0`` (default) keeps the serve path at exactly one program
        dispatch per request — the tier-2 quality contract.
    shadow:
        Optional :class:`~spark_ensemble_tpu.telemetry.quality
        .ShadowScorer`; sees every delivered full-tier request AFTER the
        reply resolves (sampling happens inside the scorer).  The caller
        owns its lifecycle (``close()``).
    """

    def __init__(
        self,
        model,
        *,
        replicas: int = 2,
        methods: Tuple[str, ...] = ("predict",),
        prefix_tiers: Tuple[int, ...] = (),
        min_bucket: int = 8,
        max_batch_size: int = 256,
        deadline_ms: float = 250.0,
        deadline_grace: float = 4.0,
        hedge_init_ms: float = 25.0,
        hedge_min_ms: float = 1.0,
        degrade_depth: int = 8,
        shed_depth: int = 64,
        max_replays: Optional[int] = None,
        eject_after: int = 3,
        recover_after: int = 8,
        slow_ms: float = 250.0,
        slow_streak_limit: int = 3,
        breaker_backoff: Optional[RetryPolicy] = None,
        donate: Optional[bool] = None,
        label: str = "fleet",
        telemetry_path: Optional[str] = None,
        drift: Optional[bool] = None,
        drift_window: int = 2048,
        attribution_fraction: float = 0.0,
        uncertainty_threshold: float = 0.5,
        shadow=None,
    ):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1; got {replicas}")
        if not (0.0 <= float(attribution_fraction) <= 1.0):
            raise ValueError(
                "attribution_fraction must be in [0, 1]; got "
                f"{attribution_fraction}"
            )
        # a router-built base engine is router-owned: stop() must stop it
        # so its drift monitor's quality/* source dies with the fleet (an
        # injected engine stays caller-owned, e.g. from_registry leases)
        self._owns_base = not isinstance(model, InferenceEngine)
        if isinstance(model, InferenceEngine):
            base = model
        else:
            base = InferenceEngine(
                model,
                methods=methods,
                prefix_tiers=prefix_tiers,
                min_bucket=min_bucket,
                max_batch_size=max_batch_size,
                donate=donate,
                warm=True,
                label=f"{label}:warm",
                telemetry_path=telemetry_path,
                drift=drift,
                drift_window=drift_window,
            )
        self._base = base
        self._tiers = base.prefix_tiers  # ascending member counts
        self._deadline_s = float(deadline_ms) / 1e3
        self._deadline_grace = float(deadline_grace)
        self._hedge_init_s = float(hedge_init_ms) / 1e3
        self._hedge_min_s = float(hedge_min_ms) / 1e3
        self._degrade_depth = int(degrade_depth)
        self._shed_depth = int(shed_depth)
        self._max_replays = (
            int(max_replays) if max_replays is not None else int(replicas)
        )
        self._eject_after = int(eject_after)
        self._recover_after = int(recover_after)
        self._slow_s = float(slow_ms) / 1e3
        self._slow_streak_limit = int(slow_streak_limit)
        self._backoff = breaker_backoff or RetryPolicy(
            max_retries=0, base_delay=0.25, max_delay=5.0
        )
        self._label = label
        self._telemetry_path = telemetry_path
        self._stream = serving_stream_id(label)
        self._tracer = Tracer(self._emit_trace, thread="router")
        self._t_start = time.time()
        self._metrics = global_metrics()
        # live statusz/SLO source: MetricsRegistry.snapshot() pulls this
        # router's counters on demand (one-stop process snapshot)
        self._source_name = f"fleet/{self._stream}"
        self._metrics.register_source(self._source_name, self.slo_snapshot)
        self._lock = threading.Lock()
        # control-plane lock: serializes swap_model/add_replica/
        # remove_replica against each other (the hot `_lock` is never held
        # across a rebind's quiesce wait)
        self._ctl_lock = threading.Lock()
        self._seq = 0
        self._version = 0
        self._next_replica_idx = int(replicas)
        self._stopped = False
        self._registry = None
        self._registry_name = None
        self._registry_release = None
        self._window: "collections.deque" = collections.deque(maxlen=256)
        self._counters = {
            "requests": 0, "hedges_fired": 0, "hedges_won": 0,
            "shed": 0, "degraded": 0, "replays": 0, "crashes": 0,
            "attributed": 0, "quality_flagged": 0,
            "swaps": 0, "scale_ups": 0, "scale_downs": 0,
        }
        # model-quality plane (telemetry/quality.py, docs/quality.md):
        # every 1/attribution_fraction-th full-model request is decomposed
        # over the pre-warmed prefix tiers (staged margins + per-member
        # disagreement as uncertainty).  Attribution is the ONE quality
        # layer that adds dispatches (one per tier, all pre-warmed), which
        # is why it defaults off; the drift sketch rides inside the predict
        # programs and the shadow scorer samples after delivery.
        self._attr_period = (
            max(1, int(round(1.0 / float(attribution_fraction))))
            if float(attribution_fraction) > 0.0
            else 0
        )
        self._uncertainty_threshold = float(uncertainty_threshold)
        self._shadow = shadow
        self._replicas = [
            _Replica(f"{label}:r{i}", base.clone(f"{label}:r{i}"))
            for i in range(int(replicas))
        ]
        for rep in self._replicas:
            self._ensure_worker(rep)
        # warm boundary for the zero-steady-state-compile contract: every
        # program (full + prefix tiers) exists before the first request
        self._warm_snapshot = compile_snapshot()

    # -- registry integration ----------------------------------------------

    @classmethod
    def from_registry(cls, registry, name: str, **opts) -> "FleetRouter":
        """A fleet over a :class:`ModelRegistry` entry, sharing its warmed
        engine's compiled programs and pinning the entry against LRU
        eviction until :meth:`stop` (the registry's lease machinery — a
        hot-swap cannot free buffers under a live fleet)."""
        engine = registry._acquire(name)
        try:
            router = cls(engine, **opts)
        except BaseException:
            registry._release(name)
            raise
        router._registry = registry
        router._registry_name = name
        router._registry_release = lambda: registry._release(name)
        return router

    # -- routing -----------------------------------------------------------

    def _emit_trace(self, rec: Dict[str, Any]) -> None:
        # span chokepoint: spans ride the same standalone-event sinks as
        # the fleet's SLO events, tagged with this router's stream id
        rec = dict(rec)
        emit_event(
            rec.pop("event"), path=self._telemetry_path,
            fit_id=self._stream, **rec,
        )

    def _set_state(self, rep: _Replica, state: str, reason: str) -> None:
        # called under self._lock; telemetry goes out band via a timer-free
        # emit (file append) — cheap enough to keep transitions atomic
        prev, rep.state = rep.state, state
        if prev == state:
            return
        rep.transitions += 1
        emit_event(
            "replica_state",
            path=self._telemetry_path,
            fit_id=self._stream,
            replica=rep.name,
            state=state,
            prev=prev,
            reason=reason,
            ejections=rep.ejections,
        )
        self._metrics.counter("fleet/breaker_transitions").inc()

    def _pick(self, exclude: Set[str]) -> Optional[_Replica]:
        """Routing policy, called under ``self._lock``: due half-open
        probes first (one request decides re-admission), then the
        shallowest healthy queue; degraded replicas stay in rotation with
        a depth penalty so a lone healthy replica is not overloaded."""
        now = time.monotonic()
        for rep in self._replicas:
            if rep.state == "ejected" and now >= rep.reopen_at:
                self._set_state(rep, "half_open", "backoff elapsed")
                rep.probing = False
        for rep in self._replicas:
            if (
                rep.state == "half_open"
                and not rep.probing
                and rep.name not in exclude
            ):
                rep.probing = True
                self._ensure_worker(rep)
                return rep
        cands = [
            (rep.inflight + (4 if rep.state == "degraded" else 0), i, rep)
            for i, rep in enumerate(self._replicas)
            if rep.state in ("healthy", "degraded")
            and rep.name not in exclude
        ]
        if not cands:
            return None
        return min(cands)[2]

    def _choose_tier(self, remaining_s: float, depth: int) -> int:
        """Staged degradation: mild pressure serves the largest prefix,
        severe pressure the smallest; no tiers configured means the full
        model always (shedding is then the only pressure valve)."""
        if not self._tiers:
            return 0
        p99 = self._p99_s()
        severe = (
            remaining_s < 0.5 * p99 or depth >= 2 * self._degrade_depth
        )
        moderate = remaining_s < p99 or depth >= self._degrade_depth
        if severe:
            return self._tiers[0]
        if moderate:
            return self._tiers[-1]
        return 0

    def _p99_s(self) -> float:
        return _quantile_ms(self._window, 0.99, self._hedge_init_s * 1e3) / 1e3

    def _dispatch(self, req: _FleetRequest, rep: _Replica) -> None:
        # called under self._lock
        rep.inflight += 1
        req.outstanding += 1
        rep.queue.put(req)

    def submit(
        self,
        X,
        method: str = "predict",
        deadline_ms: Optional[float] = None,
    ) -> Future:
        """Route a request; the Future resolves to a :class:`FleetResponse`
        (or raises: shed, no live replica, or replay budget exhausted)."""
        if self._stopped:
            raise RuntimeError("fleet is stopped")
        # validate shape HERE: a malformed request must fail the caller,
        # not look like a replica fault and trip its breaker
        self._base._normalize(X)
        deadline_s = (
            self._deadline_s if deadline_ms is None else float(deadline_ms) / 1e3
        )
        t0 = time.perf_counter()
        with self._lock:
            self._seq += 1
            self._counters["requests"] += 1
            rep = self._pick(exclude=set())
            if rep is None:
                self._counters["shed"] += 1
                shed_reason = "no live replica"
            elif rep.inflight >= self._shed_depth:
                self._counters["shed"] += 1
                shed_reason = f"queue depth {rep.inflight} >= {self._shed_depth}"
            else:
                shed_reason = None
                tier = self._choose_tier(deadline_s, rep.inflight)
                req = _FleetRequest(
                    self._seq, np.asarray(X, np.float32), method, tier,
                    t0 + deadline_s, t0,
                )
                req.primary = rep.name
                if telemetry_sink_active(self._telemetry_path):
                    # root of this request's causal tree on the router
                    # track; ends in _resolve/_fail — on a worker thread,
                    # so no same-thread jax profiler annotation
                    req.span = self._tracer.begin_span(
                        "fleet_request", annotate=False,
                        seq=self._seq, method=method, tier=tier,
                    )
                self._dispatch(req, rep)
        if shed_reason is not None:
            emit_event(
                "request_shed",
                path=self._telemetry_path,
                fit_id=self._stream,
                reason=shed_reason,
            )
            self._metrics.counter("fleet/shed").inc()
            raise FleetOverloadError(f"request shed: {shed_reason}")
        self._arm_hedge(req, deadline_s)
        return req.future

    def predict(
        self,
        X,
        method: str = "predict",
        deadline_ms: Optional[float] = None,
    ) -> FleetResponse:
        """Synchronous :meth:`submit`; waits up to ``deadline x grace``
        then raises :class:`FleetDeadlineError`."""
        deadline_s = (
            self._deadline_s if deadline_ms is None else float(deadline_ms) / 1e3
        )
        fut = self.submit(X, method=method, deadline_ms=deadline_s * 1e3)
        try:
            return fut.result(timeout=deadline_s * self._deadline_grace)
        except (_FutureTimeout, TimeoutError) as e:  # distinct until 3.11
            raise FleetDeadlineError(
                f"no reply within {deadline_s * self._deadline_grace:.3f}s "
                f"(deadline {deadline_s:.3f}s x grace {self._deadline_grace})"
            ) from e

    # -- hedging -----------------------------------------------------------

    def _arm_hedge(self, req: _FleetRequest, deadline_s: float) -> None:
        if len(self._replicas) < 2:
            return
        hedge_s = min(max(self._p99_s(), self._hedge_min_s), 0.8 * deadline_s)
        timer = threading.Timer(hedge_s, self._fire_hedge, args=(req,))
        timer.daemon = True
        req.hedge_timer = timer
        timer.start()

    def _fire_hedge(self, req: _FleetRequest) -> None:
        if req.future.done():
            return
        with self._lock:
            if req.hedged or req.future.done():
                return
            rep = self._pick(exclude={req.primary} if req.primary else set())
            if rep is None:
                return
            req.hedged = True
            self._counters["hedges_fired"] += 1
            fid = None
            if req.span:
                # flow arrow request-span -> the hedge twin's serve span
                fid = new_flow_id()
                req.span.attrs.setdefault("flow_out", []).append(fid)
                req.flow_in = (rep.name, fid)
            self._dispatch(req, rep)
        emit_event(
            "hedge_fired",
            path=self._telemetry_path,
            fit_id=self._stream,
            seq=req.seq,
            primary=req.primary,
            hedge=rep.name,
            flow=fid,
        )
        self._metrics.counter("fleet/hedges").inc()

    # -- replica workers ---------------------------------------------------

    def _ensure_worker(self, rep: _Replica) -> None:
        if rep.worker is None or not rep.worker.is_alive():
            rep.worker = threading.Thread(
                target=self._worker_loop,
                args=(rep,),
                name=f"se-tpu-{rep.name}",
                daemon=True,
            )
            rep.worker.start()

    def _worker_loop(self, rep: _Replica) -> None:
        while True:
            item = rep.queue.get()
            if item is _SHUTDOWN:
                return
            if item is _KILL:
                self._on_crash(rep, None, ChaosReplicaCrash("killed"))
                return
            req: _FleetRequest = item
            if req.future.done():
                with self._lock:
                    rep.inflight -= 1
                    req.outstanding -= 1
                continue
            try:
                self._serve_on(rep, req)
            except ChaosReplicaCrash as e:
                self._on_crash(rep, req, e)
                return
            except Exception as e:  # breaker food, never a worker death
                if self._on_failure(rep, req, e):
                    return

    def _serve_on(self, rep: _Replica, req: _FleetRequest) -> None:
        # a hedge/replay flow arrow targets ONE replica: only the serve
        # that actually runs on it consumes the arrow (the original
        # dispatch on the primary must not claim a hedge's flow id)
        fid = None
        fe = req.flow_in
        if fe is not None and fe[0] == rep.name:
            fid = fe[1]
            req.flow_in = None
        serve_sp = (
            self._tracer.begin_span(
                "serve", parent=req.span, thread=rep.name, annotate=False,
                seq=req.seq, replica=rep.name, tier=req.tier,
                flow_in=fid,
            )
            if req.span else NULL_SPAN
        )
        with serve_sp:
            self._serve_on_inner(rep, req, serve_sp)

    def _serve_on_inner(
        self, rep: _Replica, req: _FleetRequest, serve_sp
    ) -> None:
        ctrl = controller()
        site = f"{self._label}:{rep.name}:req{req.seq}"
        # snapshot the bound engine + version ONCE: the whole serve — the
        # predict AND any staged attribution — runs against one model
        # generation even if a rolling swap rebinds the replica meanwhile
        # (it cannot while this serve is in flight, but the single read
        # makes the no-torn-response invariant structural, not scheduled)
        eng = rep.engine
        version = rep.version
        stall = ctrl.stall_s(site)
        if stall:
            time.sleep(stall)  # a stuck replica: hedge timer's territory
        ctrl.crash(site)  # may raise ChaosReplicaCrash
        t0 = time.perf_counter()
        out = eng.predict(req.X, method=req.method, tier=req.tier)
        slow = ctrl.slow_s(site)
        if slow:
            time.sleep(slow)  # alive but slow: breaker's slow streak
        serve_s = time.perf_counter() - t0
        # staged attribution (telemetry/quality.py): sampled full-model
        # requests are decomposed over the pre-warmed prefix tiers BEFORE
        # delivery, so the caller's FleetResponse carries the fields
        attribution = None
        if (
            self._attr_period
            and req.tier == 0
            and self._tiers
            and req.seq % self._attr_period == 0
        ):
            attribution = staged_attribution(
                eng, req.X, method=req.method,
                uncertainty_threshold=self._uncertainty_threshold,
                full=out,
            )
            self._metrics.histogram("quality/uncertainty").record(
                attribution["uncertainty"]
            )
            with self._lock:
                self._counters["attributed"] += 1
                if attribution["flagged"]:
                    self._counters["quality_flagged"] += 1
        now = time.perf_counter()
        resp = FleetResponse(
            value=out,
            tier=req.tier,
            degraded=req.tier != 0,
            replica=rep.name,
            hedged=req.hedged,
            replays=req.replays,
            latency_ms=(now - req.t_submit) * 1e3,
            uncertainty=(
                attribution["uncertainty"] if attribution else None
            ),
            staged_margins=(
                attribution["margins"] if attribution else None
            ),
            quality_flagged=(
                attribution["flagged"] if attribution else False
            ),
            version=version,
        )
        delivered = self._resolve(req, resp)
        if delivered and self._shadow is not None and req.tier == 0:
            # shadow scoring rides AFTER delivery: the candidate's eval can
            # never add latency to the answer the caller already has
            try:
                self._shadow.observe(req.X, out, request_id=req.seq)
            except Exception:  # noqa: BLE001 - quality plane never breaks serving
                pass
        serve_sp.add(delivered=delivered, serve_ms=serve_s * 1e3)
        with self._lock:
            rep.inflight -= 1
            req.outstanding -= 1
            rep.served += 1
            rep.fail_streak = 0
            rep.latencies.append(serve_s * 1e3)
            if delivered:
                self._window.append(resp.latency_ms)
                if resp.degraded:
                    self._counters["degraded"] += 1
                if resp.hedged and req.primary != rep.name:
                    self._counters["hedges_won"] += 1
            if serve_s + (slow or 0.0) > self._slow_s:
                rep.slow_streak += 1
                rep.ok_streak = 0
                if (
                    rep.state == "healthy"
                    and rep.slow_streak >= self._slow_streak_limit
                ):
                    self._set_state(rep, "degraded", "slow streak")
            else:
                rep.slow_streak = 0
                rep.ok_streak += 1
                if rep.state == "half_open":
                    rep.probing = False
                    rep.ejections = 0
                    self._set_state(rep, "healthy", "probe succeeded")
                elif (
                    rep.state == "degraded"
                    and rep.ok_streak >= self._recover_after
                ):
                    self._set_state(rep, "healthy", "recovered")
        if delivered:
            emit_event(
                "fleet_request",
                path=self._telemetry_path,
                fit_id=self._stream,
                seq=req.seq,
                replica=rep.name,
                method=req.method,
                rows=int(np.shape(req.X)[0]) if np.ndim(req.X) > 1 else 1,
                tier=req.tier,
                degraded=resp.degraded,
                hedged=resp.hedged,
                replays=req.replays,
                latency_ms=resp.latency_ms,
                version=resp.version,
                # attribution-sampled requests carry their uncertainty so
                # telemetry_report can quantile it offline
                **(
                    {
                        "uncertainty": resp.uncertainty,
                        "quality_flagged": resp.quality_flagged,
                    }
                    if resp.uncertainty is not None
                    else {}
                ),
            )
            self._metrics.counter("fleet/requests").inc()
            self._metrics.histogram("fleet/latency_ms").record(
                resp.latency_ms
            )

    def _resolve(self, req: _FleetRequest, resp: FleetResponse) -> bool:
        try:
            req.future.set_result(resp)
        except InvalidStateError:
            return False  # the other dispatch won; drop, never duplicate
        if req.hedge_timer is not None:
            req.hedge_timer.cancel()
        req.span.end(
            replica=resp.replica, hedged=resp.hedged, replays=resp.replays,
            degraded=resp.degraded, latency_ms=resp.latency_ms,
        )
        return True

    # -- failure / crash handling ------------------------------------------

    def _eject(self, rep: _Replica, reason: str) -> None:
        # called under self._lock
        rep.ejections += 1
        rep.probing = False
        rep.reopen_at = time.monotonic() + self._backoff.delay(
            rep.name, rep.ejections
        )
        self._set_state(rep, "ejected", reason)

    def _drain(self, rep: _Replica) -> List[_FleetRequest]:
        # called under self._lock: pull every queued request off a dead
        # replica so it can be replayed elsewhere
        drained: List[_FleetRequest] = []
        while True:
            try:
                item = rep.queue.get_nowait()
            except queue_mod.Empty:
                return drained
            if item in (_SHUTDOWN, _KILL):
                continue
            rep.inflight -= 1
            item.outstanding -= 1
            drained.append(item)

    def _redispatch(
        self, req: _FleetRequest, exclude: Set[str], error: BaseException
    ) -> None:
        # called under self._lock
        if req.future.done():
            return
        if req.replays >= self._max_replays:
            self._fail(req, error)
            return
        rep = self._pick(exclude)
        if rep is None and exclude:
            rep = self._pick(set())  # better a suspect replica than a loss
        if rep is None:
            if req.outstanding <= 0:
                self._fail(
                    req, FleetOverloadError("no live replica to replay on")
                )
            return
        req.replays += 1
        self._counters["replays"] += 1
        if req.span:
            # flow arrow request-span -> the replayed serve's span
            fid = new_flow_id()
            req.span.attrs.setdefault("flow_out", []).append(fid)
            req.flow_in = (rep.name, fid)
        self._dispatch(req, rep)

    @staticmethod
    def _fail(req: _FleetRequest, error: BaseException) -> None:
        try:
            req.future.set_exception(error)
        except InvalidStateError:
            pass  # a racing dispatch delivered first — the caller won
        else:
            req.span.end(error=type(error).__name__)

    def _on_crash(
        self,
        rep: _Replica,
        req: Optional[_FleetRequest],
        error: ChaosReplicaCrash,
    ) -> None:
        with self._lock:
            self._counters["crashes"] += 1
            rep.failed += 1
            if req is not None:
                rep.inflight -= 1
                req.outstanding -= 1
            self._eject(rep, f"crash: {error}")
            pending = self._drain(rep)
            if req is not None and not req.future.done():
                pending.insert(0, req)
            for p in pending:
                self._redispatch(p, {rep.name}, error)
        self._metrics.counter("fleet/crashes").inc()

    def _on_failure(
        self, rep: _Replica, req: _FleetRequest, error: BaseException
    ) -> bool:
        """Breaker bookkeeping for a non-crash serve failure; returns True
        when the replica was ejected (its worker thread exits)."""
        with self._lock:
            rep.inflight -= 1
            req.outstanding -= 1
            rep.failed += 1
            rep.fail_streak += 1
            rep.ok_streak = 0
            ejected = False
            if rep.state == "half_open":
                self._eject(rep, f"probe failed: {type(error).__name__}")
                ejected = True
            elif rep.fail_streak >= self._eject_after:
                self._eject(rep, f"fail streak: {type(error).__name__}")
                ejected = True
            elif rep.state == "healthy":
                self._set_state(rep, "degraded", type(error).__name__)
            self._redispatch(req, {rep.name}, error)
            if ejected:
                for p in self._drain(rep):
                    self._redispatch(p, {rep.name}, error)
            return ejected

    # -- fault injection (bench / tests) -----------------------------------

    def kill_replica(self, name: Optional[str] = None) -> str:
        """Deterministically crash one replica (default: the first live
        one): its worker dies mid-queue and the crash path drains/replays
        exactly like a chaos ``replica_crash``."""
        with self._lock:
            live = [
                r for r in self._replicas
                if r.state in ("healthy", "degraded")
            ]
            if name is not None:
                live = [r for r in self._replicas if r.name == name]
            if not live:
                raise ValueError(f"no live replica to kill (name={name!r})")
            rep = live[0]
            rep.queue.put(_KILL)
            return rep.name

    # -- hot swap / elastic width ------------------------------------------

    def _quiesce(self, rep: _Replica, timeout_s: float = 30.0) -> None:
        """Wait for a replica already OUT of rotation (drained queue, no
        routable state) to finish its in-flight serve, then stop its worker
        thread.  Called under ``_ctl_lock`` only — never under ``_lock``."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if rep.inflight <= 0:
                    break
            time.sleep(0.001)
        worker = rep.worker
        rep.queue.put(_SHUTDOWN)
        if (
            worker is not None
            and worker.is_alive()
            and worker is not threading.current_thread()
        ):
            worker.join(timeout=5.0)

    def _rebind_replica(self, rep: _Replica, new_base, version: int, ctl) -> bool:
        """One rolling-swap step: take ``rep`` out of rotation, hold its
        queued requests, let the in-flight serve finish on the OLD engine
        (whole-version responses, never torn), rebind to a clone of
        ``new_base``, then re-admit and re-dispatch the held requests onto
        the new engine.  The held requests' futures are untouched
        throughout, so nothing is dropped and hedge duplicates still dedupe
        at the Future.  Returns True when chaos ``swap_crash`` fired
        mid-rebind — the kill lands while the replica is out of rotation
        with an empty queue, so it can strand NOTHING and recovery is
        simply completing the rebind with a fresh clone."""
        with self._lock:
            self._set_state(rep, "swapping", f"rebind to v{version}")
            held = self._drain(rep)
        self._quiesce(rep)
        crashed = False
        try:
            ctl.swap_crash(f"{self._label}:{rep.name}:swap")
        except ChaosReplicaCrash:
            crashed = True
            with self._lock:
                self._counters["crashes"] += 1
                rep.failed += 1
                rep.ejections += 1
            self._metrics.counter("fleet/crashes").inc()
        old = rep.engine
        rep.engine = new_base.clone(rep.name)
        rep.version = version
        old.stop()
        with self._lock:
            rep.fail_streak = 0
            rep.slow_streak = 0
            rep.ok_streak = 0
            rep.probing = False
            for req in held:
                if not req.future.done():
                    self._dispatch(req, rep)
            self._set_state(
                rep,
                "healthy",
                "rebind recovered from crash" if crashed
                else f"serving v{version}",
            )
            self._ensure_worker(rep)
        return crashed

    def _resolve_swap_target(self, model, name, version):
        """Resolve ``swap_model``'s target to a WARMED engine + ownership:
        a registry name acquires a pin lease on its already-warmed engine
        (zero compiles), an injected engine stays caller-owned, and a raw
        model/PackedModel is packed + warmed here mirroring the base
        engine's configuration (its warmup is the swap's only compile cost
        and moves the steady-state compile boundary)."""
        if isinstance(model, str):
            if self._registry is None:
                raise ValueError(
                    "swap_model(<name>) requires a registry-backed fleet "
                    "(FleetRouter.from_registry)"
                )
            registry, reg_name = self._registry, model
            engine = registry._acquire(reg_name)
            return engine, False, (lambda: registry._release(reg_name)), reg_name
        if isinstance(model, InferenceEngine):
            return model, False, None, name or model._label
        base = InferenceEngine(
            model,
            methods=self._base._methods,
            prefix_tiers=self._tiers,
            min_bucket=self._base._buckets[0],
            max_batch_size=self._base._max_batch,
            donate=self._base._donate,
            warm=True,
            label=f"{self._label}:v{version}:warm",
            telemetry_path=self._telemetry_path,
        )
        self._warm_snapshot = compile_snapshot()
        return base, True, None, name or f"{self._label}:v{version}"

    def swap_model(self, model, *, name: Optional[str] = None) -> Dict[str, Any]:
        """Rolling, torn-free hot swap of the served model under live
        traffic.

        ``model`` is a registry name (the fleet must come from
        :meth:`from_registry`; the new version's engine is pin-leased and
        its warm programs are shared into every replica via ``clone()``, so
        the swap adds ZERO compiles), an already-warmed
        :class:`InferenceEngine`, or a fitted model / ``PackedModel``
        (packed + warmed here first).

        Replicas rebind one at a time (:meth:`_rebind_replica`): the rest
        of the fleet keeps serving, queued requests are held and re-served
        on the new engine, and the in-flight request finishes on the old
        one — every response is computed entirely by exactly ONE model
        version, and zero requests are dropped.  The previous base engine
        is retired (stopped if router-owned, lease released if from a
        registry) only after the last replica rebinds, so a rollback swap
        can re-acquire it from the registry at any point.

        Returns a summary dict (``version``, ``swap_ms``,
        ``swap_compiles``, ``swap_crashes``) and emits it as a
        ``fleet_swap`` telemetry event."""
        if self._stopped:
            raise RuntimeError("fleet is stopped")
        ctl = controller()
        t0 = time.perf_counter()
        c0, _ = compile_snapshot()
        with self._ctl_lock:
            version = self._version + 1
            new_base, new_owns, new_release, new_name = (
                self._resolve_swap_target(model, name, version)
            )
            if (
                new_base._packed.num_features
                != self._base._packed.num_features
            ):
                if new_owns:
                    new_base.stop()
                if new_release is not None:
                    new_release()
                raise ValueError(
                    "swap target serves "
                    f"num_features={new_base._packed.num_features}, fleet "
                    f"serves {self._base._packed.num_features}; a swap must "
                    "not invalidate requests already admitted"
                )
            crashes = 0
            for rep in list(self._replicas):
                crashes += int(self._rebind_replica(rep, new_base, version, ctl))
            old_base, self._base = self._base, new_base
            old_owns, self._owns_base = self._owns_base, new_owns
            old_release = self._registry_release
            self._registry_release = new_release
            self._registry_name = new_name if new_release is not None else None
            self._tiers = new_base.prefix_tiers
            with self._lock:
                self._version = version
                self._counters["swaps"] += 1
            if old_owns:
                old_base.stop()
            if old_release is not None:
                old_release()
            c1, _ = compile_snapshot()
            out = {
                "version": version,
                "model": new_name,
                "replicas": len(self._replicas),
                "swap_ms": (time.perf_counter() - t0) * 1e3,
                "swap_compiles": c1 - c0,
                "swap_crashes": crashes,
            }
        emit_event(
            "fleet_swap",
            path=self._telemetry_path,
            fit_id=self._stream,
            **out,
        )
        self._metrics.counter("fleet/swaps").inc()
        return out

    def add_replica(self, name: Optional[str] = None) -> str:
        """Grow the fleet by one replica: a ``clone()`` of the warm base
        engine (shared programs — zero compiles), entered into rotation
        only once its worker is live.  Chaos ``scale_crash`` kills the
        warm-in BEFORE rotation entry, where it can strand nothing;
        recovery re-clones and proceeds (faults are at-most-once per
        site)."""
        if self._stopped:
            raise RuntimeError("fleet is stopped")
        ctl = controller()
        t0 = time.perf_counter()
        with self._ctl_lock:
            with self._lock:
                if name is None:
                    name = f"{self._label}:r{self._next_replica_idx}"
                    self._next_replica_idx += 1
                elif any(r.name == name for r in self._replicas):
                    raise ValueError(f"replica {name!r} already exists")
                version = self._version
            engine = self._base.clone(name)
            try:
                ctl.scale_crash(f"{self._label}:{name}:warm_in")
            except ChaosReplicaCrash:
                engine.stop()
                with self._lock:
                    self._counters["crashes"] += 1
                self._metrics.counter("fleet/crashes").inc()
                engine = self._base.clone(name)
            rep = _Replica(name, engine, version)
            with self._lock:
                self._replicas.append(rep)
                self._counters["scale_ups"] += 1
                self._ensure_worker(rep)
                n = len(self._replicas)
        emit_event(
            "fleet_scale",
            path=self._telemetry_path,
            fit_id=self._stream,
            direction="up",
            replica=name,
            replicas=n,
            warm_ms=(time.perf_counter() - t0) * 1e3,
        )
        return name

    def remove_replica(self, name: Optional[str] = None) -> str:
        """Shrink the fleet by one replica (default: the last one): it
        leaves rotation first, its queued requests replay on the
        survivors, the in-flight serve finishes, and only then do the
        worker and the engine clone die — zero drops by construction."""
        if self._stopped:
            raise RuntimeError("fleet is stopped")
        with self._ctl_lock:
            with self._lock:
                if len(self._replicas) <= 1:
                    raise ValueError("cannot remove the last replica")
                if name is None:
                    rep = self._replicas[-1]
                else:
                    match = [r for r in self._replicas if r.name == name]
                    if not match:
                        raise ValueError(f"no replica {name!r}")
                    rep = match[0]
                self._replicas.remove(rep)  # out of rotation: no new work
                for req in self._drain(rep):
                    self._redispatch(
                        req,
                        {rep.name},
                        FleetOverloadError(f"replica {rep.name} removed"),
                    )
                self._counters["scale_downs"] += 1
                n = len(self._replicas)
            self._quiesce(rep)
            rep.engine.stop()
        emit_event(
            "fleet_scale",
            path=self._telemetry_path,
            fit_id=self._stream,
            direction="down",
            replica=rep.name,
            replicas=n,
        )
        return rep.name

    # -- lifecycle / introspection ----------------------------------------

    def stop(self) -> None:
        """Stop every replica worker, emit the final SLO rows, release any
        registry pin (idempotent)."""
        if self._stopped:
            return
        self._stopped = True
        self._metrics.unregister_source(self._source_name)
        self.emit_slo()
        for rep in self._replicas:
            worker = rep.worker
            if worker is not None and worker.is_alive():
                rep.queue.put(_SHUTDOWN)
                if worker is not threading.current_thread():
                    worker.join(timeout=5.0)
        if self._owns_base:
            self._base.stop()
        release, self._registry_release = self._registry_release, None
        if release is not None:
            release()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def slo_snapshot(self) -> Dict[str, Any]:
        """Aggregate + per-replica SLO counters: p50/p99 latency, queue
        depth, hedges, breaker transitions, degraded share."""
        c, s = compile_snapshot()
        with self._lock:
            requests = self._counters["requests"]
            served = sum(r.served for r in self._replicas)
            per_replica = {
                rep.name: {
                    "state": rep.state,
                    "version": rep.version,
                    "served": rep.served,
                    "failed": rep.failed,
                    "queue_depth": rep.inflight,
                    "transitions": rep.transitions,
                    "ejections": rep.ejections,
                    "p50_ms": _quantile_ms(rep.latencies, 0.50, 0.0),
                    "p99_ms": _quantile_ms(rep.latencies, 0.99, 0.0),
                }
                for rep in self._replicas
            }
            out = {
                "label": self._label,
                "version": self._version,
                "replicas": per_replica,
                "requests": requests,
                "served": served,
                "p50_ms": _quantile_ms(self._window, 0.50, 0.0),
                "p99_ms": _quantile_ms(self._window, 0.99, 0.0),
                "degraded_share": (
                    self._counters["degraded"] / requests if requests else 0.0
                ),
                # probed live by the watchdog via the fleet/* source
                # (docs/operator.md) — keep in the SLO row, not just statusz
                "hedge_rate": (
                    self._counters["hedges_fired"] / requests
                    if requests else 0.0
                ),
                "compiles_since_warmup": c - self._warm_snapshot[0],
                "compile_s_since_warmup": s - self._warm_snapshot[1],
                "prefix_tiers": self._tiers,
            }
            out.update(self._counters)
            return out

    def statusz(self) -> Dict[str, Any]:
        """Live operator view of the fleet — the serving analogue of a
        /statusz page: identity + uptime, model shape, the per-replica
        state machines with queue depth and rolling p50/p99, hedge rate,
        and the zero-steady-state-compile counter.  Built over
        :meth:`slo_snapshot`, also exported live through
        ``global_metrics().snapshot()`` as ``fleet/<stream>`` and printed
        by ``tools/serving_smoke.py fleet``."""
        snap = self.slo_snapshot()
        requests = snap["requests"]
        return {
            "label": self._label,
            "version": snap["version"],
            "stream": self._stream,
            "trace_id": self._tracer.trace_id,
            "uptime_s": time.time() - self._t_start,
            "stopped": self._stopped,
            "deadline_ms": self._deadline_s * 1e3,
            "prefix_tiers": list(self._tiers),
            "pinned": self._registry_release is not None,
            "model": {
                "num_members": self._base._packed.num_members,
                "num_features": self._base._packed.num_features,
            },
            "requests": requests,
            "p50_ms": snap["p50_ms"],
            "p99_ms": snap["p99_ms"],
            "hedge_rate": (
                snap["hedges_fired"] / requests if requests else 0.0
            ),
            "compiles_since_warmup": snap["compiles_since_warmup"],
            "replicas": snap["replicas"],
            "counters": {
                k: snap[k] for k in (
                    "hedges_fired", "hedges_won", "shed", "degraded",
                    "replays", "crashes",
                )
            },
        }

    def emit_slo(self) -> Dict[str, Any]:
        """Emit one ``fleet_slo`` event per replica plus an aggregate row
        (the CI serving-chaos job's uploaded artifact)."""
        snap = self.slo_snapshot()
        for name, rep in snap["replicas"].items():
            emit_event(
                "fleet_slo",
                path=self._telemetry_path,
                fit_id=self._stream,
                replica=name,
                **rep,
            )
        emit_event(
            "fleet_slo",
            path=self._telemetry_path,
            fit_id=self._stream,
            replica="*",
            requests=snap["requests"],
            p50_ms=snap["p50_ms"],
            p99_ms=snap["p99_ms"],
            hedges_fired=snap["hedges_fired"],
            hedges_won=snap["hedges_won"],
            shed=snap["shed"],
            replays=snap["replays"],
            crashes=snap["crashes"],
            degraded_share=snap["degraded_share"],
            compiles_since_warmup=snap["compiles_since_warmup"],
        )
        return snap

    def stats(self) -> Dict[str, Any]:
        """Engine-level stats (shared programs) + the SLO snapshot."""
        out = self._base.stats()
        out["fleet"] = self.slo_snapshot()
        return out
