"""Packed model export: flat device-array pytree + static metadata.

``pack(model)`` compacts a fitted ensemble — bagging, boosting, GBM,
stacking, including nested base-learner and init/stacker child models — into
a :class:`PackedModel`: one flat ``{name: array}`` dict of the model's
learned device arrays plus a JSON-able static spec (classes, config params,
pytree structure).  The packed form is what the serving layer ships around:
every array is addressable by name (manifests, byte accounting, host
offload), nothing in it closes over live Python model objects, and the spec
is versioned for on-disk round-trips.

Bit-identity is the contract, not an aspiration: ``PackedModel`` serves
predictions by REBUILDING the live model object from the very same arrays
(lazily, cached), so packed inference runs the exact jitted programs the
live model runs — same code path, same programs, bit-identical outputs.
Save/load keeps the guarantee because ``.npz`` round-trips float bits
losslessly.  The on-disk artifact follows the crash-consistency conventions
of ``utils/checkpoint.py``: atomic tmpdir + rename, and a ``manifest.json``
with per-file sha256 + byte size verified on load.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_ensemble_tpu.utils.checkpoint import _file_sha256
from spark_ensemble_tpu.utils.persist import (
    _CHILD_ATTRS,
    _EXTRA_ATTRS,
    _LIST_CHILD_ATTRS,
    _class_registry,
    _decode,
)

__all__ = [
    "PACKED_FORMAT_VERSION",
    "PackedModel",
    "fit_resume",
    "pack",
    "load_packed",
]

PACKED_FORMAT_VERSION = 1
_ARTIFACT_KIND = "spark_ensemble_tpu.packed"


# ---------------------------------------------------------------------------
# model <-> (static node spec, flat arrays) encoding
# ---------------------------------------------------------------------------
#
# Same structural markers as utils/persist (__namedtuple__/__dict__/
# __list__/__array__) so persist._decode reassembles the learned pytree —
# but leaves stay as-is (device arrays keep their buffers; nothing round-
# trips through host memory just to pack).


def _flatten(obj: Any, arrays: Dict[str, Any], prefix: str):
    if obj is None:
        return None
    if isinstance(obj, (bool, int, float, str)):
        return obj
    if hasattr(obj, "_fields"):  # NamedTuple (e.g. ops.tree.Tree)
        return {
            "__namedtuple__": type(obj).__name__,
            "fields": {
                f: _flatten(getattr(obj, f), arrays, f"{prefix}.{f}")
                for f in obj._fields
            },
        }
    if isinstance(obj, dict):
        return {
            "__dict__": {
                k: _flatten(v, arrays, f"{prefix}.{k}") for k, v in obj.items()
            }
        }
    if isinstance(obj, (list, tuple)):
        return {
            "__list__": [
                _flatten(v, arrays, f"{prefix}.{i}") for i, v in enumerate(obj)
            ],
            "__tuple__": isinstance(obj, tuple),
        }
    arrays[prefix] = obj if isinstance(obj, jax.Array) else np.asarray(obj)
    return {"__array__": prefix}


def _encode_estimator(est) -> Optional[Dict[str, Any]]:
    """Estimator config as a pure-JSON node: class name, scalar params, and
    nested estimator-valued params (base_learner, stacker, ...) recursively
    — the in-memory analogue of persist's nested ``learner/`` dirs."""
    if est is None:
        return None
    node: Dict[str, Any] = {
        "class": type(est).__name__,
        "params": est.params_to_json_dict(),
    }
    estimators: Dict[str, Any] = {}
    for name, p in est._param_defs().items():
        if not p.is_estimator:
            continue
        value = getattr(est, name)
        if value is None:
            continue
        if isinstance(value, (list, tuple)):
            estimators[name] = {
                "list": [_encode_estimator(v) for v in value]
            }
        else:
            estimators[name] = {"one": _encode_estimator(value)}
    if estimators:
        node["estimators"] = estimators
    return node


def _decode_estimator(node, registry):
    if node is None:
        return None
    cls = registry[node["class"]]
    kwargs = dict(node["params"])
    for name, spec in node.get("estimators", {}).items():
        if "list" in spec:
            kwargs[name] = [
                _decode_estimator(v, registry) for v in spec["list"]
            ]
        else:
            kwargs[name] = _decode_estimator(spec["one"], registry)
    return cls(**kwargs)


def _extra_attrs(model) -> Dict[str, Any]:
    extra: Dict[str, Any] = {}
    for attr in _EXTRA_ATTRS:
        if hasattr(model, attr):
            v = getattr(model, attr)
            if isinstance(v, np.ndarray):
                v = v.tolist()
            extra[attr] = v
    return extra


def _encode_model(model, arrays: Dict[str, Any], prefix: str) -> Dict[str, Any]:
    node = _encode_estimator(model)
    node["learned"] = _flatten(model.params, arrays, f"{prefix}.p")
    node["extra"] = _extra_attrs(model)
    children = {}
    for attr in _CHILD_ATTRS:
        child = getattr(model, attr, None)
        if child is not None:
            children[attr] = _encode_model(child, arrays, f"{prefix}.{attr}")
    if children:
        node["children"] = children
    list_children = {}
    for attr in _LIST_CHILD_ATTRS:
        kids = getattr(model, attr, None)
        if kids:
            list_children[attr] = [
                _encode_model(c, arrays, f"{prefix}.{attr}{i}")
                for i, c in enumerate(kids)
            ]
    if list_children:
        node["list_children"] = list_children
    return node


def rebuild_model(node: Dict[str, Any], arrays: Dict[str, Any], registry=None):
    """Live fitted model from a packed (node, arrays) pair.  Traceable:
    construction only assigns pytrees, so the serving engine can call this
    on traced array leaves to stage a whole-model predict program."""
    if registry is None:
        registry = _class_registry()
    cls = registry[node["class"]]
    kwargs = dict(node["params"])
    for name, spec in node.get("estimators", {}).items():
        if "list" in spec:
            kwargs[name] = [
                _decode_estimator(v, registry) for v in spec["list"]
            ]
        else:
            kwargs[name] = _decode_estimator(spec["one"], registry)
    kwargs["params"] = _decode(node["learned"], arrays, registry)
    kwargs.update(node.get("extra", {}))
    for attr, child in node.get("children", {}).items():
        kwargs[attr] = rebuild_model(child, arrays, registry)
    for attr, kids in node.get("list_children", {}).items():
        kwargs[attr] = [rebuild_model(c, arrays, registry) for c in kids]
    return cls(**kwargs)


# ---------------------------------------------------------------------------
# PackedModel
# ---------------------------------------------------------------------------


class PackedModel:
    """A fitted ensemble compacted for serving: flat named device arrays +
    static metadata, with live-model-bit-identical predictions.

    ``predict``/``predict_proba``/``predict_raw`` delegate to a lazily
    rebuilt live model over the SAME arrays, so they run the exact cached
    XLA programs the original model runs.  ``save``/``load_packed`` write a
    versioned directory artifact (``packed.json`` + ``arrays.npz`` +
    sha256 ``manifest.json``).  ``offload()`` moves every array to host
    memory and drops the live view — the registry's LRU eviction hook."""

    def __init__(self, node: Dict[str, Any], arrays: Dict[str, Any]):
        self._node = node
        self._arrays = dict(arrays)
        self._model = None
        self._lock = threading.Lock()

    # -- identity ----------------------------------------------------------

    @property
    def node(self) -> Dict[str, Any]:
        """Static metadata (JSON-able): classes, config, pytree spec."""
        return self._node

    @property
    def class_name(self) -> str:
        return self._node["class"]

    @property
    def num_features(self) -> int:
        return int(self._node.get("extra", {}).get("num_features", 0))

    @property
    def num_classes(self) -> Optional[int]:
        k = self._node.get("extra", {}).get("num_classes")
        return None if k is None else int(k)

    @property
    def is_classifier(self) -> bool:
        return self.num_classes is not None

    @property
    def num_members(self) -> Optional[int]:
        """Ensemble size (GBM rounds / boosting members) when the packed
        family records one; ``None`` for non-ensemble models."""
        m = self._node.get("extra", {}).get("num_members")
        return None if m is None else int(m)

    @property
    def quality(self) -> Optional[Dict[str, Any]]:
        """The drift-reference sidecar captured at fit (host numpy):
        ``{"thresholds": f32[d, B-1], "occupancy": i32[d, B], "rows": n}``,
        or ``None`` when the model was packed without one (non-binned
        families, or pre-quality artifacts).  ``rebuild_model`` never reads
        this node, so its presence cannot perturb predictions."""
        q = self._node.get("quality")
        if not q:
            return None
        return {
            "thresholds": np.asarray(
                self._arrays[q["thresholds"]], np.float32
            ),
            "occupancy": np.asarray(self._arrays[q["occupancy"]], np.int32),
            "rows": int(q.get("rows", 0)),
        }

    # -- arrays ------------------------------------------------------------

    @property
    def array_names(self):
        return sorted(self._arrays)

    @property
    def nbytes(self) -> int:
        return int(sum(a.nbytes for a in self._arrays.values()))

    def device_arrays(self) -> Dict[str, jax.Array]:
        """The packed arrays as device arrays (no copy when already on
        device) — the engine snapshots these once at construction so its
        compiled programs keep their own buffer references."""
        return {k: jnp.asarray(v) for k, v in self._arrays.items()}

    def on_device(self) -> bool:
        return any(isinstance(a, jax.Array) for a in self._arrays.values())

    def ensure_device(self) -> "PackedModel":
        with self._lock:
            self._arrays = {
                k: jnp.asarray(v) for k, v in self._arrays.items()
            }
        return self

    def offload(self) -> "PackedModel":
        """Move every packed array to host memory and drop the cached live
        model (its jit cache holds device buffers); predictions still work
        afterwards — arrays re-upload lazily on next use."""
        with self._lock:
            self._arrays = {
                k: np.asarray(v) for k, v in self._arrays.items()
            }
            self._model = None
        return self

    # -- serving -----------------------------------------------------------

    def model(self):
        """The live fitted model rebuilt over the packed arrays (cached).
        Same arrays + same model code = bit-identical predictions."""
        with self._lock:
            if self._model is None:
                # re-upload in place: after offload() the arrays land back
                # on device here, and the rebuilt model shares the buffers
                self._arrays = {
                    k: jnp.asarray(v) for k, v in self._arrays.items()
                }
                self._model = rebuild_model(self._node, dict(self._arrays))
            return self._model

    def predict(self, X) -> jax.Array:
        return self.model().predict(X)

    def predict_proba(self, X) -> jax.Array:
        return self.model().predict_proba(X)

    def predict_raw(self, X) -> jax.Array:
        return self.model().predict_raw(X)

    # -- ensemble-prefix slicing -------------------------------------------

    def take(self, k: int) -> "PackedModel":
        """Pack the first-``k``-member prefix of this ensemble.

        Stagewise families (GBM, boosting) expose ``model.take(k)`` whose
        prediction is bit-identical to fitting the same config for only k
        rounds — round keys and masks derive from absolute round indices, so
        the prefix IS the k-round fit.  The sliced arrays are repacked into a
        fresh :class:`PackedModel`, which is what the serving engine compiles
        as a degraded tier.  Raises ``TypeError`` for families with no
        stagewise prefix structure (bagging, stacking, single models)."""
        model = self.model()
        if not hasattr(model, "take"):
            raise TypeError(
                f"{self.class_name} has no ensemble-prefix structure; "
                "take(k) applies to GBM and boosting families only"
            )
        n = self.num_members
        if n is not None and not (1 <= int(k) <= n):
            raise ValueError(
                f"take(k={k}) out of range for an ensemble of {n} members"
            )
        prefix = pack(model.take(int(k)))
        # the live model's take() drops fit-time sidecars, so re-attach the
        # drift reference: tier engines sketch against the same thresholds
        q = self._node.get("quality")
        if q:
            prefix._node["quality"] = dict(q)
            prefix._arrays[q["thresholds"]] = self._arrays[q["thresholds"]]
            prefix._arrays[q["occupancy"]] = self._arrays[q["occupancy"]]
        return prefix

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        """Write the versioned artifact directory: ``packed.json`` (static
        spec), ``arrays.npz`` (lossless float round-trip), and a
        ``manifest.json`` with per-file sha256 + byte sizes — the same
        crash-consistency conventions as ``utils/checkpoint.py`` (atomic
        tmpdir + rename; a torn write can never look like an artifact)."""
        from spark_ensemble_tpu import __version__

        parent = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(parent, exist_ok=True)
        tmp = tempfile.mkdtemp(dir=parent, prefix=".packed-tmp-")
        try:
            meta = {
                "kind": _ARTIFACT_KIND,
                "format_version": PACKED_FORMAT_VERSION,
                "package_version": __version__,
                "model": self._node,
            }
            with open(os.path.join(tmp, "packed.json"), "w") as f:
                json.dump(meta, f, indent=2, default=float)
            np.savez(
                os.path.join(tmp, "arrays.npz"),
                **{k: np.asarray(v) for k, v in self._arrays.items()},
            )
            manifest: Dict[str, Any] = {
                "format_version": PACKED_FORMAT_VERSION,
                "files": {},
            }
            for name in ("packed.json", "arrays.npz"):
                p = os.path.join(tmp, name)
                manifest["files"][name] = {
                    "sha256": _file_sha256(p),
                    "bytes": os.path.getsize(p),
                }
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=2)
            final = os.path.abspath(path)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def __repr__(self):
        return (
            f"PackedModel({self.class_name}, arrays={len(self._arrays)}, "
            f"bytes={self.nbytes})"
        )


def fit_resume(packed, X, y, n_new_rounds, sample_weight=None) -> PackedModel:
    """Warm-start refresh fit: continue a served stagewise ensemble for
    ``n_new_rounds`` more rounds on its ORIGINAL training data and repack.

    The inverse direction of :meth:`PackedModel.take`: where ``take(k)``
    proves a packed prefix IS the k-round fit, ``fit_resume`` runs the same
    contract forward — the rebuilt model's committed round state (prediction
    carry, boosting weights, line-search warm start) is rehydrated and the
    round loop re-enters at the next ABSOLUTE round index, so the result is
    bit-identical to a single ``num_members + n_new_rounds``-round fit
    (pinned per family in ``tests/test_fit_resume.py``).  This is the
    autopilot's drift response (serving/autopilot.py): a background refresh
    that never recompiles or retrains the committed prefix.

    Accepts a :class:`PackedModel` or an already-rebuilt fitted model.
    Raises ``TypeError`` for families with no stagewise round structure
    (bagging, stacking, single models)."""
    model = packed.model() if isinstance(packed, PackedModel) else packed
    if not hasattr(model, "fit_resume"):
        raise TypeError(
            f"{type(model).__name__} has no stagewise round structure; "
            "fit_resume applies to GBM and boosting families only"
        )
    n_new = int(n_new_rounds)
    resumed = model.fit_resume(X, y, n_new, sample_weight=sample_weight)
    return pack(resumed)


def pack(model) -> PackedModel:
    """Compact a fitted model into a :class:`PackedModel` (see module
    docstring); emits a ``model_packed`` telemetry event."""
    from spark_ensemble_tpu.models.base import Model
    from spark_ensemble_tpu.telemetry.events import (
        emit_event,
        serving_stream_id,
    )

    if not isinstance(model, Model):
        raise TypeError(
            f"pack() expects a fitted Model; got {type(model).__name__} "
            "(fit the estimator first)"
        )
    arrays: Dict[str, Any] = {}
    node = _encode_model(model, arrays, "m")
    # model-quality sidecar (telemetry/quality.py): fitted bin thresholds +
    # training bin occupancy ride along as ordinary packed arrays under a
    # node key rebuild_model never reads, so predictions stay bit-identical
    # while the serving engine gains an on-device drift sketch for free.
    ref = getattr(model, "drift_ref_", None)
    if isinstance(ref, dict) and "thresholds" in ref and "occupancy" in ref:
        arrays["q.thresholds"] = np.asarray(ref["thresholds"], np.float32)
        arrays["q.occupancy"] = np.asarray(ref["occupancy"], np.int32)
        node["quality"] = {
            "thresholds": "q.thresholds",
            "occupancy": "q.occupancy",
            "rows": int(ref.get("rows", 0)),
        }
    packed = PackedModel(node, arrays)
    emit_event(
        "model_packed",
        fit_id=serving_stream_id("pack"),
        family=packed.class_name,
        arrays=len(arrays),
        bytes=packed.nbytes,
        num_features=packed.num_features,
    )
    return packed


def load_packed(path: str) -> PackedModel:
    """Load a :func:`PackedModel.save` artifact, verifying the manifest
    (sha256 + size per file) and the format version before touching any
    payload — corruption and version skew fail loudly here, not as NaNs in
    production predictions."""
    mf_path = os.path.join(path, "manifest.json")
    if not os.path.exists(mf_path):
        raise FileNotFoundError(
            f"{path!r} is not a packed-model artifact (no manifest.json)"
        )
    with open(mf_path) as f:
        manifest = json.load(f)
    for name, entry in manifest.get("files", {}).items():
        p = os.path.join(path, name)
        if not os.path.exists(p):
            raise ValueError(f"packed artifact {path!r} is missing {name}")
        if os.path.getsize(p) != entry["bytes"] or _file_sha256(p) != entry["sha256"]:
            raise ValueError(
                f"packed artifact {path!r}: {name} fails its manifest "
                "checksum (truncated or corrupt write)"
            )
    with open(os.path.join(path, "packed.json")) as f:
        meta = json.load(f)
    if meta.get("kind") != _ARTIFACT_KIND:
        raise ValueError(
            f"{path!r} is not a packed-model artifact (kind={meta.get('kind')!r})"
        )
    version = int(meta.get("format_version", -1))
    if version != PACKED_FORMAT_VERSION:
        raise ValueError(
            f"packed artifact {path!r} has format_version={version}; this "
            f"build reads version {PACKED_FORMAT_VERSION}"
        )
    npz = os.path.join(path, "arrays.npz")
    arrays = dict(np.load(npz)) if os.path.exists(npz) else {}
    return PackedModel(meta["model"], arrays)
