"""Multi-model serving registry with LRU eviction of device buffers.

A serving process typically hosts more models than fit on the accelerator at
once (per-tenant models, A/B variants, rollback generations).
:class:`ModelRegistry` keeps every registered model's packed form resident in
host memory and at most ``capacity`` of them *active* — live on device with
a warmed :class:`InferenceEngine`.  Activating a model beyond capacity
offloads the least-recently-used one: its engine (and the device buffers its
compiled programs hold) is dropped and its :class:`PackedModel` arrays move
back to host, to be re-uploaded and re-warmed on next use.

Thread-safe throughout — request threads race on ``engine()``/``predict()``
the way serving frontends do.  Evictions emit ``model_evicted`` telemetry
events; per-model request events come from the engines themselves.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional

from spark_ensemble_tpu.serving.engine import InferenceEngine
from spark_ensemble_tpu.serving.export import PackedModel, pack
from spark_ensemble_tpu.telemetry.events import (
    emit_event,
    global_metrics,
    serving_stream_id,
)

__all__ = ["ModelRegistry"]


class _Entry:
    __slots__ = (
        "packed", "engine", "opts", "hits", "activations", "last_used",
        "pins", "pending_offload", "pending_remove",
    )

    def __init__(self, packed: PackedModel, opts: Dict[str, Any]):
        self.packed = packed
        self.engine: Optional[InferenceEngine] = None
        self.opts = opts
        self.hits = 0
        self.activations = 0
        self.last_used = 0.0
        # in-flight requests holding this version's device buffers: LRU
        # eviction (or explicit evict/rollback) defers while pins > 0, so a
        # hot-swap can never free arrays out from under an unsent reply
        self.pins = 0
        self.pending_offload = False
        self.pending_remove = False


class ModelRegistry:
    """Thread-safe name -> model registry serving through per-model
    :class:`InferenceEngine` instances, keeping at most ``capacity`` models
    device-resident (LRU eviction; see module docstring).

    ``engine_opts`` (and per-``register`` overrides) are forwarded to every
    :class:`InferenceEngine` the registry constructs."""

    def __init__(
        self,
        capacity: int = 4,
        *,
        telemetry_path: Optional[str] = None,
        **engine_opts,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1; got {capacity}")
        self._capacity = int(capacity)
        self._telemetry_path = telemetry_path
        self._engine_opts = dict(engine_opts)
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._stream = serving_stream_id("registry")
        self._metrics = global_metrics()

    # -- membership --------------------------------------------------------

    def register(self, name: str, model, *, warm: bool = False, **engine_opts):
        """Register a fitted model or :class:`PackedModel` under ``name``
        (packing live models on the spot).  Registration is host-only by
        default; pass ``warm=True`` to activate (device upload + AOT
        warmup) immediately."""
        packed = model if isinstance(model, PackedModel) else pack(model)
        with self._lock:
            if name in self._entries:
                raise ValueError(
                    f"model {name!r} is already registered (remove() first)"
                )
            opts = dict(self._engine_opts)
            opts.update(engine_opts)
            self._entries[name] = _Entry(packed, opts)
        if warm:
            self.engine(name)
        return self

    def remove(self, name: str) -> None:
        """Unregister ``name``.  A removal racing a live pin lease (a
        :class:`FleetRouter` / shadow engine, or a queued ``submit()``
        reply) DEFERS like ``_offload``: the entry leaves the name space
        immediately from the caller's point of view after the last pin
        releases, and the engine is only stopped once no in-flight request
        can still be computing on its buffers — popping eagerly here used
        to orphan the entry (``_release`` found nothing and the engine
        leaked, running, forever)."""
        with self._lock:
            entry = self._entries[name]
            if entry.pins > 0:
                # a lease still holds this version's device buffers:
                # _release() completes the removal at pin zero
                entry.pending_remove = True
                return
            del self._entries[name]
            engine, entry.engine = entry.engine, None
        if engine is not None:
            engine.stop()

    def names(self):
        with self._lock:
            return list(self._entries)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- serving -----------------------------------------------------------

    def engine(self, name: str) -> InferenceEngine:
        """The warmed engine for ``name`` (most-recently-used); activates
        the model if offloaded and LRU-evicts over-capacity residents."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(
                    f"no model {name!r} registered "
                    f"(registered: {sorted(self._entries)})"
                )
            self._entries.move_to_end(name)
            entry.hits += 1
            entry.last_used = time.time()
            if entry.engine is None:
                entry.packed.ensure_device()
                entry.engine = InferenceEngine(
                    entry.packed,
                    warm=True,
                    label=f"registry:{name}",
                    telemetry_path=self._telemetry_path,
                    **entry.opts,
                )
                entry.activations += 1
                self._metrics.counter("serving/activations").inc()
                self._evict_over_capacity()
            return entry.engine

    def _acquire(self, name: str) -> InferenceEngine:
        with self._lock:
            engine = self.engine(name)
            self._entries[name].pins += 1
            return engine

    def _release(self, name: str) -> None:
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:  # removed while in flight; nothing to free
                return
            entry.pins = max(entry.pins - 1, 0)
            if entry.pins == 0 and entry.pending_remove:
                # complete the deferred remove(); engine.stop() is safe
                # under the RLock (idempotent, self-join guarded)
                entry.pending_remove = False
                del self._entries[name]
                engine, entry.engine = entry.engine, None
                if engine is not None:
                    engine.stop()
                return
            if entry.pins == 0 and entry.pending_offload:
                entry.pending_offload = False
                if entry.engine is not None:
                    self._offload(name)

    @contextlib.contextmanager
    def lease(self, name: str):
        """The warmed engine for ``name``, pinned against eviction for the
        duration of the ``with`` block: a hot-swap/rollback that evicts
        this version mid-request defers its offload until the last lease
        is released (i.e. the reply was sent)."""
        engine = self._acquire(name)
        try:
            yield engine
        finally:
            self._release(name)

    def predict(self, name: str, X, method: str = "predict"):
        with self.lease(name) as engine:
            return engine.predict(X, method=method)

    def submit(self, name: str, X, method: str = "predict"):
        engine = self._acquire(name)
        try:
            fut = engine.submit(X, method=method)
        except BaseException:
            self._release(name)
            raise
        # the version stays pinned until the reply is delivered — the
        # done-callback runs after set_result/set_exception, when the
        # caller's rows are already materialized host-side
        fut.add_done_callback(lambda _f: self._release(name))
        return fut

    # -- eviction ----------------------------------------------------------

    def _resident(self):
        return [
            (n, e) for n, e in self._entries.items() if e.engine is not None
        ]

    def _evict_over_capacity(self) -> None:
        # called under self._lock; OrderedDict is LRU-ordered by move_to_end
        resident = self._resident()
        while len(resident) > self._capacity:
            name, _ = resident.pop(0)
            self._offload(name)

    def _offload(self, name: str) -> None:
        entry = self._entries[name]
        if entry.pins > 0:
            # a request resolved against this version and has not replied
            # yet: defer — _release() completes the offload at pin zero
            entry.pending_offload = True
            return
        engine, entry.engine = entry.engine, None
        if engine is not None:
            engine.stop()
        freed = entry.packed.nbytes
        entry.packed.offload()
        self._metrics.counter("serving/evictions").inc()
        emit_event(
            "model_evicted",
            path=self._telemetry_path,
            fit_id=self._stream,
            model=name,
            bytes_freed=freed,
        )

    def evict(self, name: str) -> None:
        """Explicitly offload ``name``'s device buffers (it stays
        registered; next use re-activates)."""
        with self._lock:
            if name not in self._entries:
                raise KeyError(f"no model {name!r} registered")
            if self._entries[name].engine is not None:
                self._offload(name)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            for entry in self._entries.values():
                if entry.engine is not None:
                    entry.engine.stop()
                    entry.engine = None

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: {
                    "resident": e.engine is not None,
                    "pins": e.pins,
                    "pending_remove": e.pending_remove,
                    "hits": e.hits,
                    "activations": e.activations,
                    "last_used": e.last_used,
                    "bytes": e.packed.nbytes,
                }
                for name, e in self._entries.items()
            }
