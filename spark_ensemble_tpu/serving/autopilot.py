"""Closed-loop fleet autopilot: watchdog verdicts in, fleet actions out.

PRs 9-16 built every ingredient of an autonomous fleet — ``take(k)``'s
prefix contract, the pin-leased :class:`ModelRegistry`, shadow divergence
verdicts, and the watchdog's ``slo_alert``/``/healthz`` state machine —
but a human still had to read the alerts and act.  :class:`Autopilot`
closes train -> serve -> observe -> train (docs/autopilot.md):

- **Scale**: a sustained ``serving_p99_ms``/``hedge_rate`` alert or queue
  buildup past ``queue_high`` adds a replica
  (:meth:`FleetRouter.add_replica`, a zero-compile clone); a fully-healthy
  verdict held for ``calm_ticks`` with shallow queues removes one, within
  ``[min_replicas, max_replicas]``.
- **Refresh**: a ``quality_psi_max`` drift alert triggers a background
  warm-start refresh fit (:func:`spark_ensemble_tpu.serving.export
  .fit_resume` — the committed rounds are rehydrated, only new rounds
  train), the refreshed model registers in the registry as
  ``<name>@v<N>``, and the fleet rolls onto it torn-free via
  :meth:`FleetRouter.swap_model`.  A crashed refresh (chaos
  ``refresh_crash``) leaves the serving model untouched and the next
  attempt retries from the same committed state.
- **Rollback**: a ``shadow_divergence`` alert while a refreshed version is
  serving swaps back to the pinned previous registry version — the old
  entry was never removed, so rollback is one more zero-compile rolling
  swap.

Every action is emitted as a ``fleet_action`` telemetry event (schema in
docs/telemetry.md) wrapped in a span on the ``autopilot`` track whose
``flow_out`` arrow ties the decision to the ``fleet_swap``/``fleet_scale``
row it caused — the trace shows *why* the fleet changed shape.

Determinism: :meth:`step` is a pure control-loop tick (probe -> decide ->
act) driven by the caller; ``start()`` merely runs it on a timer thread.
The loop only reads host-side snapshots — no device values, no blocking
reads — pinned by the tier-2 ``autopilot.lint`` graftlint contract.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from spark_ensemble_tpu.robustness.chaos import ChaosPreemption
from spark_ensemble_tpu.telemetry.events import (
    emit_event,
    global_metrics,
    serving_stream_id,
)
from spark_ensemble_tpu.telemetry.trace import Tracer, new_flow_id

__all__ = ["Autopilot"]

#: watchdog rules whose active alert means "the fleet is under-provisioned"
SCALE_UP_RULES = ("serving_p99_ms", "hedge_rate")


class Autopilot:
    """Control loop from watchdog verdicts to fleet actions (module
    docstring; docs/autopilot.md).

    Parameters
    ----------
    router:
        The :class:`~spark_ensemble_tpu.serving.fleet.FleetRouter` under
        control.
    watchdog:
        A :class:`~spark_ensemble_tpu.telemetry.watchdog.Watchdog`; each
        :meth:`step` advances it one ``evaluate_once`` tick (callers that
        run the watchdog's own thread should NOT also start the
        autopilot's, or rules tick twice per interval).
    registry / model_name:
        The :class:`ModelRegistry` hosting the served model (defaults to
        the router's own when built via ``from_registry``).  Needed for
        refresh + rollback; scale actions work without one.
    refresh_data:
        Zero-arg callable returning ``(X, y)`` or ``(X, y, sample_weight)``
        — the ORIGINAL training matrix ``fit_resume`` requires.  No
        callable means drift alerts are observed but not acted on.
    refresh_rounds:
        New rounds per refresh fit.
    min_replicas / max_replicas:
        Elastic-width bounds for scale actions.
    queue_high / queue_low:
        Max per-replica queue depth that triggers scale-up / permits
        scale-down.
    calm_ticks:
        Consecutive fully-healthy steps required before a scale-down (and
        between any two scale actions — flap damping).
    background_refresh:
        ``True`` runs the refresh fit on a daemon thread (serving never
        waits on training); ``False`` runs it inline in :meth:`step`, which
        is what the deterministic chaos battery drives.
    """

    def __init__(
        self,
        router,
        watchdog,
        *,
        registry=None,
        model_name: Optional[str] = None,
        refresh_data: Optional[Callable[[], tuple]] = None,
        refresh_rounds: int = 10,
        min_replicas: int = 1,
        max_replicas: int = 8,
        queue_high: int = 8,
        queue_low: int = 1,
        calm_ticks: int = 3,
        background_refresh: bool = True,
        interval_s: float = 2.0,
        telemetry_path: Optional[str] = None,
    ):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas; got "
                f"[{min_replicas}, {max_replicas}]"
            )
        self._router = router
        self._watchdog = watchdog
        self._registry = registry if registry is not None else getattr(
            router, "_registry", None
        )
        self._model_name = model_name or getattr(
            router, "_registry_name", None
        )
        self._refresh_data = refresh_data
        self._refresh_rounds = int(refresh_rounds)
        self._min_replicas = int(min_replicas)
        self._max_replicas = int(max_replicas)
        self._queue_high = int(queue_high)
        self._queue_low = int(queue_low)
        self._calm_ticks = int(calm_ticks)
        self._background = bool(background_refresh)
        self.interval_s = float(interval_s)
        self._telemetry_path = telemetry_path
        self._stream = serving_stream_id("autopilot")
        self._tracer = Tracer(self._emit_trace, thread="autopilot")
        self._metrics = global_metrics()
        self._lock = threading.Lock()
        self._steps = 0
        self._calm = 0
        self._last_scale_step = -(10**9)
        self._refresh_generation = 0
        self._refresh_inflight = False
        self._refresh_thread: Optional[threading.Thread] = None
        # rollback pin: the registry name serving BEFORE the last refresh
        # swap; consumed (cleared) by one rollback
        self._rollback_name: Optional[str] = None
        #: every action record this autopilot ever took (tests + statusz)
        self.actions: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- telemetry ---------------------------------------------------------

    def _emit_trace(self, rec: Dict[str, Any]) -> None:
        rec = dict(rec)
        emit_event(
            rec.pop("event"), path=self._telemetry_path,
            fit_id=self._stream, **rec,
        )

    def _act(self, action: str, trigger: str, fn, **attrs) -> Dict[str, Any]:
        """Run one fleet action inside a ``fleet_action`` span whose flow
        arrow points at the swap/scale row it causes; the matching event
        row carries the same fields (docs/telemetry.md)."""
        fid = new_flow_id()
        record: Dict[str, Any] = {
            "action": action, "trigger": trigger, "flow": fid, **attrs,
        }
        span = self._tracer.begin_span(
            "fleet_action", annotate=False, action=action, trigger=trigger,
        )
        span.attrs.setdefault("flow_out", []).append(fid)
        with span:
            try:
                result = fn()
                record["status"] = "ok"
                if isinstance(result, dict):
                    record.update(result)
                elif result is not None:
                    record["result"] = result
            except ChaosPreemption as e:
                # a killed refresh fit: serving model untouched, retryable
                record["status"] = "failed"
                record["error"] = str(e)
            except Exception as e:  # noqa: BLE001 - autopilot never crashes serving
                record["status"] = "failed"
                record["error"] = f"{type(e).__name__}: {e}"
            span.add(status=record["status"])
        with self._lock:
            self.actions.append(record)
        emit_event(
            "fleet_action", path=self._telemetry_path,
            fit_id=self._stream, **record,
        )
        self._metrics.counter(f"autopilot/{action}").inc()
        return record

    # -- the control loop --------------------------------------------------

    def step(self, snapshot: Optional[Dict[str, Any]] = None) -> List[Dict[str, Any]]:
        """One deterministic tick: advance the watchdog, read the fleet's
        queue state, decide, act.  Returns the action records taken this
        tick (empty list = healthy steady state)."""
        readings = self._watchdog.evaluate_once(snapshot)
        slo = self._router.slo_snapshot()
        depth = max(
            (r["queue_depth"] for r in slo["replicas"].values()), default=0
        )
        n_replicas = len(slo["replicas"])
        taken: List[Dict[str, Any]] = []
        with self._lock:
            self._steps += 1
            step = self._steps
        active = {
            name for name, r in readings.items() if r.get("active")
        }
        healthy = not active and depth <= self._queue_low
        with self._lock:
            self._calm = self._calm + 1 if healthy else 0
            calm = self._calm
            cooled = step - self._last_scale_step > self._calm_ticks

        # -- rollback first: a diverging candidate outranks everything ----
        if "shadow_divergence" in active and self._rollback_name is not None:
            name, self._rollback_name = self._rollback_name, None
            taken.append(self._act(
                "rollback", "shadow_divergence",
                lambda: self._router.swap_model(name),
                value=readings["shadow_divergence"]["value"],
                threshold=readings["shadow_divergence"]["threshold"],
                target=name,
            ))

        # -- refresh: sustained drift retrains the tail, not the prefix ----
        elif "quality_psi_max" in active and self._refresh_data is not None:
            with self._lock:
                start = not self._refresh_inflight
                if start:
                    self._refresh_inflight = True
            if start:
                if self._background:
                    t = threading.Thread(
                        target=self._refresh,
                        args=(readings["quality_psi_max"],),
                        name="se-tpu-autopilot-refresh",
                        daemon=True,
                    )
                    self._refresh_thread = t
                    t.start()
                else:
                    taken.append(self._refresh(readings["quality_psi_max"]))

        # -- elastic width --------------------------------------------------
        pressured = bool(active & set(SCALE_UP_RULES)) or depth >= self._queue_high
        if pressured and n_replicas < self._max_replicas and cooled:
            with self._lock:
                self._last_scale_step = step
            trigger = next(
                (r for r in SCALE_UP_RULES if r in active), "queue_depth"
            )
            taken.append(self._act(
                "scale_up", trigger, self._router.add_replica,
                queue_depth=depth, replicas=n_replicas + 1,
            ))
        elif (
            n_replicas > self._min_replicas
            and calm >= self._calm_ticks
            and cooled
        ):
            with self._lock:
                self._last_scale_step = step
                self._calm = 0
            taken.append(self._act(
                "scale_down", "calm", self._router.remove_replica,
                queue_depth=depth, replicas=n_replicas - 1,
            ))
        return taken

    def _refresh(self, reading: Dict[str, Any]) -> Dict[str, Any]:
        """The drift response: warm-start ``fit_resume`` on the served
        model's committed rounds, register the result as a NEW registry
        version, and roll the fleet onto it.  The previous version's name
        is pinned for rollback; a chaos ``refresh_crash`` mid-fit aborts
        before anything registers, leaving the serving model untouched."""
        from spark_ensemble_tpu.serving.export import fit_resume

        def run():
            data = self._refresh_data()
            X, y = data[0], data[1]
            sw = data[2] if len(data) > 2 else None
            packed = self._router._base.packed
            new_packed = fit_resume(
                packed, X, y, self._refresh_rounds, sample_weight=sw
            )
            with self._lock:
                self._refresh_generation += 1
                gen = self._refresh_generation
            base = self._model_name or "fleet"
            new_name = f"{base.split('@')[0]}@v{gen}"
            if self._registry is not None:
                self._registry.register(new_name, new_packed, warm=True)
                prev = getattr(self._router, "_registry_name", None)
                info = self._router.swap_model(new_name)
                with self._lock:
                    self._rollback_name = prev
                self._model_name = new_name
            else:
                info = self._router.swap_model(new_packed, name=new_name)
            return {
                "model": new_name,
                "new_rounds": self._refresh_rounds,
                "members": new_packed.num_members,
                **{f"swap_{k}" if not k.startswith("swap") else k: v
                   for k, v in info.items()},
            }

        try:
            return self._act(
                "refresh", "quality_psi_max", run,
                value=reading.get("value"),
                threshold=reading.get("threshold"),
            )
        finally:
            with self._lock:
                self._refresh_inflight = False

    # -- lifecycle / introspection -----------------------------------------

    def statusz(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "steps": self._steps,
                "calm": self._calm,
                "refresh_inflight": self._refresh_inflight,
                "refresh_generation": self._refresh_generation,
                "rollback_pin": self._rollback_name,
                "bounds": [self._min_replicas, self._max_replicas],
                "actions": list(self.actions),
            }

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 - the pilot never downs the plane
                pass

    def start(self) -> "Autopilot":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="se-tpu-autopilot", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        rt = self._refresh_thread
        if rt is not None and rt.is_alive():
            rt.join(timeout=60.0)

    def __enter__(self) -> "Autopilot":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def join_refresh(self, timeout: Optional[float] = None) -> bool:
        """Wait for an in-flight background refresh (tests / shutdown);
        returns True when no refresh is running afterwards."""
        rt = self._refresh_thread
        if rt is not None and rt.is_alive():
            rt.join(timeout=timeout)
        with self._lock:
            return not self._refresh_inflight
