"""Typed, validated, serializable estimator configuration.

The reference uses Spark ML's ``Param`` system end-to-end: typed params with
validators (``ParamValidators.gtEq(1)`` at `ensembleParams.scala:44`,
``inRange(0,1)`` at `HasSubBag.scala:49`, ``inArray`` at `GBMParams.scala:63`),
defaults via ``setDefault``, chained setters, ``copy(extra)`` cloning, and JSON
encoding with nested-estimator params excluded.  This module provides the
JAX-build equivalent: declarative ``Param`` descriptors on ``Params``
subclasses with eager validation, sklearn-style ``get_params``/``set_params``,
deep ``copy``, and JSON round-tripping (nested estimators are serialized
separately by :mod:`spark_ensemble_tpu.utils.persist`).
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Dict, Optional


class Param:
    """A declarative, validated parameter (reference: Spark ``Param[T]``)."""

    def __init__(
        self,
        default: Any = None,
        validator: Optional[Callable[[Any], bool]] = None,
        doc: str = "",
        is_estimator: bool = False,
    ):
        self.default = default
        self.validator = validator
        self.doc = doc
        # estimator-valued params (base_learner, stacker, ...) are excluded
        # from JSON metadata and persisted as nested directories, mirroring
        # the reference's filtered save (`BaggingRegressor.scala:52-58`).
        self.is_estimator = is_estimator
        self.name: str = ""  # filled by __set_name__

    def __set_name__(self, owner, name):
        self.name = name

    def validate(self, value: Any) -> Any:
        if value is not None and self.validator is not None:
            if not self.validator(value):
                raise ValueError(
                    f"invalid value {value!r} for param {self.name!r}"
                )
        return value

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj._param_values.get(self.name, self.default)

    def __set__(self, obj, value):
        obj._param_values[self.name] = self.validate(value)


# ---------------------------------------------------------------------------
# Validators (reference: org.apache.spark.ml.param.ParamValidators)
# ---------------------------------------------------------------------------

def gt_eq(lower):
    return lambda v: v >= lower


def gt(lower):
    return lambda v: v > lower


def in_range(lo, hi, lower_inclusive=True, upper_inclusive=True):
    def check(v):
        ok_lo = v >= lo if lower_inclusive else v > lo
        ok_hi = v <= hi if upper_inclusive else v < hi
        return ok_lo and ok_hi

    return check


def in_array(values):
    values = [v.lower() if isinstance(v, str) else v for v in values]
    return lambda v: (v.lower() if isinstance(v, str) else v) in values


def _jsonable(value) -> bool:
    """True when value is representable as plain JSON."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return True
    if isinstance(value, (list, tuple)):
        return all(_jsonable(v) for v in value)
    if isinstance(value, dict):
        return all(
            isinstance(k, str) and _jsonable(v) for k, v in value.items()
        )
    return False


class Params:
    """Base class with declared-``Param`` bookkeeping.

    Subclasses declare class attributes of type :class:`Param`; instances get
    per-instance values settable via constructor kwargs or ``set_params``.
    """

    def __init__(self, **kwargs):
        self._param_values: Dict[str, Any] = {}
        unknown = set(kwargs) - set(self._param_names())
        if unknown:
            raise TypeError(
                f"{type(self).__name__} got unknown params: {sorted(unknown)}"
            )
        for name, value in kwargs.items():
            setattr(self, name, value)

    @classmethod
    def _param_defs(cls) -> Dict[str, Param]:
        out: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for name, attr in vars(klass).items():
                if isinstance(attr, Param):
                    out[name] = attr
        return out

    @classmethod
    def _param_names(cls):
        return list(cls._param_defs())

    def get_params(self, deep: bool = False) -> Dict[str, Any]:
        out = {}
        for name in self._param_names():
            value = getattr(self, name)
            if deep and isinstance(value, Params):
                value = value.get_params(deep=True)
            out[name] = value
        return out

    def set_params(self, **kwargs) -> "Params":
        unknown = set(kwargs) - set(self._param_names())
        if unknown:
            raise TypeError(
                f"{type(self).__name__} got unknown params: {sorted(unknown)}"
            )
        for name, value in kwargs.items():
            setattr(self, name, value)
        return self

    def copy(self, **extra) -> "Params":
        """Deep clone, recursively copying nested estimators
        (reference: ``copy(extra: ParamMap)``, `BaggingRegressor.scala:111-115`)."""
        new = _copy.deepcopy(self)
        new.set_params(**extra)
        return new

    def config_key(self) -> tuple:
        """Hashable fingerprint of type + all params (nested estimators
        recursively).  Two instances with equal keys trace to identical
        XLA programs, so jitted train/predict programs can be cached and
        shared across estimator instances (a per-``fit`` closure would
        recompile every call)."""

        def enc(v):
            if isinstance(v, Params):
                return v.config_key()
            if isinstance(v, (list, tuple)):
                return tuple(enc(x) for x in v)
            if isinstance(v, dict):
                return tuple(sorted((k, enc(x)) for k, x in v.items()))
            return v

        return (type(self).__name__,) + tuple(
            (name, enc(getattr(self, name))) for name in self._param_names()
        )

    # -- JSON metadata (estimator-valued params excluded) -------------------
    def params_to_json_dict(self) -> Dict[str, Any]:
        defs = self._param_defs()
        out = {}
        for name, p in defs.items():
            if p.is_estimator:
                continue
            value = getattr(self, name)
            if value is None or isinstance(value, (bool, int, float, str)):
                out[name] = value
            elif isinstance(value, (list, tuple)) and _jsonable(value):
                # non-JSON containers (e.g. a tuning grid sweeping
                # estimator-valued params) are dropped from metadata rather
                # than crashing save(); learned state round-trips regardless
                out[name] = list(value)
        return out

    def __repr__(self):
        parts = ", ".join(
            f"{k}={v!r}"
            for k, v in self.get_params().items()
            if not isinstance(v, Params) and v is not None
        )
        return f"{type(self).__name__}({parts})"
